"""Ragged paged attention v2 (the "Ragged Paged Attention" TPU design,
PAPERS.md arxiv 2604.15464) + quantized KV-page support.

The PR-3 kernel (`flash_attention._paged_ragged_pallas`) dispatches a
(T, pages_per_seq) grid: every lane visits every page-table column, one
page per grid step, full masked compute at every step. Correct, but
first-cut — three structural costs the mature design removes:

  * PER-LANE DISPATCH: a lane resident for 1 page still burns
    pages_per_seq grid steps of full (H, page_size) softmax work; the
    masking throws the work away but the VPU/MXU already spent it.
  * ONE PAGE PER STEP: the DMA unit is a single page
    (page_size, H, D) — typically a few KB — so short blocks bound the
    kernel on DMA issue overhead, not bandwidth.
  * UNPACKED HEAD LAYOUT: blocks arrive as (page_size, H, D); for
    small head_dim (D < 128 lanes) the trailing dim wastes most of
    every VMEM tile ((8,128) f32 tiling).

This module rebuilds the kernel along the paper's lines:

  * ONE FLATTENED GRID over (lane, kv-block) work items: grid
    (T * num_kv_blocks,), item w -> lane w // nb, kv-block w % nb. A
    kv-block covers `block_kv_pages` pages — several page DMAs land per
    grid step (one BlockSpec per page slot, so Mosaic pipelines them),
    and the per-lane step count drops pages_per_seq / block_kv_pages x.
  * RAGGED SKIPPING: a work item whose kv-block starts past its lane's
    visible length is DEAD — `pl.when` skips its entire accumulation
    (v1 computed and masked it), and its page index maps clamp to the
    lane's last live block so no new DMA is issued for dead tail items.
  * HEAD PACKING for small head_dim: page blocks stream as
    (page_size, H*D) rows — the layout is already contiguous in HBM, so
    this is a free reshape that fills 128-lane VMEM tiles where
    (page_size, H, D) tiling padded D up to 128 — and are unpacked to
    (page_size, H, D) in-register for the (bit-identical) per-head dots.
  * TUNABLE KV-BLOCK SHAPES: `block_kv` (tokens per work item; FFConfig
    serve_attn_block_kv / --serve-attn-block-kv) with an
    autotune-by-shape table supplying defaults — sized so each step's
    K+V DMA traffic amortizes issue overhead without exceeding a VMEM
    budget. Measured entries can be registered (tools/flash_sweep.py
    style) and override the analytic pick.
  * QUANTIZED KV PAGES: int8 K/V pages ride with per-page scale arrays
    (one f32 scale per head per in-page slot — see serve/kv_cache.py
    for why scales are per-slot, not per-whole-page); the kernel DMAs
    the int8 block + its scale rows and dequantizes in-register before
    the (otherwise unchanged) online-softmax accumulation. bf16 pages
    need no scales (values upcast exactly like v1's bf16 handling).

Numerics contract: the jnp fallback is BIT-IDENTICAL to v1's
(`flash_attention._paged_decode_jnp`) on fp32 — same gather, same
dot_general dims, same single-pass softmax — so every existing
bit-equality oracle (full-prefill per lane, one-lane == decode) holds
verbatim under v2. The Pallas kernel reuses v1's exact per-page
accumulation ops (`_paged_online_page` math), so it agrees with the jnp
path to the same f32 tolerance v1 did; for int8 pages both paths
dequantize identically, so quantized jnp-vs-Pallas agreement is
unchanged while the QUANTIZATION error itself is gated by the
bounded-error + greedy-parity tests (tests/test_kv_quant.py).
"""

from __future__ import annotations

import functools
import math
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend is absent on pure-CPU builds
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    _HAS_PLTPU = False


# --------------------------------------------------------- quantization
INT8_QMAX = 127.0


def _qmax_for(dtype) -> float:
    """Largest representable magnitude of a page storage format: 127
    for int8, finfo.max (448) for float8_e4m3fn. Rows scale their amax
    to this value so the full dynamic range of the format is used."""
    dtype = jnp.dtype(dtype)
    if dtype == jnp.dtype(jnp.int8):
        return INT8_QMAX
    return float(jnp.finfo(dtype).max)


def quantize_kv_rows(x, dtype=jnp.int8):
    """Per-row symmetric quantization of K/V vectors into a narrow
    page storage format (int8 or float8_e4m3fn — the fp8 path reuses
    this machinery verbatim, scales and all).

    x (..., D) float -> (q (..., D) `dtype`, scales (...) f32) with
    q = round(x / scale), scale = amax(|x|, -1) / qmax (127 for int8,
    448 for e4m3). An all-zero row gets scale 0 and q 0 (dequant
    reproduces the zeros exactly) — the sink-page / padding-lane case.
    Each row quantizes independently of every other token, which is
    what makes the serving path's quantized content invariant to chunk
    boundaries, preemption replays, and speculative rollbacks
    (serve/engine.py). fp8 rows round at the dtype cast (the scaled
    values are <= the format's max finite by construction, so the
    saturating e4m3fn cast never produces NaN)."""
    dtype = jnp.dtype(dtype)
    qmax = _qmax_for(dtype)
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = amax / qmax
    # rows with scale 0 are all-zero: divide by 1 instead and the
    # zeros quantize to 0 regardless
    safe = jnp.where(scale > 0, scale, 1.0)
    y = xf / safe[..., None]
    if dtype == jnp.dtype(jnp.int8):
        q = jnp.clip(jnp.rint(y), -INT8_QMAX, INT8_QMAX)
    else:
        q = y  # the cast below rounds to the format's grid
    return q.astype(dtype), scale


def dequantize_kv(q, scale):
    """Inverse of quantize_kv_rows: q (..., D) int8 * scale (...) f32
    broadcast over D. Exactly the in-register dequant the kernel runs."""
    return q.astype(jnp.float32) * scale[..., None].astype(jnp.float32)


# --------------------------------------------- kv-block shape autotuning
# Analytic targets for choose_block_kv: each work item should move at
# least DMA_TARGET_BYTES of K+V so the per-step DMA issue cost is
# amortized, while the resident K/V (+ scale) blocks stay under
# VMEM_BUDGET_BYTES (Pallas double-buffers them, hence the /2).
DMA_TARGET_BYTES = 32 * 1024
VMEM_BUDGET_BYTES = 512 * 1024

# (page_size, num_heads, head_dim, kv_itemsize, pages_per_seq) ->
# block_kv tokens. Seeded analytically on first use; measured sweeps
# (register_block_kv) override — the "autotune-by-shape table".
_BLOCK_KV_TABLE: Dict[Tuple[int, int, int, int, int], int] = {}


def register_block_kv(page_size: int, num_heads: int, head_dim: int,
                      kv_itemsize: int, pages_per_seq: int,
                      block_kv: int) -> None:
    """Pin a measured kv-block shape for a geometry (overrides the
    analytic default for every later choose_block_kv on that shape)."""
    _BLOCK_KV_TABLE[(page_size, num_heads, head_dim, kv_itemsize,
                     pages_per_seq)] = int(block_kv)


def choose_block_kv(page_size: int, pages_per_seq: int, num_heads: int,
                    head_dim: int, kv_itemsize: int = 4) -> int:
    """KV tokens per work item for a pool geometry: the autotune table
    entry if one is registered, else the analytic pick — the smallest
    whole-page multiple whose K+V DMA reaches DMA_TARGET_BYTES, capped
    by the VMEM budget and the table width. Always a multiple of
    page_size and >= one page."""
    key = (page_size, num_heads, head_dim, kv_itemsize, pages_per_seq)
    got = _BLOCK_KV_TABLE.get(key)
    if got is not None:
        return got
    per_tok = 2 * num_heads * head_dim * kv_itemsize  # K + V
    if kv_itemsize == 1:  # quantized (int8/fp8) pages also stream
        per_tok += 2 * num_heads * 4  # their f32 scale rows
    want = max(1, -(-DMA_TARGET_BYTES // (per_tok * page_size)))
    cap = max(1, (VMEM_BUDGET_BYTES // 2) // (per_tok * page_size))
    ppb = min(max(1, want), cap, pages_per_seq)
    block = ppb * page_size
    _BLOCK_KV_TABLE[key] = block
    return block


def ragged_dispatch_passes(num_lanes: int, pages_per_seq: int,
                           block_kv_pages: int) -> Dict[str, int]:
    """Grid-step accounting for the serve bench: the v1 kernel runs one
    grid step per (lane, page); v2 runs one per (lane, kv-block)."""
    nb = -(-pages_per_seq // max(1, block_kv_pages))
    return {"v1": num_lanes * pages_per_seq, "v2": num_lanes * nb}


# ------------------------------------------------------------ jnp paths
def _ragged_jnp(q, k_pages, v_pages, page_tables, lane_slots, lane_lens,
                scale, k_scales=None, v_scales=None):
    """Vectorized fallback over the flattened ragged layout.

    Gathers each lane's pages (int8 gathers move 1/4 the bytes of f32),
    dequantizes, and runs EXACTLY v1's math — same dot_general dims,
    same masked single-pass softmax, same divide-after-matmul — so fp32
    outputs are bit-identical to `flash_attention._paged_decode_jnp`
    (the oracle every serve parity test is built on)."""
    b, h, d = q.shape
    ps = k_pages.shape[1]
    lane_tables = jnp.take(page_tables, lane_slots, axis=0)  # (T, pp)
    pp = lane_tables.shape[1]
    k = jnp.take(k_pages, lane_tables, axis=0)  # (T, pp, ps, H, D)
    v = jnp.take(v_pages, lane_tables, axis=0)
    if k_scales is not None:
        ks = jnp.take(k_scales, lane_tables, axis=0)  # (T, pp, ps, H)
        vs = jnp.take(v_scales, lane_tables, axis=0)
        k = dequantize_kv(k, ks)
        v = dequantize_kv(v, vs)
    k = k.reshape(b, pp * ps, h, d)
    v = v.reshape(b, pp * ps, h, d)
    s = jax.lax.dot_general(
        q, k, (((2,), (3,)), ((0, 1), (0, 2))),
        preferred_element_type=jnp.float32) * scale     # (T, H, pp*ps)
    pos = jax.lax.broadcasted_iota(jnp.int32, (b, 1, pp * ps), 2)
    s = jnp.where(pos < lane_lens[:, None, None], s, -jnp.inf)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jax.lax.dot_general(
        p, v.astype(jnp.float32), (((2,), (1,)), ((0, 1), (0, 2))),
        preferred_element_type=jnp.float32)
    return (o / l).astype(q.dtype)


# --------------------------------------------------------- Pallas kernel
def _online_block(q, k, v, length, kv_base, m_ref, l_ref, acc_ref, *,
                  scale):
    """One kv-block of one lane's online-softmax accumulation — v1's
    `_paged_online_page` ops verbatim (dot dims, f32 stats, p-stays-f32
    v-upcasts convention) over a (bs, H, D) block instead of a single
    page, so the f32 agreement with the jnp path carries over."""
    h = q.shape[0]
    bs = k.shape[0]
    s = jax.lax.dot_general(
        q, k, (((1,), (2,)), ((0,), (1,))),
        preferred_element_type=jnp.float32) * scale     # (H, bs)
    pos = kv_base + jax.lax.broadcasted_iota(jnp.int32, (h, bs), 1)
    s = jnp.where(pos < length, s, -jnp.inf)
    m_prev = m_ref[:]
    l_prev = l_ref[:]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    p = jnp.exp(s - m_new)
    alpha = jnp.exp(m_prev - m_new)
    m_ref[:] = m_new
    l_ref[:] = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
    pv = jax.lax.dot_general(
        p, v.astype(jnp.float32), (((1,), (0,)), ((0,), (1,))),
        preferred_element_type=jnp.float32)
    acc_ref[:] = acc_ref[:] * alpha + pv


def _ragged_v2_kernel(pt_ref, ls_ref, ll_ref, *refs, page_size,
                      pages_per_seq, num_blocks, block_pages, scale,
                      quantized):
    """Flattened-grid kernel body. Grid (T * num_blocks,); work item
    w covers kv positions [blk * block_pages * ps, ...) of lane
    w // num_blocks. Page refs arrive head-PACKED as (1, ps, H*D)
    blocks (plus (1, ps, H) scale blocks when quantized) and are
    unpacked in-register; dead items (block start past the lane's
    visible length) skip their whole accumulation."""
    n_in = 2 * block_pages * (2 if quantized else 1) + 1
    q_ref = refs[0]
    kv_refs = refs[1:n_in]
    o_ref = refs[n_in]
    m_ref, l_ref, acc_ref = refs[n_in + 1:]

    w = pl.program_id(0)
    t = w // num_blocks
    blk = w % num_blocks
    length = ll_ref[t]

    @pl.when(blk == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    h, d = q_ref.shape[1], q_ref.shape[2]
    base = blk * block_pages * page_size

    # dead item: this block starts at or past the lane's visible
    # length (lane_lens >= 1, so block 0 is always live) — skip the
    # entire accumulation. v1 computed the full masked block here.
    @pl.when(base < length)
    def _accumulate():
        q = q_ref[0]                     # (H, D)
        for i in range(block_pages):
            if quantized:
                kq = kv_refs[4 * i + 0][0]       # (ps, H*D) int8
                ks = kv_refs[4 * i + 1][0]       # (ps, H) f32
                vq = kv_refs[4 * i + 2][0]
                vs = kv_refs[4 * i + 3][0]
                k = dequantize_kv(kq.reshape(page_size, h, d), ks)
                v = dequantize_kv(vq.reshape(page_size, h, d), vs)
            else:
                k = kv_refs[2 * i + 0][0].reshape(page_size, h, d)
                v = kv_refs[2 * i + 1][0].reshape(page_size, h, d)
            _online_block(q, k, v, length, base + i * page_size,
                          m_ref, l_ref, acc_ref, scale=scale)

    @pl.when(blk == num_blocks - 1)
    def _emit():
        o_ref[0] = (acc_ref[:] / l_ref[:]).astype(o_ref.dtype)


def _ragged_v2_pallas(q, k_pages, v_pages, page_tables, lane_slots,
                      lane_lens, scale, block_kv_pages, interpret,
                      k_scales=None, v_scales=None):
    if not _HAS_PLTPU:
        raise NotImplementedError("pallas TPU backend unavailable")
    t, h, d = q.shape
    npages, ps = k_pages.shape[0], k_pages.shape[1]
    pp = page_tables.shape[1]
    bp = max(1, min(int(block_kv_pages), pp))
    nb = -(-pp // bp)
    quantized = k_scales is not None

    # head packing: page rows stream as (ps, H*D) — contiguous in HBM,
    # so the reshape is free — and unpack in-register in the kernel
    kp = k_pages.reshape(npages, ps, h * d)
    vp = v_pages.reshape(npages, ps, h * d)

    def page_index(i):
        """Index map for page slot i of each work item: the physical
        page at table column blk*bp + i of the item's lane, CLAMPED to
        the lane's last live column — dead tail items re-select a page
        already resident, so they issue no new DMA (their compute is
        pl.when-skipped anyway)."""
        def imap(w, pt, ls, ll):
            tt = w // nb
            col = (w % nb) * bp + i
            # clamp into both the table and the lane's live range so
            # dead items never demand a fresh (sink) page DMA
            live_last = jnp.maximum((ll[tt] - 1) // ps, 0)
            col = jnp.minimum(jnp.minimum(col, pp - 1), live_last)
            return (pt[ls[tt], col], 0, 0)
        return imap

    def q_index(w, pt, ls, ll):
        return (w // nb, 0, 0)

    in_specs = [pl.BlockSpec((1, h, d), q_index)]
    args = [q]
    for i in range(bp):
        imap = page_index(i)
        in_specs.append(pl.BlockSpec((1, ps, h * d), imap))
        args.append(kp)
        if quantized:
            in_specs.append(pl.BlockSpec((1, ps, h), imap))
            args.append(k_scales)
        in_specs.append(pl.BlockSpec((1, ps, h * d), imap))
        args.append(vp)
        if quantized:
            in_specs.append(pl.BlockSpec((1, ps, h), imap))
            args.append(v_scales)
    kern = functools.partial(
        _ragged_v2_kernel, page_size=ps, pages_per_seq=pp,
        num_blocks=nb, block_pages=bp, scale=scale, quantized=quantized)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,  # page_tables, lane_slots, lane_lens
        grid=(t * nb,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, h, d), q_index),
        scratch_shapes=[
            pltpu.VMEM((h, 1), jnp.float32),   # running max
            pltpu.VMEM((h, 1), jnp.float32),   # running sum
            pltpu.VMEM((h, d), jnp.float32),   # output accumulator
        ],
    )
    return pl.pallas_call(
        kern, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((t, h, d), q.dtype),
        interpret=interpret,
    )(page_tables, lane_slots, lane_lens, *args)


# ------------------------------------------------------------ entry point
def paged_attention_ragged_v2(q, k_pages, v_pages, page_tables,
                              lane_slots, lane_lens, *, k_scales=None,
                              v_scales=None, scale=None, block_kv=None,
                              use_pallas=None, interpret=False):
    """Ragged batched attention through page tables — kernel v2.

    Same contract as flash_attention.paged_attention_ragged (q (T,H,D),
    one query token per lane; page 0 = sink; every lane_lens >= 1) plus:

      k_scales/v_scales — (num_pages, page_size, H) f32 per-page scale
        arrays for int8 K/V pages (None = unquantized pages; the two
        must be both present or both absent).
      block_kv — KV tokens per flattened work item (None = the
        autotune-by-shape table via choose_block_kv; rounded to whole
        pages).

    fp32 outputs are bit-identical to v1 on the jnp path (same math);
    Pallas-vs-jnp agreement is the same f32 tolerance as v1.
    """
    if (k_scales is None) != (v_scales is None):
        raise ValueError("k_scales and v_scales must be given together")
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if use_pallas is None:
        use_pallas = (interpret or (_HAS_PLTPU
                                    and jax.default_backend() == "tpu"))
    if use_pallas:
        ps = k_pages.shape[1]
        if block_kv is None:
            block_kv = choose_block_kv(
                ps, page_tables.shape[1], q.shape[1], q.shape[2],
                jnp.dtype(k_pages.dtype).itemsize)
        return _ragged_v2_pallas(
            q, k_pages, v_pages, page_tables, lane_slots, lane_lens,
            scale, max(1, int(block_kv) // ps), interpret,
            k_scales=k_scales, v_scales=v_scales)
    return _ragged_jnp(q, k_pages, v_pages, page_tables, lane_slots,
                       lane_lens, scale, k_scales=k_scales,
                       v_scales=v_scales)
