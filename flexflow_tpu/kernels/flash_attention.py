"""Flash attention (Pallas, TPU).

Replaces the reference's single cuDNN fused-MHA call
(src/ops/attention.cu:245 cudnnMultiHeadAttnForward) with an online-softmax
blocked kernel that never materializes the (Lq, Lk) score matrix in HBM.

Forward is a Pallas kernel (grid over (batch*heads, q-blocks), inner
fori_loop over k-blocks with online max/sum rescaling). Backward is two
Pallas kernels (dq over q-blocks; dk/dv over k-blocks) that recompute
probabilities from the saved logsumexp — exact gradients with no saved or
materialized probability tensor.

All MXU dots run in the input dtype (bf16 on TPU) with float32
accumulation (`preferred_element_type`); softmax statistics stay float32.
Casting to f32 *before* the dot would push the matmuls off the MXU's
native bf16 path and cost ~4x.

Layout contract: (batch, seq, heads, head_dim) in/out, matching
ops/attention.py. head_dim is zero-padded to a multiple of 128 lanes
(padding is exact: zero d-columns contribute nothing to q.k^T, and padded
v columns are sliced off the output).

Set `interpret=True` to run the same kernels through the Pallas
interpreter on CPU — used by tests/test_flash_attention.py on the forced
CPU platform.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend is absent on pure-CPU builds
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401
    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    _HAS_PLTPU = False

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128


def _dot_t(a, b):
    """a (m, d) . b^T (d, n) -> (m, n), contracting the last dims."""
    return jax.lax.dot_general(a, b, (((1,), (1,)), ((), ())),
                               preferred_element_type=jnp.float32)


def _dot_tt(a, b):
    """a^T (k, m) . b (k, n) -> (m, n), contracting the first dims."""
    return jax.lax.dot_general(a, b, (((0,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)


def _causal_mask(s, q0, k0, block_q, block_k):
    """Mask scores s (block_q, block_k) where q0+i < k0+j (top-left aligned)."""
    qpos = q0 + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    kpos = k0 + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    return jnp.where(qpos >= kpos, s, -jnp.inf)


# ---------------------------------------------------------------- forward
def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                      block_q, block_k, seq_k, scale, causal):
    qi = pl.program_id(1)
    q = q_ref[:]  # (block_q, d), native dtype — bf16 dots ride the MXU
    d = q.shape[-1]
    m0 = jnp.full((block_q,), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)

    num_kb = seq_k // block_k
    if causal:
        # blocks strictly above the diagonal contribute nothing
        num_kb = jnp.minimum(num_kb,
                             ((qi + 1) * block_q + block_k - 1) // block_k)

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[pl.ds(j * block_k, block_k), :]
        v = v_ref[pl.ds(j * block_k, block_k), :]
        s = _dot_t(q, k) * scale  # f32 accumulate
        if causal:
            s = _causal_mask(s, qi * block_q, j * block_k, block_q, block_k)
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=1)
        acc_new = acc * alpha[:, None] + jax.lax.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, num_kb, body, (m0, l0, acc0))
    o_ref[:] = (acc / l[:, None]).astype(o_ref.dtype)
    lse_ref[:] = (m + jnp.log(l))[:, None]


def _fwd_pallas(q, k, v, *, causal, scale, block_q, block_k, interpret):
    """q,k,v: (bh, s, d_padded) -> o (bh, sq, d_padded), lse (bh, sq, 1)."""
    bh, sq, d = q.shape
    sk = k.shape[1]
    kern = functools.partial(
        _flash_fwd_kernel, block_q=block_q, block_k=block_k, seq_k=sk,
        scale=scale, causal=causal)
    grid = (bh, sq // block_q)
    return pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, sk, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, sk, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)


# --------------------------------------------------------------- backward
def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, *, block_q, block_k, seq_k, scale, causal):
    qi = pl.program_id(1)
    q = q_ref[:]          # (block_q, d)
    do = do_ref[:]        # (block_q, d)
    lse = lse_ref[:]      # (block_q, 1) f32
    delta = delta_ref[:]  # (block_q, 1) f32
    d = q.shape[-1]
    acc0 = jnp.zeros((block_q, d), jnp.float32)

    num_kb = seq_k // block_k
    if causal:
        num_kb = jnp.minimum(num_kb,
                             ((qi + 1) * block_q + block_k - 1) // block_k)

    def body(j, acc):
        k = k_ref[pl.ds(j * block_k, block_k), :]
        v = v_ref[pl.ds(j * block_k, block_k), :]
        s = _dot_t(q, k) * scale
        if causal:
            s = _causal_mask(s, qi * block_q, j * block_k, block_q, block_k)
        p = jnp.exp(s - lse)         # masked -inf rows exp to exactly 0
        dp = _dot_t(do, v)           # (block_q, block_k) f32
        ds = (p * (dp - delta) * scale).astype(k.dtype)
        return acc + jax.lax.dot(ds, k, preferred_element_type=jnp.float32)

    acc = jax.lax.fori_loop(0, num_kb, body, acc0)
    dq_ref[:] = acc.astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, *, block_q, block_k, seq_q, scale,
                          causal):
    kj = pl.program_id(1)
    k = k_ref[:]  # (block_k, d)
    v = v_ref[:]
    d = k.shape[-1]
    dk0 = jnp.zeros((block_k, d), jnp.float32)
    dv0 = jnp.zeros((block_k, d), jnp.float32)

    num_qb = seq_q // block_q
    start_qb = 0
    if causal:
        # q blocks strictly left of this k block see none of it
        start_qb = (kj * block_k) // block_q

    def body(i, carry):
        dk, dv = carry
        q = q_ref[pl.ds(i * block_q, block_q), :]
        do = do_ref[pl.ds(i * block_q, block_q), :]
        lse = lse_ref[pl.ds(i * block_q, block_q), :]
        delta = delta_ref[pl.ds(i * block_q, block_q), :]
        s = _dot_t(q, k) * scale
        if causal:
            s = _causal_mask(s, i * block_q, kj * block_k, block_q, block_k)
        p = jnp.exp(s - lse)
        dv = dv + _dot_tt(p.astype(do.dtype), do)
        dp = _dot_t(do, v)
        ds = (p * (dp - delta) * scale).astype(q.dtype)
        dk = dk + _dot_tt(ds, q)
        return dk, dv

    dk, dv = jax.lax.fori_loop(start_qb, num_qb, body, (dk0, dv0))
    dk_ref[:] = dk.astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)


def _bwd_pallas(q, k, v, o, lse, do, *, causal, scale, block_q, block_k,
                interpret):
    bh, sq, d = q.shape
    sk = k.shape[1]
    # delta_i = rowsum(do * o): cheap elementwise, fused by XLA
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)  # (bh, sq, 1)

    blk_q = lambda b, i: (b, i, 0)  # noqa: E731
    full = lambda b, i: (b, 0, 0)  # noqa: E731

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, block_q=block_q,
                          block_k=block_k, seq_k=sk, scale=scale,
                          causal=causal),
        grid=(bh, sq // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), blk_q),
            pl.BlockSpec((None, sk, d), full),
            pl.BlockSpec((None, sk, d), full),
            pl.BlockSpec((None, block_q, d), blk_q),
            pl.BlockSpec((None, block_q, 1), blk_q),
            pl.BlockSpec((None, block_q, 1), blk_q),
        ],
        out_specs=pl.BlockSpec((None, block_q, d), blk_q),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    blk_k = lambda b, j: (b, j, 0)  # noqa: E731
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, block_q=block_q,
                          block_k=block_k, seq_q=sq, scale=scale,
                          causal=causal),
        grid=(bh, sk // block_k),
        in_specs=[
            pl.BlockSpec((None, sq, d), full),
            pl.BlockSpec((None, block_k, d), blk_k),
            pl.BlockSpec((None, block_k, d), blk_k),
            pl.BlockSpec((None, sq, d), full),
            pl.BlockSpec((None, sq, 1), full),
            pl.BlockSpec((None, sq, 1), full),
        ],
        out_specs=[
            pl.BlockSpec((None, block_k, d), blk_k),
            pl.BlockSpec((None, block_k, d), blk_k),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------- custom VJP
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    o, _ = _fwd_pallas(q, k, v, causal=causal, scale=scale,
                       block_q=block_q, block_k=block_k, interpret=interpret)
    return o


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    o, lse = _fwd_pallas(q, k, v, causal=causal, scale=scale,
                         block_q=block_q, block_k=block_k,
                         interpret=interpret)
    return o, (q, k, v, o, lse)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, do):
    q, k, v, o, lse = res
    dq, dk, dv = _bwd_pallas(q, k, v, o, lse, do, causal=causal, scale=scale,
                             block_q=block_q, block_k=block_k,
                             interpret=interpret)
    return dq, dk, dv


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_profitable(b: int, h: int, sq: int, sk: int, d: int) -> bool:
    """The measured auto-dispatch gate, shared by every flash call site
    (unsharded ops/attention.py and the all-to-all SP lowering,
    parallel/ulysses.py) so a re-tune propagates everywhere. Constants
    from the v5e b8/h8 2026-07 sweep (tests_tpu/test_flash_tpu.py): at
    d=64 the 128-lane padding doubles the kernel's dot FLOPs and XLA
    ties or wins; at d=128 flash wins from s>=1024; at any d flash wins
    once the materialized (b,h,sq,sk) score tensor stresses HBM."""
    score_bytes = b * h * sq * sk * 6  # f32 logits + bf16 probs
    return (d % 128 == 0 and sk >= 1024) or score_bytes > 2**31


# ------------------------------------------------- paged attention (serve)
#
# The serving path (flexflow_tpu/serve): query tokens attend to their
# sequence's K/V history, which lives in fixed-size PAGES addressed
# through a per-sequence page table (serve/kv_cache.py — the "Ragged
# Paged Attention" layout, PAPERS.md). Two entry points over the same
# math:
#
#   * paged_attention_decode — ONE query token per sequence (the
#     classic decode step): rows of the page table are sequences.
#   * paged_attention_ragged — one query token per LANE, where a lane
#     is any (sequence, position) pair: a chunked-prefill step packs
#     prompt chunks from several sequences plus every running decode
#     token into one call. Lanes pick their sequence's page-table row
#     through a slot index and mask at their own position+1, so a
#     prefill token at position p sees exactly keys 0..p even though
#     later chunk tokens' K/V are already scattered into the pages.
#
# Each has two implementations with identical semantics:
#
#   * _paged_decode_jnp — gather pages with jnp.take, masked online-free
#     softmax in f32. XLA lowers the gather to dynamic-gather; for
#     single-query lanes the op is HBM-bound either way, so this is
#     also a credible TPU path, and it is the reference the Pallas
#     kernels are tested against bit-for-bit on CPU.
#   * _paged_decode_pallas / _paged_ragged_pallas — scalar-prefetch
#     kernels: the page table (and, for ragged, the lane->slot map and
#     lane lengths) rides in SMEM ahead of the grid so each
#     (lane, page) grid step DMAs exactly one K and one V page picked
#     by table[slot[lane], page]; online max/sum rescaling accumulates
#     across a lane's pages in VMEM scratch, and the output is written
#     on the lane's last grid step. Never materializes the gathered
#     (B, max_len, H, D) K/V that the jnp path pays for.
#
# Both dispatch: Pallas on TPU (or interpret=True), jnp elsewhere — the
# CPU-fallback story for the whole serve package.


def _paged_decode_jnp(q, k_pages, v_pages, page_table, seq_lens, scale):
    """q (B,H,D); k/v_pages (P, ps, H, D); page_table (B, pp) int32;
    seq_lens (B,) int32 -> (B, H, D).

    Padding page-table entries point at the sink page 0; every position
    >= seq_len is masked to -inf before the softmax, so sink contents
    are never observed. All statistics in f32."""
    b, h, d = q.shape
    ps = k_pages.shape[1]
    pp = page_table.shape[1]
    k = jnp.take(k_pages, page_table, axis=0)  # (B, pp, ps, H, D)
    v = jnp.take(v_pages, page_table, axis=0)
    k = k.reshape(b, pp * ps, h, d)
    v = v.reshape(b, pp * ps, h, d)
    # batch over (seq, head): s[b,h,t] = q[b,h,:] . k[b,t,h,:]
    s = jax.lax.dot_general(
        q, k, (((2,), (3,)), ((0, 1), (0, 2))),
        preferred_element_type=jnp.float32) * scale     # (B, H, pp*ps)
    pos = jax.lax.broadcasted_iota(jnp.int32, (b, 1, pp * ps), 2)
    s = jnp.where(pos < seq_lens[:, None, None], s, -jnp.inf)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)                                  # (B, H, pp*ps) f32
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jax.lax.dot_general(                            # (B, H, D)
        p, v.astype(jnp.float32), (((2,), (1,)), ((0, 1), (0, 2))),
        preferred_element_type=jnp.float32)
    return (o / l).astype(q.dtype)


def _paged_online_page(q, k, v, length, j, m_ref, l_ref, acc_ref, *,
                       page_size, scale):
    """One page of one lane's online-softmax accumulation — the body
    shared by the decode and ragged kernels (they differ only in how
    the lane's length and page-table row are selected)."""
    h, _ = q.shape
    # scores for this page: (H, ps), f32 accumulate on the MXU
    s = jax.lax.dot_general(
        q, k, (((1,), (2,)), ((0,), (1,))),
        preferred_element_type=jnp.float32) * scale
    # mask positions past the lane's visible length (padding pages are
    # the sink page; their scores die here)
    pos = j * page_size + jax.lax.broadcasted_iota(jnp.int32, (h, page_size),
                                                   1)
    s = jnp.where(pos < length, s, -jnp.inf)

    m_prev = m_ref[:]               # (H, 1)
    l_prev = l_ref[:]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    p = jnp.exp(s - m_new)          # (H, ps); fully-masked rows -> 0
    alpha = jnp.exp(m_prev - m_new)
    m_ref[:] = m_new
    l_ref[:] = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
    # p stays f32 and v upcasts, matching _paged_decode_jnp exactly —
    # the implementations must not diverge for bf16 KV pages
    pv = jax.lax.dot_general(       # (H, D): p (H,ps) . v (ps,H,D) per-head
        p, v.astype(jnp.float32), (((1,), (0,)), ((0,), (1,))),
        preferred_element_type=jnp.float32)
    acc_ref[:] = acc_ref[:] * alpha + pv


def _paged_decode_kernel(pt_ref, sl_ref, q_ref, k_ref, v_ref, o_ref,
                         m_ref, l_ref, acc_ref, *, page_size, pages_per_seq,
                         scale):
    """Grid (B, pages_per_seq); k_ref/v_ref hold THE page selected by
    the scalar-prefetched table for this (seq, page) step."""
    b = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    _paged_online_page(q_ref[0], k_ref[0], v_ref[0], sl_ref[b], j,
                       m_ref, l_ref, acc_ref, page_size=page_size,
                       scale=scale)

    @pl.when(j == pages_per_seq - 1)
    def _emit():
        o_ref[0] = (acc_ref[:] / l_ref[:]).astype(o_ref.dtype)


def _paged_ragged_kernel(pt_ref, ls_ref, ll_ref, q_ref, k_ref, v_ref,
                         o_ref, m_ref, l_ref, acc_ref, *, page_size,
                         pages_per_seq, scale):
    """Grid (T, pages_per_seq) over LANES: lane t's pages come from row
    ls_ref[t] of the table (several lanes of one sequence share a row)
    and its causal visibility is its own ll_ref[t] = position + 1."""
    t = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    _paged_online_page(q_ref[0], k_ref[0], v_ref[0], ll_ref[t], j,
                       m_ref, l_ref, acc_ref, page_size=page_size,
                       scale=scale)

    @pl.when(j == pages_per_seq - 1)
    def _emit():
        o_ref[0] = (acc_ref[:] / l_ref[:]).astype(o_ref.dtype)


def _paged_decode_pallas(q, k_pages, v_pages, page_table, seq_lens, scale,
                         interpret):
    if not _HAS_PLTPU:
        raise NotImplementedError("pallas TPU backend unavailable")
    b, h, d = q.shape
    ps = k_pages.shape[1]
    pp = page_table.shape[1]
    kern = functools.partial(_paged_decode_kernel, page_size=ps,
                             pages_per_seq=pp, scale=scale)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # page_table, seq_lens
        grid=(b, pp),
        in_specs=[
            pl.BlockSpec((1, h, d), lambda b, j, pt, sl: (b, 0, 0)),
            pl.BlockSpec((1, ps, h, d),
                         lambda b, j, pt, sl: (pt[b, j], 0, 0, 0)),
            pl.BlockSpec((1, ps, h, d),
                         lambda b, j, pt, sl: (pt[b, j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, h, d), lambda b, j, pt, sl: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, 1), jnp.float32),   # running max
            pltpu.VMEM((h, 1), jnp.float32),   # running sum
            pltpu.VMEM((h, d), jnp.float32),   # output accumulator
        ],
    )
    return pl.pallas_call(
        kern, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, d), q.dtype),
        interpret=interpret,
    )(page_table, seq_lens, q, k_pages, v_pages)


def paged_attention_decode(q, k_pages, v_pages, page_table, seq_lens, *,
                           scale=None, use_pallas=None, interpret=False):
    """Single-query attention through a page table (decode step).

    q (B, H, D) — one query token per sequence; k_pages/v_pages
    (num_pages, page_size, H, D); page_table (B, pages_per_seq) int32
    physical page ids (0 = sink/padding); seq_lens (B,) int32 tokens
    resident per sequence (positions >= seq_len are masked). Every
    seq_lens entry must be >= 1: a zero-length lane has every score
    masked to -inf, which NaNs the softmax in both implementations —
    callers with empty lanes must clamp them to 1 and aim their page
    table at the sink (serve/engine.py does exactly this). Returns
    (B, H, D).

    use_pallas: None = auto (Pallas kernel on TPU, jnp gather path
    elsewhere — the CPU fallback that makes the whole serve package run
    under JAX_PLATFORMS=cpu), True = force (combine with interpret=True
    off TPU), False = always jnp (wins over interpret).
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if use_pallas is None:
        use_pallas = (interpret or (_HAS_PLTPU
                                    and jax.default_backend() == "tpu"))
    if use_pallas:
        return _paged_decode_pallas(q, k_pages, v_pages, page_table,
                                    seq_lens, scale, interpret)
    return _paged_decode_jnp(q, k_pages, v_pages, page_table, seq_lens,
                             scale)


def _paged_ragged_pallas(q, k_pages, v_pages, page_tables, lane_slots,
                         lane_lens, scale, interpret):
    if not _HAS_PLTPU:
        raise NotImplementedError("pallas TPU backend unavailable")
    t, h, d = q.shape
    ps = k_pages.shape[1]
    pp = page_tables.shape[1]
    kern = functools.partial(_paged_ragged_kernel, page_size=ps,
                             pages_per_seq=pp, scale=scale)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,  # page_tables, lane_slots, lane_lens
        grid=(t, pp),
        in_specs=[
            pl.BlockSpec((1, h, d), lambda t, j, pt, ls, ll: (t, 0, 0)),
            pl.BlockSpec((1, ps, h, d),
                         lambda t, j, pt, ls, ll: (pt[ls[t], j], 0, 0, 0)),
            pl.BlockSpec((1, ps, h, d),
                         lambda t, j, pt, ls, ll: (pt[ls[t], j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, h, d), lambda t, j, pt, ls, ll: (t, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, 1), jnp.float32),   # running max
            pltpu.VMEM((h, 1), jnp.float32),   # running sum
            pltpu.VMEM((h, d), jnp.float32),   # output accumulator
        ],
    )
    return pl.pallas_call(
        kern, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((t, h, d), q.dtype),
        interpret=interpret,
    )(page_tables, lane_slots, lane_lens, q, k_pages, v_pages)


def paged_attention_ragged_v1(q, k_pages, v_pages, page_tables,
                              lane_slots, lane_lens, *, scale=None,
                              use_pallas=None, interpret=False):
    """The PR-3 first-cut ragged kernel — grid (T, pages_per_seq), one
    page per grid step, full masked compute per step. Kept as the
    bit-equality oracle and A/B baseline for kernel v2
    (kernels/paged_ragged_v2.py); new code should call
    paged_attention_ragged, which dispatches v2."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if use_pallas is None:
        use_pallas = (interpret or (_HAS_PLTPU
                                    and jax.default_backend() == "tpu"))
    if use_pallas:
        return _paged_ragged_pallas(q, k_pages, v_pages, page_tables,
                                    lane_slots, lane_lens, scale, interpret)
    lane_tables = jnp.take(page_tables, lane_slots, axis=0)  # (T, pp)
    return _paged_decode_jnp(q, k_pages, v_pages, lane_tables, lane_lens,
                             scale)


def paged_attention_ragged(q, k_pages, v_pages, page_tables, lane_slots,
                           lane_lens, *, scale=None, use_pallas=None,
                           interpret=False, k_scales=None, v_scales=None,
                           block_kv=None):
    """Ragged batched attention through page tables — the chunked
    prefill/mixed-step kernel (serve/engine.py), v2 since PR 8
    (kernels/paged_ragged_v2.py: one flattened (lane, kv-block) grid
    with ragged skipping, head packing, and tunable kv-block shapes,
    per the "Ragged Paged Attention" paper in PAPERS.md).

    q (T, H, D) — one query token per LANE, where lanes mix prompt-chunk
    tokens from any number of sequences with single decode tokens;
    k_pages/v_pages (num_pages, page_size, H, D); page_tables
    (max_seqs, pages_per_seq) int32 physical page ids (0 =
    sink/padding); lane_slots (T,) int32 selects each lane's page-table
    row (lanes of the same sequence share a row); lane_lens (T,) int32
    the lane's visible tokens — position + 1 for a prefill token at
    `position`, so causality inside a chunk is exact even though the
    whole chunk's K/V is scattered before attention runs. Every
    lane_lens entry must be >= 1 (see paged_attention_decode). Returns
    (T, H, D).

    Quantized KV pages: pass int8 k_pages/v_pages with their
    (num_pages, page_size, H) f32 k_scales/v_scales; the kernel (and
    the fallback) dequantizes at read (serve/kv_cache.py).
    block_kv tunes the kv-block shape (FFConfig.serve_attn_block_kv;
    None = autotune-by-shape table).

    The jnp fallback runs v1's math verbatim, so a 1-lane-per-sequence
    fp32 call is bit-for-bit `paged_attention_decode`, and the op order
    matches the contiguous full-prefill reference exactly (tested in
    tests/test_serve_v2.py; v2-vs-v1 equality in tests/test_kv_quant.py).
    use_pallas: None = auto (Pallas on TPU), True = force (combine with
    interpret=True off TPU), False = always jnp.
    """
    from .paged_ragged_v2 import paged_attention_ragged_v2
    return paged_attention_ragged_v2(
        q, k_pages, v_pages, page_tables, lane_slots, lane_lens,
        k_scales=k_scales, v_scales=v_scales, scale=scale,
        block_kv=block_kv, use_pallas=use_pallas, interpret=interpret)


def flash_attention_bshd(q, k, v, *, causal=False,
                         block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                         interpret=False, pad_lanes=True):
    """softmax(QK^T/sqrt(d))V for (b, s, h, d) tensors via Pallas.

    Raises on unsupported shapes/platform; callers fall back to XLA.

    pad_lanes=True zero-pads head_dim up to a 128-lane multiple (always
    safe). pad_lanes=False hands Mosaic the raw head_dim (still a
    multiple of 8): halves the kernel's HBM traffic and dot FLOPs for
    d=64, at the cost of relying on Mosaic's sub-128 lane handling.
    """
    if not interpret and (not _HAS_PLTPU or jax.default_backend() != "tpu"):
        raise NotImplementedError("pallas flash attention requires TPU")
    b, sq, h, d = q.shape
    sk = k.shape[1]
    if sq % block_q != 0 or sk % block_k != 0:
        raise NotImplementedError(f"seq ({sq},{sk}) not divisible by block")
    if d > 256:
        raise NotImplementedError("head_dim > 256 unsupported")

    # scale uses the unpadded head_dim
    scale = 1.0 / math.sqrt(d)
    if pad_lanes or d % 8 != 0:
        d_pad = max(128, ((d + 127) // 128) * 128)
    else:
        d_pad = d

    def to_bhd(x, s):
        x = jnp.swapaxes(x, 1, 2).reshape(b * h, s, d)
        if d_pad != d:
            x = jnp.pad(x, ((0, 0), (0, 0), (0, d_pad - d)))
        return x

    o = _flash(to_bhd(q, sq), to_bhd(k, sk), to_bhd(v, sk),
               causal, scale, block_q, block_k, interpret)
    o = o[..., :d].reshape(b, h, sq, d)
    return jnp.swapaxes(o, 1, 2)
