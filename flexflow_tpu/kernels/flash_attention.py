"""Flash attention (Pallas, TPU).

Replaces the reference's single cuDNN fused-MHA call
(src/ops/attention.cu:245 cudnnMultiHeadAttnForward) with an online-softmax
blocked kernel that never materializes the (Lq, Lk) score matrix in HBM.

Forward is a Pallas kernel (grid over (batch*heads, q-blocks), inner
fori_loop over k-blocks with online max/sum rescaling). Backward is a
custom VJP that recomputes probabilities from the saved logsumexp — exact
gradients with no saved probability tensor.

Layout contract: (batch, seq, heads, head_dim) in/out, matching
ops/attention.py. head_dim is zero-padded to a multiple of 128 lanes
(padding is exact: zero d-columns contribute nothing to q.k^T, and padded
v columns are sliced off the output).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend is absent on pure-CPU builds
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401
    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    _HAS_PLTPU = False

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                      block_q, block_k, seq_k, scale, causal):
    qi = pl.program_id(1)
    q = q_ref[:].astype(jnp.float32)  # (block_q, d)
    d = q.shape[-1]
    m0 = jnp.full((block_q,), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)

    num_kb = seq_k // block_k
    if causal:
        # blocks strictly above the diagonal contribute nothing
        num_kb = jnp.minimum(num_kb,
                             ((qi + 1) * block_q + block_k - 1) // block_k)

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            qpos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(qpos >= kpos, s, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=1)
        acc_new = acc * alpha[:, None] + jax.lax.dot(
            p, v, preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, num_kb, body, (m0, l0, acc0))
    o_ref[:] = (acc / l[:, None]).astype(o_ref.dtype)
    lse_ref[:] = (m + jnp.log(l))[:, None]


def _fwd_pallas(q, k, v, *, causal, scale, block_q, block_k):
    """q,k,v: (bh, s, d_padded) -> o (bh, sq, d_padded), lse (bh, sq, 1)."""
    bh, sq, d = q.shape
    sk = k.shape[1]
    kern = functools.partial(
        _flash_fwd_kernel, block_q=block_q, block_k=block_k, seq_k=sk,
        scale=scale, causal=causal)
    grid = (bh, sq // block_q)
    return pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, sk, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, sk, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq, 1), jnp.float32),
        ],
    )(q, k, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, scale, block_q, block_k):
    o, _ = _fwd_pallas(q, k, v, causal=causal, scale=scale,
                       block_q=block_q, block_k=block_k)
    return o


def _flash_fwd(q, k, v, causal, scale, block_q, block_k):
    o, lse = _fwd_pallas(q, k, v, causal=causal, scale=scale,
                         block_q=block_q, block_k=block_k)
    return o, (q, k, v, o, lse)


def _flash_bwd(causal, scale, block_q, block_k, res, do):
    q, k, v, o, lse = res
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    s = jnp.einsum("bqd,bkd->bqk", qf, kf) * scale
    if causal:
        # top-left alignment (j <= i), matching the forward kernel's
        # qpos >= kpos mask exactly — required for correct gradients
        # when seq_q != seq_k.
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool))
        s = jnp.where(mask, s, -jnp.inf)
    p = jnp.exp(s - lse)  # (bh, sq, sk); lse broadcasts over last dim
    dv = jnp.einsum("bqk,bqd->bkd", p, dof)
    dp = jnp.einsum("bqd,bkd->bqk", dof, vf)
    delta = jnp.sum(dof * o.astype(jnp.float32), axis=-1, keepdims=True)
    ds = p * (dp - delta) * scale
    dq = jnp.einsum("bqk,bkd->bqd", ds, kf)
    dk = jnp.einsum("bqk,bqd->bkd", ds, qf)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention_bshd(q, k, v, *, causal=False,
                         block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K):
    """softmax(QK^T/sqrt(d))V for (b, s, h, d) tensors via Pallas.

    Raises on unsupported shapes/platform; callers fall back to XLA.
    """
    if not _HAS_PLTPU or jax.default_backend() != "tpu":
        raise NotImplementedError("pallas flash attention requires TPU")
    b, sq, h, d = q.shape
    sk = k.shape[1]
    if sq % block_q != 0 or sk % block_k != 0:
        raise NotImplementedError(f"seq ({sq},{sk}) not divisible by block")
    if d > 256:
        raise NotImplementedError("head_dim > 256 unsupported")

    # scale uses the unpadded head_dim
    scale = 1.0 / math.sqrt(d)
    d_pad = max(128, ((d + 127) // 128) * 128)

    def to_bhd(x, s):
        x = jnp.swapaxes(x, 1, 2).reshape(b * h, s, d)
        if d_pad != d:
            x = jnp.pad(x, ((0, 0), (0, 0), (0, d_pad - d)))
        return x

    o = _flash(to_bhd(q, sq), to_bhd(k, sk), to_bhd(v, sk),
               causal, scale, block_q, block_k)
    o = o[..., :d].reshape(b, h, sq, d)
    return jnp.swapaxes(o, 1, 2)
