"""Multi-timestep LSTM recurrence (Pallas, TPU).

Replaces the `lax.scan` recurrence of ops/rnn.py (the analog of the
reference's cuDNN RNN, nmt/lstm.cu) for the sequence loop ONLY — the
time-batched input GEMM (x @ wx) stays outside in XLA where it already
saturates the MXU.

Why a kernel: under scan, XLA re-reads the recurrent weight `wh`
(H, 4H — 16 MB f32 at NMT's H=1024) from HBM every timestep, so the
recurrence is wh-bandwidth-bound: T=40 steps stream 640 MB for 21 GFLOP
of math. Here the grid iterates over time with `wh` mapped to a
CONSTANT block index — Mosaic keeps the block resident in VMEM across
grid steps (no recopy on unchanged index) — and the (B, H) h/c carry
lives in VMEM scratch, cutting HBM traffic per step to the xg slice in
and the y/c slices out.

Backward is a second time-reversed kernel that RECOMPUTES the gates
from the stashed per-step h/c states (flash-attention-style recompute:
one extra (B,H)x(H,4H) GEMM per step instead of stashing (T, B, 4H)
activations), accumulating dwh in an f32 VMEM scratch and carrying
dh/dc across steps. Gate layout matches ops/rnn.py: [i, f, g, o].

Layout contract: xg (T, B, 4H) = x@wx + b precomputed; returns
ys (T, B, H) and cs (T, B, H). B % 8 == 0 and H % 128 == 0 required
(unsupported shapes raise — the LSTM op's default path IS the scan,
and force-mode must fail loudly rather than silently degrade).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend is absent on pure-CPU builds
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False


def _prec(dtype):
    """f32 operands dot at HIGHEST so the kernel and the XLA scan agree
    to f32 accuracy (DEFAULT lets Mosaic and XLA pick different bf16
    pass counts on the MXU); bf16 operands stay DEFAULT — single-pass
    native, and precision would only slow them down."""
    return (jax.lax.Precision.HIGHEST if dtype == jnp.float32
            else jax.lax.Precision.DEFAULT)


def _gates(lin, h):
    """lin (B, 4H) f32 logits -> activated i, f, g, o, each (B, H)."""
    hdim = h
    i = jax.nn.sigmoid(lin[:, :hdim])
    f = jax.nn.sigmoid(lin[:, hdim:2 * hdim])
    g = jnp.tanh(lin[:, 2 * hdim:3 * hdim])
    o = jax.nn.sigmoid(lin[:, 3 * hdim:])
    return i, f, g, o


# ---------------------------------------------------------------- forward
def _fwd_kernel(xg_ref, wh_ref, h0_ref, c0_ref, ys_ref, cs_ref,
                h_scr, c_scr, *, hdim):
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        h_scr[:] = h0_ref[:].astype(jnp.float32)
        c_scr[:] = c0_ref[:].astype(jnp.float32)

    h_prev = h_scr[:]
    lin = xg_ref[:].astype(jnp.float32) + jax.lax.dot(
        h_prev.astype(wh_ref.dtype), wh_ref[:],
        precision=_prec(wh_ref.dtype),
        preferred_element_type=jnp.float32)
    i, f, g, o = _gates(lin, hdim)
    c = f * c_scr[:] + i * g
    h = o * jnp.tanh(c)
    h_scr[:] = h
    c_scr[:] = c
    ys_ref[:] = h.astype(ys_ref.dtype)
    cs_ref[:] = c.astype(cs_ref.dtype)


def _fwd_pallas(xg, wh, h0, c0, *, interpret):
    T, B, four_h = xg.shape
    H = four_h // 4
    kern = functools.partial(_fwd_kernel, hdim=H)
    scratch = [
        pltpu.VMEM((B, H), jnp.float32),
        pltpu.VMEM((B, H), jnp.float32),
    ]
    return pl.pallas_call(
        kern,
        grid=(T,),
        in_specs=[
            pl.BlockSpec((None, B, four_h), lambda t: (t, 0, 0)),
            pl.BlockSpec((H, four_h), lambda t: (0, 0)),  # resident
            pl.BlockSpec((B, H), lambda t: (0, 0)),
            pl.BlockSpec((B, H), lambda t: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, B, H), lambda t: (t, 0, 0)),
            pl.BlockSpec((None, B, H), lambda t: (t, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, B, H), xg.dtype),
            jax.ShapeDtypeStruct((T, B, H), jnp.float32),
        ],
        scratch_shapes=scratch,
        interpret=interpret,
    )(xg, wh, h0, c0)


# --------------------------------------------------------------- backward
def _bwd_kernel(xg_ref, wh_ref, hprev_ref, cprev_ref, cs_ref, dys_ref,
                dxg_ref, dwh_ref, dh0_ref, dc0_ref,
                dh_scr, dc_scr, dwh_scr, *, hdim, T):
    step = pl.program_id(0)  # 0..T-1, walking time T-1..0 via index maps
    t_is_last = step == T - 1  # i.e. time step 0

    @pl.when(step == 0)
    def _init():
        dh_scr[:] = jnp.zeros_like(dh_scr)
        dc_scr[:] = jnp.zeros_like(dc_scr)
        dwh_scr[:] = jnp.zeros_like(dwh_scr)

    h_prev = hprev_ref[:].astype(jnp.float32)
    lin = xg_ref[:].astype(jnp.float32) + jax.lax.dot(
        h_prev.astype(wh_ref.dtype), wh_ref[:],
        precision=_prec(wh_ref.dtype),
        preferred_element_type=jnp.float32)
    i, f, g, o = _gates(lin, hdim)
    c = cs_ref[:].astype(jnp.float32)
    c_prev = cprev_ref[:].astype(jnp.float32)
    tanh_c = jnp.tanh(c)

    dh = dys_ref[:].astype(jnp.float32) + dh_scr[:]
    dc = dh * o * (1.0 - tanh_c * tanh_c) + dc_scr[:]
    do = dh * tanh_c
    di = dc * g
    dg = dc * i
    df = dc * c_prev
    dlin = jnp.concatenate([
        di * i * (1.0 - i),
        df * f * (1.0 - f),
        dg * (1.0 - g * g),
        do * o * (1.0 - o),
    ], axis=1)  # (B, 4H)

    dxg_ref[:] = dlin.astype(dxg_ref.dtype)
    dwh_scr[:] += jax.lax.dot_general(
        h_prev.astype(wh_ref.dtype), dlin.astype(wh_ref.dtype),
        (((0,), (0,)), ((), ())), precision=_prec(wh_ref.dtype),
        preferred_element_type=jnp.float32)
    dh_scr[:] = jax.lax.dot_general(
        dlin.astype(wh_ref.dtype), wh_ref[:],
        (((1,), (1,)), ((), ())), precision=_prec(wh_ref.dtype),
        preferred_element_type=jnp.float32)
    dc_scr[:] = dc * f

    @pl.when(t_is_last)
    def _finish():
        dwh_ref[:] = dwh_scr[:].astype(dwh_ref.dtype)
        dh0_ref[:] = dh_scr[:].astype(dh0_ref.dtype)
        dc0_ref[:] = dc_scr[:].astype(dc0_ref.dtype)


def _bwd_pallas(xg, wh, h0, c0, ys, cs, dys, *, interpret):
    T, B, four_h = xg.shape
    H = four_h // 4
    # previous-step states, host-assembled so the kernel needs no
    # negative block indices: hs_prev[t] = h_{t-1} (h0 at t=0)
    hs_prev = jnp.concatenate([h0[None].astype(ys.dtype), ys[:-1]], axis=0)
    cs_prev = jnp.concatenate([c0[None].astype(cs.dtype), cs[:-1]], axis=0)

    rev = lambda t: (T - 1 - t, 0, 0)  # noqa: E731
    const2 = lambda t: (0, 0)  # noqa: E731
    kern = functools.partial(_bwd_kernel, hdim=H, T=T)
    scratch = [
        pltpu.VMEM((B, H), jnp.float32),
        pltpu.VMEM((B, H), jnp.float32),
        pltpu.VMEM((H, four_h), jnp.float32),
    ]
    dxg, dwh, dh0, dc0 = pl.pallas_call(
        kern,
        grid=(T,),
        in_specs=[
            pl.BlockSpec((None, B, four_h), rev),
            pl.BlockSpec((H, four_h), const2),  # resident
            pl.BlockSpec((None, B, H), rev),    # hs_prev
            pl.BlockSpec((None, B, H), rev),    # cs_prev
            pl.BlockSpec((None, B, H), rev),    # cs
            pl.BlockSpec((None, B, H), rev),    # dys
        ],
        out_specs=[
            pl.BlockSpec((None, B, four_h), rev),
            pl.BlockSpec((H, four_h), const2),
            pl.BlockSpec((B, H), const2),
            pl.BlockSpec((B, H), const2),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, B, four_h), xg.dtype),
            jax.ShapeDtypeStruct((H, four_h), jnp.float32),
            jax.ShapeDtypeStruct((B, H), jnp.float32),
            jax.ShapeDtypeStruct((B, H), jnp.float32),
        ],
        scratch_shapes=scratch,
        interpret=interpret,
    )(xg, wh, hs_prev, cs_prev, cs, dys)
    return dxg, dwh, dh0, dc0


# ---------------------------------------------------------- custom VJP
@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _lstm_seq(xg, wh, h0, c0, interpret):
    ys, _ = _fwd_pallas(xg, wh, h0, c0, interpret=interpret)
    return ys


def _lstm_seq_fwd(xg, wh, h0, c0, interpret):
    ys, cs = _fwd_pallas(xg, wh, h0, c0, interpret=interpret)
    return ys, (xg, wh, h0, c0, ys, cs)


def _lstm_seq_bwd(interpret, res, dys):
    xg, wh, h0, c0, ys, cs = res
    dxg, dwh, dh0, dc0 = _bwd_pallas(xg, wh, h0, c0, ys, cs, dys,
                                     interpret=interpret)
    return (dxg, dwh.astype(wh.dtype), dh0.astype(h0.dtype),
            dc0.astype(c0.dtype))


_lstm_seq.defvjp(_lstm_seq_fwd, _lstm_seq_bwd)


def scan_reference(xg, wh, h0, c0):
    """Executable specification of the recurrence: the exact lax.scan
    the kernel replaces (ops/rnn.py cell with f32 carries). Both test
    suites validate the kernel against THIS single definition."""
    def cell(carry, xg_t):
        h_prev, c_prev = carry
        lin = xg_t.astype(jnp.float32) + jnp.dot(
            h_prev.astype(wh.dtype), wh,
            precision=_prec(wh.dtype),
            preferred_element_type=jnp.float32)
        i, f, g, o = jnp.split(lin, 4, axis=-1)
        c = jax.nn.sigmoid(f) * c_prev + jax.nn.sigmoid(i) * jnp.tanh(g)
        h = jax.nn.sigmoid(o) * jnp.tanh(c)
        return (h, c), h.astype(xg.dtype)

    (_, _), ys = jax.lax.scan(
        cell, (h0.astype(jnp.float32), c0.astype(jnp.float32)), xg)
    return ys


def lstm_sequence(xg, wh, h0, c0, *, interpret=False):
    """Run the LSTM recurrence over time via the Pallas kernel.

    xg (T, B, 4H) precomputed input gates (x@wx + b); wh (H, 4H);
    h0/c0 (B, H). Returns ys (T, B, H). Raises on unsupported
    shapes/platform — deliberate for the force-mode caller
    (LSTM use_pallas=True): an explicitly requested but unusable
    kernel must fail loudly, not silently degrade; the DEFAULT LSTM
    path is the scan."""
    if not _HAS_PLTPU or (not interpret
                          and jax.default_backend() != "tpu"):
        raise NotImplementedError("pallas lstm requires TPU (or the "
                                  "pallas TPU plugin for interpret mode)")
    T, B, four_h = xg.shape
    H = four_h // 4
    if B % 8 != 0 or H % 128 != 0:
        raise NotImplementedError(
            f"pallas lstm needs B%8==0 and H%128==0, got B={B} H={H}")
    return _lstm_seq(xg, wh, h0, c0, interpret)
