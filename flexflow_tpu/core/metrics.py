"""Training metrics.

Reference: src/metrics_functions/ — a `PerfMetrics` struct accumulated
per-partition on device and folded through an UPDATE_METRICS task on CPU0
(metrics_functions.cu:177-320, model.cc:2084-2108). On TPU the per-part
accumulation + future-fold is a single jnp reduction inside the jitted
step; the host only sees final scalars.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Sequence

import jax
import jax.numpy as jnp

METRICS_ACCURACY = "accuracy"
METRICS_CCE = "categorical_crossentropy"
METRICS_SPARSE_CCE = "sparse_categorical_crossentropy"
METRICS_MSE = "mean_squared_error"
METRICS_RMSE = "root_mean_squared_error"
METRICS_MAE = "mean_absolute_error"


@dataclasses.dataclass
class PerfMetrics:
    """Host-side accumulator, mirroring the reference struct
    (include/metrics_functions.h:26-58)."""

    train_all: int = 0
    train_correct: int = 0
    cce_loss: float = 0.0
    sparse_cce_loss: float = 0.0
    mse_loss: float = 0.0
    rmse_loss: float = 0.0
    mae_loss: float = 0.0

    def update(self, other: "PerfMetrics"):
        self.train_all += other.train_all
        self.train_correct += other.train_correct
        self.cce_loss += other.cce_loss
        self.sparse_cce_loss += other.sparse_cce_loss
        self.mse_loss += other.mse_loss
        self.rmse_loss += other.rmse_loss
        self.mae_loss += other.mae_loss

    def accuracy(self) -> float:
        return self.train_correct / max(1, self.train_all)


def compute_metrics(metric_names: Sequence[str], preds: jax.Array,
                    labels: jax.Array, sparse: bool) -> Dict[str, jax.Array]:
    """Pure-JAX metric computation; returns scalar sums/counts so results
    are exact under any sharding (mean taken on host)."""
    out: Dict[str, jax.Array] = {}
    if sparse:
        # same normalization as the loss (per-position seq2seq labels
        # flatten) so accuracy and CCE score identical positions
        from .losses import flatten_sparse_labels
        preds, lbl = flatten_sparse_labels(preds, labels)
    else:
        lbl = None
    n = preds.shape[0]
    out["count"] = jnp.asarray(n, jnp.int32)
    for m in metric_names:
        if m == METRICS_ACCURACY:
            pred_cls = jnp.argmax(preds, axis=-1).astype(jnp.int32)
            if sparse:
                correct = jnp.sum(pred_cls == lbl)
            else:
                correct = jnp.sum(pred_cls == jnp.argmax(labels, axis=-1))
            out["correct"] = correct
        elif m in (METRICS_CCE, METRICS_SPARSE_CCE):
            logp = jnp.log(jnp.clip(preds, 1e-12, 1.0))
            if sparse:
                # mode="clip": see core/losses.py — the fill-mode OOB
                # select breaks under GSPMD when classes are sharded
                nll = -jnp.take_along_axis(logp, lbl[:, None], axis=-1,
                                           mode="clip")
            else:
                nll = -jnp.sum(labels * logp, axis=-1)
            out["cce_sum"] = jnp.sum(nll)
        elif m == METRICS_MSE:
            out["mse_sum"] = jnp.sum(
                jnp.mean(jnp.square(preds - labels), axis=-1))
        elif m == METRICS_RMSE:
            # per-sample root-mean-square error, summed (host divides by
            # count — matches the reference's per-part rmse accumulation)
            out["rmse_sum"] = jnp.sum(
                jnp.sqrt(jnp.mean(jnp.square(preds - labels), axis=-1)))
        elif m == METRICS_MAE:
            out["mae_sum"] = jnp.sum(
                jnp.mean(jnp.abs(preds - labels), axis=-1))
    return out
