"""Executor: compiles the op graph into jitted train/eval steps.

Replaces the reference's per-iteration Legion machinery (SURVEY.md 3.3):
forward/zero_gradients/backward/update index launches + begin/end_trace
become ONE jitted function per step — XLA tracing plays the role Legion
tracing played (record once, replay thereafter), `jax.grad` replaces the
hand-written backward tasks, and GSPMD inserts every collective the
mapper/NCCL layer used to orchestrate.

State layout (all pytrees, shardable):
  params     {op_name: {weight_name: array}}
  states     {op_name: {state_name: array}}     (e.g. BN running stats)
  opt_state  optimizer-specific mirror of params
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..op import Op, OpContext
from ..tensor import Tensor
from . import initializers as I
from . import losses as L
from . import metrics as M
from . import precision as MP
from .optimizers import Optimizer
from ..parallel.pconfig import Strategy
from ..parallel.sharding import (
    batch_sharding,
    effective_op_strategy,
    op_output_sharding,
    place_global,
    place_process_local,
    spec_for_axes,
    weight_sharding,
)

# sentinel marking "no pinned sharding" in the recorded optimizer-slot
# sharding tree (None would read as an empty pytree under tree_map)
_NO_SHARDING = object()


def zero_applicable(config, mesh) -> bool:
    """The single ZeRO-1 eligibility rule (base and staged executors
    must agree): requested AND a data axis > 1 exists to shard over."""
    return bool(getattr(config, "zero_optimizer_sharding", False)
                and mesh is not None
                and mesh.shape.get("data", 1) > 1)


class TrainState:
    """Flat container; registered as a pytree for jit/donation."""

    def __init__(self, params, states, opt_state, step):
        self.params = params
        self.states = states
        self.opt_state = opt_state
        self.step = step

    def tree_flatten(self):
        return (self.params, self.states, self.opt_state, self.step), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


jax.tree_util.register_pytree_node(
    TrainState, TrainState.tree_flatten, TrainState.tree_unflatten)


def _permute_nhwc_sharding(s, mesh):
    """NCHW-axes NamedSharding -> the same logical sharding over an
    NHWC-permuted runtime value (executor NHWC residency)."""
    sp = tuple(s.spec) + (None,) * (4 - len(tuple(s.spec)))
    return NamedSharding(mesh, P(sp[0], sp[2], sp[3], sp[1]))


class Executor:
    def __init__(self, model, optimizer: Optimizer, loss_fn, metric_names,
                 mesh: Optional[Mesh] = None,
                 strategy: Optional[Strategy] = None,
                 comp_mode: str = "training"):
        self.model = model
        self.config = model.config
        self.optimizer = optimizer
        # reference COMP_MODE_INFERENCE (ffconst.h): no optimizer state
        # is allocated and the train steps refuse to build — forward/
        # evaluate only, at half the parameter memory of a training
        # compile (no momentum/m/v slots)
        if comp_mode not in ("training", "inference"):
            raise ValueError(
                f"comp_mode must be CompMode.TRAINING ('training') or "
                f"CompMode.INFERENCE ('inference'), got {comp_mode!r}")
        self.comp_mode = comp_mode
        self.loss_fn = L.resolve(loss_fn) if loss_fn is not None else None
        self.loss_name = loss_fn if isinstance(loss_fn, str) else "custom"
        self.metric_names = list(metric_names or [])
        self.mesh = mesh
        self.strategy = strategy or Strategy()
        # mixed-precision policy (core/precision.py): float params and
        # optimizer state live in param_dtype (f32 masters by default);
        # when compute_dtype != f32 the step casts params + float
        # activations down on the way in (forward_values) and computes
        # the loss/metrics on f32-upcast logits. compute_dtype == f32
        # is the no-cast fast path — builder-level bf16 models
        # (dtype=jnp.bfloat16 activations) keep their exact numerics.
        self.compute_dtype = jnp.dtype(self.config.compute_dtype)
        self.param_dtype = jnp.dtype(self.config.param_dtype)
        self._mp_active = MP.policy_active(self.config)
        self._train_step = None
        self._train_step_multi = None
        self._train_step_accum = None
        # bucketed backward-overlapped gradient sync (core/overlap.py):
        # bucket partition + the custom_vjp sync-point op are cached
        # against the sparse routing (sparse tables scatter outside the
        # bucketed reduction) and rebuilt when it changes. An unset
        # grad_bucket_mb (None) auto-tunes from the machine model for
        # THIS mesh (resolve_bucket_mb; 0 = monolithic when there is no
        # data axis to sync over); explicit values are authoritative.
        from .overlap import resolve_bucket_mb
        self._grad_bucket_mb = resolve_bucket_mb(self.config, model,
                                                 mesh=mesh)
        self._grad_buckets_cache = None
        self._bucket_tagger = None
        # runtime LR multiplier (model.set_learning_rate / keras
        # LearningRateScheduler): passed into every jitted step as a
        # traced scalar, so changing it NEVER recompiles
        self._lr_scale: float = 1.0
        self._lr_device = None  # cached device scalar (see _lr)
        self._lr_device_scale = None
        # resolved scan-vs-unroll decision for train_step_multi, keyed
        # on config.multi_step_unroll (see the property)
        self._train_step_multi_mode = None
        self._train_step_multi_unroll = None
        self._eval_step = None
        self._eval_step_multi = None
        self._sparse_ops_cache = None
        self._sparse_cache_key = None
        # the shared program registry (core/programs.py): train-step
        # dispatch resolves through it, so fit's compiled steps get the
        # same exact compile counting + AOT snapshot/warm-boot story as
        # the serving programs (--program-cache-dir). Lazy: built on
        # first dispatch, None after a construction failure (direct jit
        # dispatch is the fallback — training never depends on it)
        self._programs = None
        self._programs_failed = False
        self._last_aux_losses = []
        # lower device-explicit placements (strategy device_ids) into
        # the stacked-embedding slot layout BEFORE any weight_specs()
        # read — the executable form of the reference's slice_task
        # routing (mapper.cc:346-440); re-entrant across recompiles
        from ..ops.embedding import DistributedEmbedding
        for op in model.ops:
            if isinstance(op, DistributedEmbedding):
                s = self.strategy.for_op(op.name)
                op.apply_placement(s.device_ids or None, mesh)
        # fusion (reference apply_fusion, model.cc:1472): constrain
        # sharding only at fused-group boundaries.
        self._sharding_boundary = None
        if self.config.perform_fusion:
            from .fusion import boundary_ops, compute_fusion_groups
            self._sharding_boundary = boundary_ops(
                compute_fusion_groups(model, self.strategy))
        # sibling-conv batching (core/fusion.conv_sibling_groups): the
        # group leader runs the merged conv at its walk position; the
        # other members pop their pre-sliced output. Skipped when a
        # member has its own sharding strategy entry (a per-branch
        # channel-out split would shard the merged conv differently).
        self._conv_merge_leader = {}
        if getattr(self.config, "sibling_conv_fusion", True):
            from .fusion import _strategy_key, conv_sibling_groups
            for group in conv_sibling_groups(model):
                strat_keys = {_strategy_key(self.strategy, op.name)
                              for op in group}
                if len(strat_keys) > 1:
                    continue
                self._conv_merge_leader[group[0].name] = group
        # NHWC layout residency: under conv_layout="NHWC", values flow
        # channels-last BETWEEN conv-family ops instead of each op
        # transposing in and out. Per-op transpose pairs rely on XLA
        # cancellation, which breaks at Concat module boundaries and
        # ballooned compile time (round-4 NHWC arm >600s); residency
        # removes the pairs structurally. _nhwc_resident = tensor uids
        # whose runtime value is NHWC-permuted; _nhwc_reads = ops that
        # consume their inputs in that form.
        self._nhwc_resident, self._nhwc_reads = (
            self._compute_nhwc_resident()
            if self.config.conv_layout == "NHWC" else (set(), set()))

    def _compute_nhwc_resident(self):
        """Static dataflow pass for conv_layout="NHWC": which tensor
        values stay NHWC-permuted between ops, and which ops read them
        that way. Conv/Pool/BN always EMIT resident outputs (they
        compute in NHWC anyway); Concat-on-channels and same-shape
        pointwise ops PROPAGATE residency when every tensor input is
        resident; everything else reads NCHW (the walk inserts the
        transpose at the read). Per-op NCHW semantics (weights, state,
        output_axes, get/set_weights) are untouched — this is purely
        about the runtime value layout between ops."""
        core = {"conv2d", "pool2d", "batch_norm"}
        pointwise = {"element_unary", "element_binary", "dropout"}
        resident: set = set()
        reads: set = set()
        for op in self.model.ops:
            ins = op.inputs
            all_res = bool(ins) and all(t.uid in resident for t in ins)
            out4 = (op.outputs
                    and len(op.outputs[0].shape) == 4)
            if op.op_type in core and out4 \
                    and len(ins[0].shape) == 4:
                if all_res:
                    reads.add(op.name)
                resident.update(t.uid for t in op.outputs)
            elif (op.op_type == "concat" and out4 and all_res
                    and getattr(op, "axis", None) == 1):
                reads.add(op.name)
                resident.update(t.uid for t in op.outputs)
            elif (op.op_type in pointwise and out4 and all_res
                    and all(tuple(t.shape) == tuple(op.outputs[0].shape)
                            for t in ins)):
                # pointwise on identical shapes: layout-transparent
                reads.add(op.name)
                resident.update(t.uid for t in op.outputs)
        return resident, reads

    # ---------------- initialization ----------------
    def init_state(self, rng) -> TrainState:
        """Create params/states with per-parameter folded keys, sharded
        per strategy. Replaces reference initializer index launches
        (initializer.cc) + optimizer->init replicas (optimizer.cc:22-41)."""
        params: Dict[str, Dict[str, jax.Array]] = {}
        states: Dict[str, Dict[str, jax.Array]] = {}
        for op in self.model.ops:
            wspecs = op.weight_specs()
            if wspecs:
                op_params = {}
                for wname, spec in wspecs.items():
                    key = jax.random.fold_in(
                        jax.random.fold_in(rng, _stable_hash(op.name)),
                        _stable_hash(wname))
                    init_fn = spec.custom_init or I.resolve(spec.initializer)
                    if spec.fan_in is not None or spec.fan_out is not None:
                        arr = init_fn(key, spec.shape, spec.dtype,
                                      fan_in=spec.fan_in,
                                      fan_out=spec.fan_out)
                    else:
                        arr = init_fn(key, spec.shape, spec.dtype)
                    # master storage dtype: f32-declared float weights
                    # store at param_dtype; an EXPLICIT non-f32 spec
                    # dtype (a builder's bf16 table) wins over the knob
                    if (self.param_dtype != jnp.float32
                            and jnp.dtype(spec.dtype) == jnp.float32):
                        arr = arr.astype(self.param_dtype)
                    if self.mesh is not None:
                        sh = weight_sharding(
                            spec,
                            effective_op_strategy(
                                op, self.strategy.for_op(op.name),
                                self.mesh),
                            self.mesh)
                        arr = place_global(arr, sh)
                    op_params[wname] = arr
                params[op.name] = op_params
            sspecs = op.state_specs()
            if sspecs:
                op_states = {}
                for sname, sspec in sspecs.items():
                    # host-side init: placing from device via the
                    # multi-process callback would round-trip device->
                    # host->device for nothing
                    arr = np.full(sspec.shape, sspec.init_value,
                                  np.dtype(sspec.dtype))
                    if self.mesh is not None:
                        arr = place_global(
                            arr, NamedSharding(self.mesh, P()))
                    else:
                        arr = jnp.asarray(arr)
                    op_states[sname] = arr
                states[op.name] = op_states
        opt_state = (self.optimizer.init_state(params)
                     if self.optimizer and self.comp_mode != "inference"
                     else {})
        opt_state = self._zero_shard_slots(opt_state)
        return TrainState(params, states, opt_state, self._init_step())

    def _zero_shard_slots(self, opt_state):
        """ZeRO-1 (config.zero_optimizer_sharding): re-place dense
        optimizer slots sharded over the `data` axis — the first
        still-unsharded dimension that divides takes it. Pure GSPMD:
        the update's sharding constraint (_apply_update) keeps them
        there across steps and XLA inserts the reduce-scatter /
        all-gather. Sparse-table slots keep their layout (their scatter
        update addresses rows by index). Records the slot sharding tree
        either way so _apply_update can pin outputs."""
        self._opt_shardings = None
        if not opt_state:
            return opt_state
        if zero_applicable(self.config, self.mesh):
            nd = self.mesh.shape["data"]
            sparse = {op.name for op in self.model.ops
                      if op.op_type in ("embedding",
                                        "distributed_embedding")}

            def place(path, arr):
                if not isinstance(arr, jax.Array) or arr.ndim == 0:
                    return arr
                # path = (slot, op_name, weight_name)
                if len(path) >= 2 and str(getattr(
                        path[1], "key", "")) in sparse:
                    return arr
                sh = arr.sharding
                spec = (list(sh.spec) if isinstance(sh, NamedSharding)
                        else [])
                spec += [None] * (arr.ndim - len(spec))
                used = {ax for e in spec if e
                        for ax in (e if isinstance(e, tuple) else (e,))}
                if "data" in used:
                    return arr
                for i in range(arr.ndim):
                    if spec[i] is None and arr.shape[i] % nd == 0:
                        spec[i] = "data"
                        # freshly-initialized slots are zeros by
                        # construction (SGD momentum / Adam m,v), so
                        # materialize host-side and place_global —
                        # multi-controller meshes span devices this
                        # process cannot address (device_put/device_get
                        # would both fail there)
                        return place_global(
                            np.zeros(arr.shape, arr.dtype),
                            NamedSharding(self.mesh, P(*spec)))
                return arr

            opt_state = jax.tree_util.tree_map_with_path(place,
                                                         opt_state)
            self._opt_shardings = jax.tree_util.tree_map(
                lambda a: (a.sharding
                           if isinstance(a, jax.Array)
                           and isinstance(a.sharding, NamedSharding)
                           else _NO_SHARDING),
                opt_state)
        return opt_state

    def _init_step(self):
        """Step counter, committed to the mesh (replicated) when one
        exists: a checkpoint restore otherwise brings it back committed
        to ONE device, and jit rejects the mixed device assignment
        against mesh-sharded params."""
        if self.mesh is None:
            return jnp.zeros((), jnp.int32)
        return place_global(np.zeros((), np.int32),
                            NamedSharding(self.mesh, P()))

    # ---------------- forward ----------------
    def forward_values(self, params, states, inputs: Dict[str, jax.Array],
                      training: bool, rng, seq_length: int = -1):
        """Topological walk of the graph; returns (tensor-values map,
        new_states)."""
        # mixed precision: master params (param_dtype) and float inputs
        # cast to compute_dtype HERE, inside whatever function is being
        # differentiated — the cast's transpose upcasts cotangents, so
        # gradients leave the bf16 region in the master dtype. Labels
        # are not inputs and never pass through this cast.
        if self._mp_active:
            params = MP.cast_floats(params, self.compute_dtype)
        values: Dict[int, jax.Array] = {}
        for t in self.model.input_tensors:
            if t.name not in inputs:
                raise KeyError(f"missing input {t.name!r}; have {list(inputs)}")
            v = inputs[t.name]
            if self._mp_active and MP.is_float_array(v) \
                    and v.dtype != self.compute_dtype:
                v = v.astype(self.compute_dtype)
            values[t.uid] = v
        new_states: Dict[str, Dict[str, jax.Array]] = {}
        aux_losses = []
        # pre-sliced outputs of merged sibling convs, keyed by the
        # member op that will claim them at its own walk position
        merged_pending: Dict[str, jax.Array] = {}
        for op in self.model.ops:
            ctx = OpContext(
                training=training,
                rng=(jax.random.fold_in(rng, _stable_hash(op.name))
                     if rng is not None else None),
                seq_length=seq_length,
                state_in=states.get(op.name, {}),
                mesh=self.mesh,
                op_strategy=self.strategy.for_op(op.name),
                nhwc_in=op.name in self._nhwc_reads,
                nhwc_out=bool(op.outputs
                              and op.outputs[0].uid
                              in self._nhwc_resident),
            )
            xs = []
            for t in op.inputs:
                v = values[t.uid]
                if (t.uid in self._nhwc_resident
                        and op.name not in self._nhwc_reads):
                    # layout boundary: this consumer wants NCHW (XLA
                    # CSEs the duplicate when several consumers read)
                    v = jnp.transpose(v, (0, 3, 1, 2))
                xs.append(v)
            op_params = params.get(op.name, {})
            # remat: recompute this op's activations in backward instead of
            # saving them (HBM-for-FLOPs trade, SURVEY.md env notes). Ops
            # with functional state (BN) or aux losses (MoE) are excluded —
            # their ctx side-channel values must not escape the
            # checkpointed trace (tracer leak otherwise).
            if op.name in merged_pending:
                ys = [merged_pending.pop(op.name)]
            elif op.name in self._conv_merge_leader:
                from ..ops.conv import merged_conv_forward
                group = self._conv_merge_leader[op.name]
                plist = [params.get(m.name, {}) for m in group]
                # group members share the leader's input and geometry,
                # so the leader's residency flags speak for the group
                nin, nout = ctx.nhwc_in, ctx.nhwc_out
                if self.config.remat:
                    outs = jax.checkpoint(
                        lambda ps, x, _g=group, _i=nin, _o=nout:
                        merged_conv_forward(_g, ps, x, _i, _o))(
                            plist, xs[0])
                else:
                    outs = merged_conv_forward(group, plist, xs[0],
                                               nin, nout)
                for m, y in zip(group[1:], outs[1:]):
                    merged_pending[m.name] = y
                ys = [outs[0]]
            elif (self.config.remat and op.weight_specs()
                    and not op.state_specs()
                    and not getattr(op, "has_aux_loss", False)):
                ys = jax.checkpoint(
                    lambda p, x, _op=op, _ctx=ctx: _op.forward(p, x, _ctx)
                )(op_params, xs)
            else:
                ys = op.forward(op_params, xs, ctx)
            if self.mesh is not None and (
                    self._sharding_boundary is None
                    or op.name in self._sharding_boundary):
                shardings = op_output_sharding(
                    op, self.strategy.for_op(op.name), self.mesh)
                # NHWC-resident values are permuted (N,H,W,C) at
                # runtime while op axes speak NCHW — permute the spec
                # with them or the constraint pins the wrong dims
                shardings = [
                    _permute_nhwc_sharding(s, self.mesh)
                    if (t.uid in self._nhwc_resident
                        and len(t.shape) == 4) else s
                    for t, s in zip(op.outputs, shardings)]
                ys = [jax.lax.with_sharding_constraint(y, s)
                      for y, s in zip(ys, shardings)]
            if self._mp_active:
                # keep the VALUE stream at compute_dtype: ops that pin
                # their output dtype (Embedding's out_dtype defaults
                # f32) would otherwise silently upcast everything
                # downstream of them back to f32. State/aux outputs
                # (BN statistics, MoE aux loss) are NOT values and
                # stay f32.
                ys = [y.astype(self.compute_dtype)
                      if MP.is_float_array(y)
                      and y.dtype != self.compute_dtype else y
                      for y in ys]
            for t, y in zip(op.outputs, ys):
                values[t.uid] = y
            if ctx.state_out:
                new_states[op.name] = ctx.state_out
            if ctx.aux_loss is not None:
                aux_losses.append(ctx.aux_loss)
        # carry through untouched states (eval path of ops w/o forward call)
        for name, s in states.items():
            new_states.setdefault(name, s)
        self._last_aux_losses = aux_losses
        # normalize NHWC-resident values back to logical NCHW so every
        # caller (loss, metrics, tests reading intermediate tensors)
        # sees declared shapes; under jit the unused transposes are DCE'd
        for uid in self._nhwc_resident:
            if uid in values and values[uid].ndim == 4:
                values[uid] = jnp.transpose(values[uid], (0, 3, 1, 2))
        return values, new_states

    # ---------------- bucketed grad-sync points (core/overlap.py) -----
    def _grad_buckets(self):
        """Cached walk-order sync-bucket partition (list of (names,
        bytes)); [] when grad_bucket_mb is 0 (legacy monolithic)."""
        if self._grad_buckets_cache is None:
            from .overlap import grad_buckets
            self._grad_buckets_cache = grad_buckets(
                self.model, self._grad_bucket_mb,
                sparse_ops=set(self._sparse_table_ops()))
        return self._grad_buckets_cache

    def grad_bucket_info(self) -> Dict[str, Any]:
        """Bucket layout for profiling.train_report."""
        buckets = self._grad_buckets()
        return {"count": len(buckets),
                "bucket_mb": self._grad_bucket_mb,
                "bytes": [b for _, b in buckets]}

    def _tag_grad_buckets(self, params):
        """Thread the bucketed params through the sync-point op so each
        bucket's gradient all-reduce anchors inside the backward pass at
        grad-completion (identity on values — grads stay bit-identical;
        see core/overlap.make_bucket_tagger)."""
        buckets = self._grad_buckets()
        if not buckets:
            return params
        if self._bucket_tagger is None:
            from .overlap import make_bucket_tagger
            self._bucket_tagger = make_bucket_tagger(
                [names for names, _ in buckets])
        sub = {n: params[n] for names, _ in buckets for n in names
               if n in params}
        if not sub:
            return params
        tagged = self._bucket_tagger(sub)
        return {**params, **tagged}

    def _outputs_and_loss(self, params, states, batch, training, rng,
                          seq_length):
        if training and self._grad_bucket_mb > 0:
            params = self._tag_grad_buckets(params)
        values, new_states = self.forward_values(
            params, states, batch, training, rng, seq_length)
        logits = values[self.model.final_tensor.uid]
        if self._mp_active and MP.is_float_array(logits):
            # losses and metrics score f32-upcast logits — the one
            # policy-exempt region (precision.py): a bf16 NLL would
            # round away exactly the signal the parity gate measures
            logits = logits.astype(jnp.float32)
        loss = jnp.asarray(0.0, jnp.float32)
        if self.loss_fn is not None and "label" in batch:
            loss = self.loss_fn(logits, batch["label"])
        for aux in self._last_aux_losses:
            loss = loss + aux
        return loss, (logits, new_states)

    # ---------------- sparse-table routing ----------------
    def _sparse_table_ops(self) -> Dict[str, Op]:
        """Embedding-family ops eligible for the sparse-update path:
        their index tensors are graph INPUTS (so the executor can gather
        the touched rows before differentiation) and the optimizer has a
        sparse row form (Optimizer.sparse_mode): "exact" is used freely,
        "lazy" (stale untouched rows, SparseAdam-style) only when
        config.sparse_embedding_lazy opts in. Reference analog: the
        scatter-add embedding backward + per-table update of
        src/ops/embedding.cu — the dense-gradient alternative writes the
        full (vocab, dim) table's worth of zeros + updates every step,
        ruinous at DLRM scale.

        Eligibility is keyed on the live sparse flags + optimizer; if
        they change after steps were compiled, the stale compiled steps
        are dropped so the next dispatch rebuilds with the new routing
        (cost_model.py reads config live — keep the two in agreement)."""
        # the optimizer OBJECT (not id(): a recycled address after gc
        # could false-match) — default object __eq__ is identity and the
        # strong ref pins it
        key = (self.config.sparse_embedding_updates,
               self.config.sparse_embedding_lazy,
               self.optimizer,
               self.optimizer.sparse_mode() if self.optimizer else None)
        if self._sparse_ops_cache is not None:
            if self._sparse_cache_key == key:
                return self._sparse_ops_cache
            # routing changed post-build: invalidate compiled steps that
            # baked in the old sparse/dense split (and the grad-sync
            # bucket partition, which excludes sparse tables)
            self._train_step = None
            self._train_step_multi = None
            self._train_step_accum = None
            self._grad_buckets_cache = None
            self._bucket_tagger = None
        from ..ops.embedding import DistributedEmbedding, Embedding
        out: Dict[str, Op] = {}
        mode = (self.optimizer.sparse_mode() if self.optimizer else None)
        allowed = mode == "exact" or (
            mode == "lazy" and self.config.sparse_embedding_lazy)
        if self.config.sparse_embedding_updates and allowed:
            input_uids = {t.uid for t in self.model.input_tensors}
            for op in self.model.ops:
                if not isinstance(op, (Embedding, DistributedEmbedding)):
                    continue
                if all(t.uid in input_uids for t in op.inputs):
                    out[op.name] = op
        self._sparse_ops_cache = out
        self._sparse_cache_key = key
        return out

    # ---------------- step builders ----------------
    def _compute_grads(self, params, states, batch, rng):
        """Gradients for one (micro)batch. For sparse tables the touched
        rows are pre-gathered OUTSIDE the differentiated function
        (forward consumes them via the "__rows__" override), so autodiff
        returns row-gradients instead of a dense table.

        -> (loss, logits, new_states, grads, sparse_idx) where `grads`
        has {"__rows__": ...} entries for sparse ops."""
        from ..ops.embedding import DistributedEmbedding
        seq_length = self.config.iter_config.seq_length
        sparse_ops = self._sparse_table_ops()
        diff_params = params
        sparse_idx: Dict[str, jax.Array] = {}
        if sparse_ops:
            diff_params = dict(params)
            for name, op in sparse_ops.items():
                table = params[name]["kernel"]
                if isinstance(op, DistributedEmbedding):
                    # slot order (matches the kernel layout, incl.
                    # device-placed permutations)
                    idx = op.slot_ids([batch[t.name]
                                       for t in op.inputs])
                    # flat slot-offset gather, NOT vmap(take): the
                    # batched-gather form mis-partitions under GSPMD
                    # when the slot axis is sharded (ops/embedding.py
                    # _slot_gather has the full story)
                    from ..ops.embedding import _slot_gather
                    rows = _slot_gather(table, idx)
                else:
                    idx = batch[op.inputs[0].name].astype(jnp.int32)
                    rows = jnp.take(table, idx, axis=0, mode="clip")
                sparse_idx[name] = idx
                diff_params[name] = {"__rows__": rows}
        grad_fn = jax.value_and_grad(
            self._outputs_and_loss, argnums=0, has_aux=True)
        (loss, (logits, new_states)), grads = grad_fn(
            diff_params, states, batch, True, rng, seq_length)
        return loss, logits, new_states, grads, sparse_idx

    def _apply_update(self, state: TrainState, grads, sparse_idx,
                      new_states, lr_scale=1.0) -> TrainState:
        """Apply the optimizer to dense grads + scatter-apply sparse row
        grads; returns the next TrainState (metrics are the caller's)."""
        from ..ops.embedding import DistributedEmbedding
        sparse_ops = self._sparse_table_ops()
        if sparse_ops:
            dense_params = {k: v for k, v in state.params.items()
                            if k not in sparse_ops}
            dense_grads = {k: grads[k] for k in dense_params}
            # optimizer state mirrors params at the top (op-name) level
            # for both built-ins ({"v": {op: ...}} / {"m","v"}): split
            # out the sparse tables' slots so the dense update's tree
            # structures match, then merge the scatter-updated slots back
            dense_opt = {slot: {k: v for k, v in tree.items()
                                if k not in sparse_ops}
                         for slot, tree in state.opt_state.items()}
            new_params, new_opt = self.optimizer.update(
                dense_params, dense_grads, dense_opt, state.step,
                lr_scale=lr_scale)
            new_params = dict(new_params)
            new_opt = {slot: dict(tree) for slot, tree in new_opt.items()}
            for name, op in sparse_ops.items():
                table = state.params[name]["kernel"]
                g = grads[name]["__rows__"]
                dim = table.shape[-1]
                slots = {slot: tree[name]["kernel"]
                         for slot, tree in state.opt_state.items()
                         if name in tree}
                if isinstance(op, DistributedEmbedding):
                    ntab = table.shape[0]
                    newt, new_slots = jax.vmap(
                        lambda w_, i_, g_, s_: self.optimizer.
                        sparse_update(w_, i_, g_, s_, state.step,
                                      lr_scale=lr_scale)
                    )(table, sparse_idx[name].reshape(ntab, -1),
                      g.reshape(ntab, -1, dim), slots)
                else:
                    newt, new_slots = self.optimizer.sparse_update(
                        table, sparse_idx[name].reshape(-1),
                        g.reshape(-1, dim), slots, state.step,
                        lr_scale=lr_scale)
                new_params[name] = {**state.params[name], "kernel": newt}
                for slot, arr in new_slots.items():
                    new_opt[slot][name] = {
                        **state.opt_state[slot][name], "kernel": arr}
        else:
            new_params, new_opt = self.optimizer.update(
                state.params, grads, state.opt_state, state.step,
                lr_scale=lr_scale)
        shardings = getattr(self, "_opt_shardings", None)
        if shardings is not None:
            # ZeRO slots must STAY data-sharded across steps: without
            # the constraint XLA's propagation may emit replicated slot
            # outputs, silently un-sharding them after one step
            new_opt = jax.tree_util.tree_map(
                lambda a, sh: (a if sh is _NO_SHARDING
                               else jax.lax.with_sharding_constraint(
                                   a, sh)),
                new_opt, shardings)
        return TrainState(new_params, new_states, new_opt, state.step + 1)

    def _step_body(self, state: TrainState, batch: Dict[str, jax.Array],
                   rng, lr_scale=1.0
                   ) -> Tuple[TrainState, Dict[str, jax.Array]]:
        """One optimizer step (pure; shared by the single-step and the
        scanned multi-step compilations)."""
        loss, logits, new_states, grads, sparse_idx = self._compute_grads(
            state.params, state.states, batch, rng)
        new_state = self._apply_update(state, grads, sparse_idx,
                                       new_states, lr_scale)
        metrics = {"loss": loss}
        if "label" in batch and self.metric_names:
            sparse = self.loss_name.startswith("sparse")
            metrics.update(M.compute_metrics(
                self.metric_names, logits, batch["label"], sparse))
        return new_state, metrics

    def build_train_step(self):
        return jax.jit(self._step_body, donate_argnums=(0,))

    def _multi_step_unroll(self) -> bool:
        """Should train_step_multi unroll its K steps instead of
        lax.scan? config.multi_step_unroll: True / False / "auto".
        Auto unrolls only when the donated params are a large fraction
        of device memory (the scan's double-buffered carry would 2x
        them); everything else keeps the scan (constant compile time)."""
        mode = getattr(self.config, "multi_step_unroll", "auto")
        if mode is True or mode is False:
            return mode
        dev = jax.devices()[0]
        if dev.platform != "tpu":
            return False  # CPU/GPU alias scan carries in place
        try:
            limit = (dev.memory_stats() or {}).get("bytes_limit")
        except Exception:  # tunnel devices may not expose stats
            limit = None
        limit = limit or 16e9  # v5e-class default when unreported
        state = getattr(self.model, "state", None)
        if state is None:
            return False
        # the double-buffered carry is the WHOLE donated TrainState:
        # params + op states + optimizer slots (Adam's m/v triple the
        # param bytes), not just params — counted PER DEVICE: on a
        # multi-device mesh a sharded leaf occupies only its shard
        # bytes per chip, and comparing global bytes against one
        # chip's bytes_limit would over-trigger the unrolled body
        # (paying K-times compile) on models that actually fit scanned
        def _per_device_bytes(x):
            itemsize = jnp.dtype(x.dtype).itemsize
            shd = getattr(x, "sharding", None)
            if shd is not None:
                try:
                    shard_shape = shd.shard_shape(x.shape)
                    n = 1
                    for d in shard_shape:
                        n *= d
                    return n * itemsize
                except Exception:
                    pass
            return x.size * itemsize

        pbytes = sum(
            _per_device_bytes(x)
            for x in jax.tree_util.tree_leaves(
                (state.params, state.states, state.opt_state)))
        return pbytes > 0.25 * limit

    def build_train_step_multi(self):
        """K optimizer steps per device dispatch, via `lax.scan` over the
        leading (step) axis of a stacked batch. This is the TPU analog of
        the reference's Legion trace record/replay (begin_trace/end_trace,
        SURVEY.md 3.3): one host round trip launches many iterations, so
        per-dispatch latency (severe through a remote-TPU tunnel) is
        amortized instead of paid per step. Metrics come back stacked
        with a leading (K,) axis."""

        unroll = self._train_step_multi_unroll
        if unroll is None:  # direct build_* callers (tests): resolve now
            unroll = self._multi_step_unroll()
        if unroll:
            # UNROLLED K steps: a lax.scan carry is double-buffered on
            # TPU (old + new buffer live across the body), which doubles
            # the resident footprint of the donated params — at DLRM
            # scale (26x1M-row tables = 6.2G) the scanned program needs
            # 2x-table scratch and OOMs a 16G chip that the single-step
            # program fits comfortably. Straight-line sequential updates
            # alias in place, keeping the one-dispatch amortization
            # without the 2x liveness. Compile time grows with K, so
            # this is gated on param bytes (big-param models have small
            # graphs in practice).
            def train_multi(state: TrainState, batches, rngs, lr_scale):
                k = jax.tree_util.tree_leaves(batches)[0].shape[0]
                out = []
                for i in range(k):
                    batch = jax.tree_util.tree_map(lambda x: x[i], batches)
                    state, metrics = self._step_body(
                        state, batch, rngs[i], lr_scale)
                    out.append(metrics)
                stacked = jax.tree_util.tree_map(
                    lambda *ms: jnp.stack(ms), *out)
                return state, stacked
        else:
            def train_multi(state: TrainState, batches, rngs, lr_scale):
                def body(st, xs):
                    batch, rng = xs
                    return self._step_body(st, batch, rng, lr_scale)

                return jax.lax.scan(body, state, (batches, rngs))

        return jax.jit(train_multi, donate_argnums=(0,))

    def build_train_step_accum(self):
        """Gradient accumulation: scan K MICRObatches computing and
        summing gradients, then apply ONE optimizer update with the mean
        — the effective batch is K x microbatch without K x the
        activation memory. No reference analog (FlexFlow scales batch by
        adding GPUs, multi_gpu_tests.sh GPUS*64); on TPU this is the
        standard single-chip route to large-batch parity. Sparse-table
        row gradients are CONCATENATED across microbatches and applied
        in one scatter, so the result is identical to a K x-sized batch
        (duplicates across microbatches coalesce exactly like duplicates
        within one). BN statistics advance per microbatch (each sees its
        own microbatch moments, as torch/keras accumulation loops do)."""
        sparse_ops = self._sparse_table_ops()

        def train_accum(state: TrainState, batches, rngs, lr_scale):
            k = jax.tree_util.tree_leaves(batches)[0].shape[0]
            dense_zero = jax.tree_util.tree_map(
                lambda w: jnp.zeros(w.shape, jnp.float32),
                {n: p for n, p in state.params.items()
                 if n not in sparse_ops})

            def body(carry, xs):
                states_c, gacc = carry
                batch, rng = xs
                loss, logits, new_states, grads, sidx = \
                    self._compute_grads(state.params, states_c, batch,
                                        rng)
                dense_g = {n: grads[n] for n in gacc}
                gacc = jax.tree_util.tree_map(
                    lambda a, g: a + g.astype(jnp.float32),
                    gacc, dense_g)
                rows = {n: grads[n]["__rows__"] for n in sparse_ops}
                metrics = {"loss": loss}
                if "label" in batch and self.metric_names:
                    sparse = self.loss_name.startswith("sparse")
                    metrics.update(M.compute_metrics(
                        self.metric_names, logits, batch["label"],
                        sparse))
                return (new_states, gacc), (rows, sidx, metrics)

            (new_states, gsum), (rows_st, sidx_st, metrics) = \
                jax.lax.scan(body, (state.states, dense_zero),
                             (batches, rngs))
            # mean over microbatches = the K x-batch loss gradient
            gmean = jax.tree_util.tree_map(lambda g: g / k, gsum)
            grads = dict(gmean)
            sparse_idx = {}
            for name, op in sparse_ops.items():
                r = rows_st[name] / k          # (K, ...) row grads
                i = sidx_st[name]              # (K, ...) indices
                from ..ops.embedding import DistributedEmbedding
                if isinstance(op, DistributedEmbedding):
                    # (K, E, ...) -> (E, K*...): per-table concat
                    r = jnp.moveaxis(r, 0, 1)
                    i = jnp.moveaxis(i, 0, 1)
                    ntab = r.shape[0]
                    r = r.reshape(ntab, -1, r.shape[-1])
                    i = i.reshape(ntab, -1)
                else:
                    r = r.reshape(-1, r.shape[-1])
                    i = i.reshape(-1)
                grads[name] = {"__rows__": r}
                sparse_idx[name] = i
            new_state = self._apply_update(state, grads, sparse_idx,
                                           new_states, lr_scale)
            # one optimizer step happened, whatever K was: fold the
            # per-microbatch metrics like one K x batch (sums of
            # sum-style metrics, mean loss)
            metrics = {name: jnp.sum(v, axis=0)
                       for name, v in metrics.items()}
            metrics["loss"] = metrics["loss"] / k
            return new_state, metrics

        return jax.jit(train_accum, donate_argnums=(0,))

    def _eval_body(self, state: TrainState, batch: Dict[str, jax.Array]):
        loss, (logits, _) = self._outputs_and_loss(
            state.params, state.states, batch, False, None,
            self.config.iter_config.seq_length)
        metrics = {"loss": loss}
        if "label" in batch and self.metric_names:
            sparse = self.loss_name.startswith("sparse")
            metrics.update(M.compute_metrics(
                self.metric_names, logits, batch["label"], sparse))
        return logits, metrics

    def build_eval_step(self):
        return jax.jit(self._eval_body)

    def build_eval_step_multi(self):
        """K eval batches per dispatch (scan over the stacked step axis;
        read-only twin of train_step_multi). Returns metrics stacked
        (K,) — logits are dropped to keep the dispatch output small."""

        def eval_multi(state: TrainState, batches):
            def body(_, batch):
                _logits, metrics = self._eval_body(state, batch)
                return (), metrics

            _, metrics = jax.lax.scan(body, (), batches)
            return metrics

        return jax.jit(eval_multi)

    def _require_training(self):
        if self.comp_mode == "inference":
            raise RuntimeError(
                "model was compiled with comp_mode=INFERENCE (no "
                "optimizer state); recompile with comp_mode=TRAINING "
                "to train")

    # ---------------- program registry ----------------
    def _opt_sig(self):
        """Stable token for the optimizer's PROGRAM identity: class +
        scalar hyperparameters (they are baked into the compiled step
        as constants — the runtime lr_scale is the only traced knob)."""
        opt = self.optimizer
        if opt is None:
            return None
        hp = {k: v for k, v in vars(opt).items()
              if isinstance(v, (int, float, bool, str))}
        return (type(opt).__name__, tuple(sorted(hp.items())))

    def _train_fingerprint(self) -> dict:
        """Cache identity of this executor's train programs — the
        analog of ServeEngine._program_fingerprint for fit's step
        (argument shapes/dtypes/shardings are keyed per call by the
        registry; this folds what the arguments cannot express)."""
        cfg = self.config
        mesh_sig = None
        if self.mesh is not None:
            mesh_sig = tuple(sorted(
                (str(k), int(v))
                for k, v in dict(self.mesh.shape).items()))
        arch = tuple((op.name, type(op).__name__)
                     for op in self.model.ops)
        return {
            "kind": "train",
            "jax": jax.__version__,
            "backend": jax.default_backend(),
            "devices": jax.device_count(),
            "arch": arch,
            "mesh": mesh_sig,
            "compute_dtype": str(self.compute_dtype),
            "param_dtype": str(self.param_dtype),
            "loss": self.loss_name,
            "metrics": tuple(self.metric_names),
            "grad_bucket_mb": self._grad_bucket_mb,
            "fusion": bool(cfg.perform_fusion),
            "seq_length": cfg.iter_config.seq_length,
        }

    def _train_variant(self) -> str:
        """Per-dispatch build-variant token folded into the registry
        key: everything _sparse_table_ops / the multi-mode check can
        rebuild the jitted step over WITHOUT any argument changing
        shape. A stale-variant executable therefore can never be
        resolved for a rebuilt step."""
        mode = self.optimizer.sparse_mode() if self.optimizer else None
        return repr((self.config.sparse_embedding_updates,
                     self.config.sparse_embedding_lazy,
                     self._opt_sig(), mode,
                     self._train_step_multi_unroll))

    def program_registry(self):
        """The executor's ProgramRegistry, or None when construction
        failed (training falls back to direct jit dispatch)."""
        if self._programs is None and not self._programs_failed:
            try:
                from .programs import ProgramRegistry
                self._programs = ProgramRegistry(
                    self._train_fingerprint(),
                    cache_dir=getattr(self.config,
                                      "program_cache_dir", None))
                self._programs.load_warm()
            except Exception as e:
                import warnings
                warnings.warn(
                    f"program registry unavailable for training ({e}); "
                    f"dispatching through jit directly", stacklevel=2)
                self._programs_failed = True
        return self._programs

    def compile_counts(self) -> dict:
        """Exact per-family compile counts for the train programs
        (registry query — empty dict before the first dispatch)."""
        reg = self._programs
        return {} if reg is None else reg.compile_counts()

    def save_programs(self) -> int:
        """Snapshot freshly compiled train executables to
        config.program_cache_dir (no-op when unarmed/clean). fit calls
        this at exit so the next process boots the step warm."""
        reg = self._programs
        if reg is None or not reg.cache_dir or not reg._dirty:
            return 0
        return reg.save()

    def _lr(self):
        """The runtime LR multiplier as a traced scalar input — a value
        change re-dispatches, never recompiles.

        The device scalar is CACHED: re-making it per dispatch would put
        one synchronous host->device transfer on every train_batches
        call, serializing the otherwise-async dispatch queue on host
        (or, through the axon tunnel, network) round trips — all other
        dispatch arguments (donated state, staged batches) are already
        device-resident by design."""
        if (self._lr_device is None
                or self._lr_device_scale != self._lr_scale):
            self._lr_device = jnp.asarray(self._lr_scale, jnp.float32)
            self._lr_device_scale = self._lr_scale
        return self._lr_device

    @property
    def train_step(self):
        self._require_training()
        # consult the sparse routing FIRST: a post-build change to the
        # sparse flags/optimizer invalidates the cached compiled step
        # (see _sparse_table_ops), so the rebuild happens on dispatch
        self._sparse_table_ops()
        if self._train_step is None:
            self._train_step = self.build_train_step()
        jitted = self._train_step
        reg = self.program_registry()
        if reg is None:
            return lambda st, b, r: jitted(st, b, r, self._lr())
        var = self._train_variant()
        return lambda st, b, r: reg.call(
            "train_step", jitted, st, b, r, self._lr(), extra_key=var)

    @property
    def train_step_multi(self):
        self._require_training()
        self._sparse_table_ops()
        # the compiled body bakes in the scan-vs-unroll choice: a
        # post-build change to config.multi_step_unroll (the documented
        # OOM override) must rebuild, same as the sparse-routing key.
        # The RESOLVED decision is cached against the config value —
        # _multi_step_unroll() itself touches jax.devices().
        # memory_stats() and sums the param tree, which must not run
        # per dispatch in the hot loop this property serves
        mode = getattr(self.config, "multi_step_unroll", "auto")
        if (self._train_step_multi_mode != mode
                or self._train_step_multi_unroll is None):
            self._train_step_multi = None
            self._train_step_multi_mode = mode
            self._train_step_multi_unroll = self._multi_step_unroll()
        if self._train_step_multi is None:
            self._train_step_multi = self.build_train_step_multi()
        jitted = self._train_step_multi
        reg = self.program_registry()
        if reg is None:
            return lambda st, bs, rs: jitted(st, bs, rs, self._lr())
        var = self._train_variant()
        return lambda st, bs, rs: reg.call(
            "train_step_multi", jitted, st, bs, rs, self._lr(),
            extra_key=var)

    @property
    def train_step_accum(self):
        self._require_training()
        self._sparse_table_ops()
        if self._train_step_accum is None:
            self._train_step_accum = self.build_train_step_accum()
        jitted = self._train_step_accum
        reg = self.program_registry()
        if reg is None:
            return lambda st, bs, rs: jitted(st, bs, rs, self._lr())
        var = self._train_variant()
        return lambda st, bs, rs: reg.call(
            "train_step_accum", jitted, st, bs, rs, self._lr(),
            extra_key=var)

    @property
    def eval_step(self):
        if self._eval_step is None:
            self._eval_step = self.build_eval_step()
        return self._eval_step

    @property
    def eval_step_multi(self):
        if self._eval_step_multi is None:
            self._eval_step_multi = self.build_eval_step_multi()
        return self._eval_step_multi

    # ---------------- data placement ----------------
    @property
    def declared_input_dtypes(self) -> Dict[str, Any]:
        """Target device dtype per input name — THE dtype-resolution rule
        for batches (shard_batch, shard_batch_stacked, and fit()'s
        prefetch loader all share it so every path casts identically).
        Under an active compute_dtype policy float inputs declare the
        COMPUTE dtype, so the dataloader casts in the host->device
        transfer (half the transfer bytes) and the in-step cast is a
        no-op."""
        out: Dict[str, Any] = {}
        for t in self.model.input_tensors:
            dt = t.dtype
            if self._mp_active and jnp.issubdtype(dt, jnp.floating):
                dt = self.compute_dtype
            out[t.name] = dt
        return out

    def shard_batch(self, batch: Dict[str, np.ndarray]):
        """Place a host batch on device(s), sharded over the data axis —
        the TPU analog of SingleDataLoader::next_batch's per-part copies
        (flexflow_dataloader.cc:649-740). Inputs are cast to their
        DECLARED tensor dtype (a bf16 model fed f32 numpy trains in bf16,
        like the reference loader honoring the region's type)."""
        declared = self.declared_input_dtypes
        multi = jax.process_count() > 1
        out = {}
        for k, v in batch.items():
            want = declared.get(k)
            if self.mesh is not None and multi:
                # multi-controller SPMD: each process holds ITS shard of
                # the global batch (global batch = concat over
                # processes); device_put cannot address remote devices —
                # this is the make_array_from_process_local_data path
                # SURVEY §7.7 prescribes for the loader
                if isinstance(v, jax.Array) \
                        and not v.is_fully_addressable:
                    # already a global array (loader/caller placed it);
                    # an eager cast is impossible here, so a declared-
                    # dtype mismatch must fail, not silently train wide
                    if want is not None and v.dtype != want:
                        raise TypeError(
                            f"input {k!r}: pre-placed global array has "
                            f"dtype {v.dtype}, declared {want}; place "
                            f"it with the declared dtype")
                    out[k] = v
                    continue
                host = np.asarray(v, dtype=want) if want is not None \
                    else np.asarray(v)
                out[k] = place_process_local(
                    host, batch_sharding(self.mesh, host.ndim))
                continue
            # single-pass conversion: asarray+astype would materialize
            # the batch twice on device per step; likewise a host batch
            # bound for a mesh is cast on HOST and device_put ONCE
            # straight to the sharding (jnp.asarray first would land it
            # on the default device and copy it again — the
            # host_to_device double-materialization, core/dataloader.py)
            if self.mesh is not None and not isinstance(v, jax.Array):
                host = np.asarray(v) if want is None \
                    else np.asarray(v, dtype=jnp.dtype(want))
                out[k] = jax.device_put(
                    host, batch_sharding(self.mesh, host.ndim))
                continue
            arr = jnp.asarray(v, dtype=want) if want is not None \
                else jnp.asarray(v)
            if self.mesh is not None:
                out[k] = jax.device_put(
                    arr, batch_sharding(self.mesh, arr.ndim))
            else:
                out[k] = arr
        return out


    def shard_batch_stacked(self, batches: List[Dict[str, np.ndarray]]):
        """Stack K host batches along a new leading (step) axis and place
        them on device for `train_step_multi` — the data axis moves to
        dim 1, the step axis stays unsharded (each scan iteration
        consumes one slice). Values that already live on device are
        stacked device-side (never round-tripped through the host — a
        device->host pull per dispatch would dwarf the dispatch cost the
        multi-step path exists to amortize)."""
        declared = self.declared_input_dtypes
        keys = batches[0].keys()
        out = {}
        multi = jax.process_count() > 1

        def stacked_sharding(ndim):
            # spec of one step-slice, shifted right past the step axis
            sh = batch_sharding(self.mesh, ndim - 1)
            spec = P(None, *sh.spec) if sh.spec else P()
            return NamedSharding(self.mesh, spec)

        for k in keys:
            vals = [b[k] for b in batches]
            want = declared.get(k)
            if multi and any(isinstance(v, jax.Array) for v in vals):
                # eager stack/device_put cannot place onto the global
                # mesh from one process; grouped dispatch over
                # pre-placed device batches is a single-process feature
                raise NotImplementedError(
                    "steps_per_dispatch over device-resident batches is "
                    "not supported in multi-process runs; pass host "
                    "numpy batches (each process's shard)")
            if all(isinstance(v, jax.Array) for v in vals):
                arr = jnp.stack([
                    v if want is None or v.dtype == want else v.astype(want)
                    for v in vals])
            else:
                stacked = np.stack([np.asarray(v) for v in vals])
                if self.mesh is not None and multi:
                    host = stacked.astype(want) if want is not None \
                        else stacked
                    out[k] = place_process_local(
                        host, stacked_sharding(host.ndim))
                    continue
                arr = jnp.asarray(stacked, dtype=want) if want is not None \
                    else jnp.asarray(stacked)
            if self.mesh is not None:
                out[k] = jax.device_put(arr, stacked_sharding(arr.ndim))
            else:
                out[k] = arr
        return out


def _stable_hash(s: str) -> int:
    """Deterministic string hash (Python's hash() is salted per-process)."""
    h = 2166136261
    for c in s.encode():
        h = ((h ^ c) * 16777619) & 0x7FFFFFFF
    return h
