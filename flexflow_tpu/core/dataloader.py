"""Data loading.

Reference: `SingleDataLoader` (python/flexflow_dataloader.cc:576-740) —
the full dataset lives in zero-copy host memory (attached numpy),
`next_batch` index-launches per-part GPU copies with per-part sample
offsets, `reset` rewinds. TPU-native equivalent: the dataset stays in
host numpy; `next_batch` device_puts the next slice sharded over the
mesh `data` axis (and, multi-host, assembles a global array from
process-local shards via jax.make_array_from_process_local_data).
"""

from __future__ import annotations

from typing import Dict, Iterator, Optional  # noqa: F401

import jax
import jax.numpy as jnp
import numpy as np

from ..parallel.sharding import batch_sharding, place_process_local


def host_to_device(host, mesh, dtype=None) -> jax.Array:
    """Host batch -> device array sharded over the mesh's data axis.
    The single place batches land on devices (native and Python paths).
    `dtype` casts IN the transfer (one materialization — a post-hoc
    astype would move the wide dtype and buffer it twice). Multi-
    controller SPMD: the host batch is this PROCESS's shard of the
    global batch (place_process_local)."""
    if mesh is not None and jax.process_count() > 1:
        h = np.asarray(host, dtype=dtype)
        return place_process_local(h, batch_sharding(mesh, h.ndim))
    if mesh is not None and not isinstance(host, jax.Array):
        # single-host sharded path: cast on HOST and device_put once,
        # straight to the sharding — `jnp.asarray` first would
        # materialize the batch on the default device and then copy it
        # a second time into the sharded layout (double transfer +
        # double buffering, every step)
        h = np.asarray(host) if dtype is None \
            else np.asarray(host, dtype=jnp.dtype(dtype))
        return jax.device_put(h, batch_sharding(mesh, h.ndim))
    arr = jnp.asarray(host, dtype=dtype)
    if mesh is not None:
        arr = jax.device_put(arr, batch_sharding(mesh, arr.ndim))
    return arr


class SingleDataLoader:
    """One loader per (input tensor, full dataset array) pair, mirroring
    the reference's per-tensor loaders; `DataLoaderSet` batches them."""

    def __init__(self, name: str, data: np.ndarray, batch_size: int,
                 mesh=None, shuffle: bool = False, seed: int = 0,
                 drop_last: bool = True, dtype=None):
        self.name = name
        self.data = np.asarray(data)
        self.batch_size = int(batch_size)
        self.mesh = mesh
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.dtype = dtype  # target device dtype; cast in the transfer
        self._rng = np.random.RandomState(seed)
        self._order = np.arange(len(self.data))
        self._pos = 0
        if shuffle:
            self._rng.shuffle(self._order)

    @property
    def num_samples(self) -> int:
        return len(self.data)

    @property
    def num_batches(self) -> int:
        n = self.num_samples // self.batch_size
        if not self.drop_last and self.num_samples % self.batch_size:
            n += 1
        return n

    def reset(self) -> None:
        self._pos = 0
        if self.shuffle:
            self._rng.shuffle(self._order)

    def next_batch(self):
        """Host slice -> device array sharded over the data axis."""
        if self._pos + self.batch_size > self.num_samples:
            if self.drop_last or self._pos >= self.num_samples:
                raise StopIteration
        sel = self._order[self._pos:self._pos + self.batch_size]
        self._pos += self.batch_size
        return host_to_device(self.data[sel], self.mesh, self.dtype)


class DataLoaderSet:
    """Batches several SingleDataLoaders in lockstep (inputs + label),
    the shape FFModel.fit consumes.

    When the native runtime is available the per-batch row gather runs
    on a C++ background thread (csrc/dataloader.cc), double-buffered so
    host gather overlaps device dispatch — the prefetch analog of the
    reference's next_batch index-launched copies
    (flexflow_dataloader.cc:649-740). The pure-Python path gets the
    same overlap from a Python worker thread (`_iter_prefetch`,
    `prefetch=False` opts out)."""

    def __init__(self, arrays: Dict[str, np.ndarray], batch_size: int,
                 mesh=None, shuffle: bool = True, seed: int = 0,
                 use_native: Optional[bool] = None,
                 dtypes: Optional[Dict] = None,
                 prefetch: bool = True):
        n = {len(v) for v in arrays.values()}
        assert len(n) == 1, "all arrays must have equal sample counts"
        # one shared shuffled order: shuffle once here, not per-loader
        self._order_rng = np.random.RandomState(seed)
        self.mesh = mesh
        # target device dtype per key (e.g. a bf16 model's declared input
        # dtypes): cast happens IN the host->device transfer, once
        self.dtypes = dict(dtypes or {})
        self.loaders = {
            k: SingleDataLoader(k, v, batch_size, mesh=mesh, shuffle=False,
                                dtype=self.dtypes.get(k))
            for k, v in arrays.items()
        }
        self.shuffle = shuffle
        self.batch_size = batch_size
        # pure-Python path overlap (parity with the native loader): a
        # background thread runs the per-batch row gathers one/two
        # batches ahead while the main thread does the host->device
        # transfer of the current one. prefetch=False is the escape
        # hatch (debugging, or hosts where a second thread hurts).
        self.prefetch = bool(prefetch)
        self._native = None
        if use_native is not False:
            from .. import native
            if native.available():
                from ..native.wrappers import NativePrefetchLoader
                self._native = NativePrefetchLoader(
                    {k: np.asarray(v) for k, v in arrays.items()},
                    batch_size, drop_last=True)
            else:
                assert use_native is not True, "native loader requested " \
                    "but the native library is unavailable"

    @property
    def num_batches(self) -> int:
        return next(iter(self.loaders.values())).num_batches

    def _epoch_order(self) -> np.ndarray:
        order = np.arange(next(iter(self.loaders.values())).num_samples)
        if self.shuffle:
            self._order_rng.shuffle(order)
        return order

    def _set_order(self, order: np.ndarray) -> None:
        for l in self.loaders.values():
            l._order = order
            l._pos = 0

    def reset(self) -> None:
        self._set_order(self._epoch_order())

    # ---------------- crash-safe loader state --------------------------
    def state_dict(self) -> dict:
        """Resumable shuffle-stream state: the shared order rng — the
        only stream that decides future epochs' permutations. The
        granularity is deliberately the EPOCH: a permutation already
        drawn for an in-progress epoch was consumed from the rng before
        this snapshot and is not recoverable from it, so save at epoch
        boundaries (mid-epoch resume replays the epoch from its start —
        the same contract as fit's checkpoint replay)."""
        s = self._order_rng.get_state()
        return {"rng": [s[0], np.asarray(s[1]).tolist(), int(s[2]),
                        int(s[3]), float(s[4])]}

    def load_state_dict(self, state: dict) -> None:
        # parse EVERYTHING before mutating anything: a malformed file
        # must leave the loader untouched (the load_state contract),
        # not half-applied with the rng already overwritten
        s = state["rng"]
        rng_state = (s[0], np.asarray(s[1], dtype=np.uint32), int(s[2]),
                     int(s[3]), float(s[4]))
        self._order_rng.set_state(rng_state)

    def save_state(self, path: str) -> None:
        """Checkpoint the loader state ATOMICALLY (temp then
        os.replace, core/checkpoint.atomic_write_json): a kill at any
        instant leaves either the previous complete state file or the
        new one, never a truncation — the same crash contract as
        save_checkpoint, so a restarted run replays the exact
        epoch-level shuffle stream of an uninterrupted one (see
        state_dict for the epoch granularity)."""
        from .checkpoint import atomic_write_json
        atomic_write_json(path, self.state_dict(),
                          fault_site="loader.commit")

    def load_state(self, path: str) -> bool:
        """Restore from save_state's file; False (state untouched) when
        the file is absent or unreadable."""
        import json
        try:
            with open(path) as f:
                state = json.load(f)
            self.load_state_dict(state)
        except (OSError, ValueError, KeyError, TypeError):
            return False
        return True

    def close(self) -> None:
        """Release the native worker thread + double buffers (no-op on
        the Python path). Safe to call more than once."""
        if self._native is not None:
            self._native.close()
            self._native = None

    def __iter__(self) -> Iterator[Dict[str, jax.Array]]:
        return self.iter_with_order(self._epoch_order())

    def iter_with_order(self, order: np.ndarray
                        ) -> Iterator[Dict[str, jax.Array]]:
        """Iterate one epoch in an EXPLICIT sample order — lets a caller
        that owns the shuffle stream (fit()'s checkpoint-replayable
        permutations) still ride the native double-buffered prefetch."""
        order = np.asarray(order)
        n = next(iter(self.loaders.values())).num_samples
        assert len(order) == n, (  # native path asserts the same
            f"order has {len(order)} entries for {n} samples")
        if self._native is not None:
            self._native.start_epoch(order)
            while True:
                batch = self._native.next_batch()
                if batch is None:
                    return
                # explicit copy: jax may alias aligned host memory, and
                # the worker reuses the double buffer after the next
                # next_batch call
                yield {k: host_to_device(np.array(v, copy=True), self.mesh,
                                         self.dtypes.get(k))
                       for k, v in batch.items()}
        elif self.prefetch and self.num_batches > 1:
            yield from self._iter_prefetch(order)
        else:
            # iterator-LOCAL slicing: the shared loaders' cursors are
            # left untouched, so overlapping epoch iterators (or direct
            # loader users) never see each other's position
            bs = self.batch_size
            for i in range(self.num_batches):
                sel = order[i * bs:(i + 1) * bs]
                yield {k: host_to_device(l.data[sel], self.mesh, l.dtype)
                       for k, l in self.loaders.items()}

    def _iter_prefetch(self, order: np.ndarray
                       ) -> Iterator[Dict[str, jax.Array]]:
        """Double-buffered pure-Python epoch: a background thread runs
        the fancy-indexed row gathers AND (single-process runs) the
        cast + host->device transfer up to two batches ahead of the
        main thread — the same gather/transfer overlap the native
        loader gets from its C++ worker (csrc/dataloader.cc), minus the
        shared buffer (each gather is a fresh array, so nothing here
        can alias a batch the consumer still holds).

        Staging on the worker matters because CONSECUTIVE DONATED
        dispatches synchronize on the CPU/TPU runtime (the next step
        cannot alias the previous step's output buffer until it
        exists), so the main thread's dispatch call blocks for most of
        the device step — host work only overlaps device compute if it
        happens on another thread. This is the loader half of the
        async training runtime (core/overlap.py has the dispatch-window
        half; tools/train_bench.py measures the two together). A
        multi-process mesh keeps staging on the main thread:
        place_process_local is a collective-addressing operation the
        worker must not race.

        Batch ORDER and CONTENT are byte-identical to the synchronous
        path: the worker walks the same `order` slices through the same
        host_to_device, and the bounded queue only changes WHEN a batch
        is staged, not what it reads."""
        import queue
        import threading
        bs = self.batch_size
        q: "queue.Queue" = queue.Queue(maxsize=2)   # the double buffer
        stop = threading.Event()
        stage_on_worker = jax.process_count() == 1

        def gather() -> None:
            try:
                for i in range(self.num_batches):
                    if stop.is_set():
                        return
                    sel = order[i * bs:(i + 1) * bs]
                    batch = {k: l.data[sel]
                             for k, l in self.loaders.items()}
                    if stage_on_worker:
                        batch = {k: host_to_device(
                            v, self.mesh, self.dtypes.get(k))
                            for k, v in batch.items()}
                    q.put(batch)
                q.put(None)                          # end of epoch
            except BaseException as e:               # surface in consumer
                q.put(e)

        worker = threading.Thread(target=gather, daemon=True,
                                  name="ff-dataloader-prefetch")
        worker.start()
        try:
            while True:
                item = q.get()
                if item is None:
                    break
                if isinstance(item, BaseException):
                    raise item
                if stage_on_worker:
                    yield item
                else:
                    yield {k: host_to_device(v, self.mesh,
                                             self.dtypes.get(k))
                           for k, v in item.items()}
        finally:
            # abandoned iterator (break / exception): unblock a worker
            # parked on the full queue, then reap it
            stop.set()
            try:
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass
            worker.join(timeout=5.0)


def synthetic_inputs(model, n_samples: int, seed: int = 0,
                     int_high: int = 10) -> Dict[str, np.ndarray]:
    """Synthetic input arrays (n_samples rows) matching the model's
    declared input tensors (reference: syntheticInput when no --dataset,
    alexnet.cc:100-104). Integer tensors get uniform ints in
    [0, int_high); float tensors get standard normals in their dtype."""
    rng = np.random.RandomState(seed)
    x = {}
    for t in model.input_tensors:
        shape = (n_samples,) + tuple(t.shape[1:])
        if jnp.issubdtype(t.dtype, jnp.integer):
            x[t.name] = rng.randint(0, int_high, shape).astype(np.int32)
        else:
            x[t.name] = rng.randn(*shape).astype(np.dtype(t.dtype).name)
    return x


def synthetic_batch(model, label_classes: int = 10, seed: int = 0
                    ) -> Dict[str, np.ndarray]:
    """One synthetic batch (batch-size rows) incl. integer labels."""
    bs = model.input_tensors[0].shape[0]
    batch = synthetic_inputs(model, bs, seed)
    rng = np.random.RandomState(seed + 1)
    batch["label"] = rng.randint(0, label_classes, bs).astype(np.int32)
    return batch
