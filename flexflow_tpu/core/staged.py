"""StagedExecutor: pipelined execution of arbitrary op graphs.

The executable lowering of whole-op device placement (reference
FFMapper::slice_task routing ops to ParallelConfig.device_ids,
/root/reference/src/mapper/mapper.cc:346-440) and of pipeline
parallelism over non-uniform graphs (SURVEY §7 hard part (c)). The op
graph is cut into S stages (from strategy pins or flops-balanced
auto-cut); parameters flat-pack into per-stage rows sharded over the
mesh `pipe` axis (real per-device weight residency); forward runs the
GPipe microbatch schedule (parallel/graph_pipeline.py).

Inherits every step builder from Executor — only parameter layout
(init_state), the loss-bearing forward (_outputs_and_loss), and the
weight-access hooks change. Elementwise optimizers (SGD/Adam) update
the packed rows directly, so optimizer state is stage-resident too.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import initializers as I
from .executor import Executor, _stable_hash
from ..parallel.graph_pipeline import (
    PackSpec,
    StagePlan,
    build_stage_plan,
    make_pack_spec,
    pack_params,
    pipeline_1f1b_grads,
    pipeline_logits,
    pipeline_logits_interleaved,
    read_op_weights,
    write_op_weights,
)

PACKED = "__stages__"
STATE_PACKED = "__stage_state__"


class StagedExecutor(Executor):
    def __init__(self, model, optimizer, loss_fn, metric_names,
                 mesh: Mesh, strategy, comp_mode: str,
                 stage_of: Dict[str, int], pipe_axis: str,
                 num_microbatches: int, schedule: str = "gpipe"):
        if mesh is None or pipe_axis not in mesh.shape:
            raise ValueError(
                f"staged execution needs a mesh axis to pipeline over; "
                f"got axis {pipe_axis!r} in {mesh}")
        n_stages = max(stage_of.values()) + 1
        n_dev = int(mesh.shape[pipe_axis])
        if n_stages % n_dev != 0:
            raise ValueError(
                f"stage count {n_stages} does not divide over the "
                f"{pipe_axis!r} axis size {n_dev}")
        self.virtual_stages = n_stages // n_dev
        if self.virtual_stages > 1 and schedule != "1f1b":
            raise ValueError(
                f"{n_stages} stages over {n_dev} devices = interleaved "
                f"execution, which requires the 1f1b schedule")
        self.pipe_axis = pipe_axis
        self.num_microbatches = int(num_microbatches)
        if schedule not in ("gpipe", "1f1b"):
            raise ValueError(f"unknown pipeline schedule {schedule!r}")
        self.schedule = schedule
        super().__init__(model, optimizer, loss_fn, metric_names,
                         mesh=mesh, strategy=strategy,
                         comp_mode=comp_mode)
        # stages run ops with ctx.mesh=None, so a per-table embedding
        # placement (which super().__init__ may have lowered into the
        # padded slot layout, mutating weight_specs) cannot execute —
        # reset BEFORE freezing the pack layout, or the packing would
        # record pre-/post-placement shapes inconsistently
        from ..ops.embedding import DistributedEmbedding
        for op in model.ops:
            if isinstance(op, DistributedEmbedding) \
                    and op.placement is not None:
                import warnings
                warnings.warn(
                    f"{op.name}: per-table device placement is ignored "
                    f"under staged (pipelined) execution; tables run "
                    f"plainly stacked inside their stage")
                op.apply_placement(None, None)
        self.plan: StagePlan = build_stage_plan(model, stage_of)
        # ZeRO-1 under staging: pad row length to the data-axis size so
        # the optimizer slot rows' L dimension shards cleanly over it
        from .executor import zero_applicable
        zero_requested = getattr(model.config,
                                 "zero_optimizer_sharding", False)
        self._zero = zero_applicable(model.config, mesh)
        if zero_requested and not self._zero:
            import warnings
            warnings.warn(
                "--zero has no effect on this mesh: no `data` axis of "
                "size > 1 to shard optimizer slots over (slots remain "
                "stage-resident only)")
        self.pack: PackSpec = make_pack_spec(
            self.plan, n_dev=int(mesh.shape[pipe_axis]),
            pad_to=(int(mesh.shape["data"]) if self._zero else 1))
        # functional state (BatchNorm running stats) packs into its own
        # per-stage rows; BOTH schedules advance them per microbatch in
        # order (gradient-accumulation semantics) — fwd ticks run
        # outside 1F1B's vjp, whose recompute reads state as a
        # constant. That is only sound when the training output ignores
        # state_in (Op.training_output_reads_state declares it).
        stateful = [op.name for op in model.ops if op.state_specs()]
        if schedule == "1f1b":
            reads = [op.name for op in model.ops
                     if op.state_specs()
                     and op.training_output_reads_state]
            if reads:
                raise NotImplementedError(
                    f"ops {reads} read their functional state in the "
                    f"training forward; 1F1B's backward recompute "
                    f"would see later-microbatch state — use "
                    f"pipeline_schedule='gpipe'")
        self.state_pack: Optional[PackSpec] = (
            make_pack_spec(self.plan, n_dev=int(mesh.shape[pipe_axis]),
                           specs_of=lambda op: op.state_specs())
            if stateful else None)

    # The sparse-embedding fast path gathers rows outside the
    # differentiated region — incompatible with packed stage rows.
    # Dense gradients through the pipeline are always correct.
    def _sparse_table_ops(self) -> Dict:
        self._sparse_ops_cache = {}
        return {}

    # ---------------- state ----------------
    def init_state(self, rng):
        by_op: Dict[str, Dict[str, np.ndarray]] = {}
        for op in self.model.ops:
            wspecs = op.weight_specs()
            if not wspecs:
                continue
            op_params = {}
            for wname, spec in wspecs.items():
                key = jax.random.fold_in(
                    jax.random.fold_in(rng, _stable_hash(op.name)),
                    _stable_hash(wname))
                init_fn = spec.custom_init or I.resolve(spec.initializer)
                if spec.fan_in is not None or spec.fan_out is not None:
                    arr = init_fn(key, spec.shape, spec.dtype,
                                  fan_in=spec.fan_in, fan_out=spec.fan_out)
                else:
                    arr = init_fn(key, spec.shape, spec.dtype)
                op_params[wname] = np.asarray(arr)
            by_op[op.name] = op_params
        packed_host = pack_params(self.pack, by_op)
        packed = {dt: self._place_packed(a)
                  for dt, a in packed_host.items()}
        params = {PACKED: packed}
        states = {}
        if self.state_pack is not None:
            st_by_op = {}
            for op in self.model.ops:
                sspecs = op.state_specs()
                if sspecs:
                    st_by_op[op.name] = {
                        sname: np.full(spec.shape, spec.init_value,
                                       np.dtype(spec.dtype))
                        for sname, spec in sspecs.items()}
            st_host = pack_params(self.state_pack, st_by_op)
            states = {STATE_PACKED: {dt: self._place_packed(a)
                                     for dt, a in st_host.items()}}
        opt_state = (self.optimizer.init_state(params)
                     if self.optimizer and self.comp_mode != "inference"
                     else {})
        # optimizer slots mirror the packed rows — stage-resident via
        # the pipe axis, and with --zero ALSO sharded over the data
        # axis on the (padded) L dimension: (pipe, data) slot layout =
        # 1/(pp*dp) optimizer memory per chip. The update's sharding
        # constraint (base _apply_update) keeps them there.
        from ..parallel.sharding import place_global
        slot_sharding = (self._zero_sharding() if self._zero
                         else self._packed_sharding())
        opt_state = jax.tree_util.tree_map(
            lambda a: place_global(np.asarray(a), slot_sharding),
            opt_state)
        self._opt_shardings = (jax.tree_util.tree_map(
            lambda a: slot_sharding, opt_state)
            if self._zero and opt_state else None)
        from .executor import TrainState
        return TrainState(params, states, opt_state, self._init_step())

    def _packed_sharding(self):
        return NamedSharding(self.mesh, P(self.pipe_axis, None))

    def _zero_sharding(self):
        """(pipe, data) layout for optimizer slot rows under --zero:
        stage-resident AND data-sharded (L padded to divide)."""
        return NamedSharding(self.mesh, P(self.pipe_axis, "data"))

    def _place_packed(self, host):
        from ..parallel.sharding import place_global
        return place_global(np.asarray(host), self._packed_sharding())

    # ---------------- gradients ----------------
    def _compute_grads(self, params, states, batch, rng):
        """1F1B computes gradients explicitly inside the pipelined tick
        loop (per-stage vjp recompute, cotangents riding the reverse
        ring); GPipe differentiates the forward schedule (base class).
        Same returned contract either way."""
        if self.schedule != "1f1b":
            return super()._compute_grads(params, states, batch, rng)
        inputs = {t.name: batch[t.name]
                  for t in self.model.input_tensors}
        label = batch.get("label")
        logits, aux, packed_grads, st = pipeline_1f1b_grads(
            self.plan, self.pack, params[PACKED], inputs, label,
            self.loss_fn, rng, self.mesh, self.pipe_axis,
            self._data_axis(), self.num_microbatches, self.model,
            seq_length=self.config.iter_config.seq_length,
            state_pack=self.state_pack,
            state_packed=states.get(STATE_PACKED))
        new_states = ({STATE_PACKED: st} if st is not None
                      else dict(states))
        loss = jnp.asarray(0.0, jnp.float32)
        if self.loss_fn is not None and label is not None:
            loss = self.loss_fn(logits, label)
        loss = loss + aux
        return loss, logits, new_states, {PACKED: packed_grads}, {}

    # ---------------- forward/loss ----------------
    def _outputs_and_loss(self, params, states, batch, training, rng,
                          seq_length):
        inputs = {t.name: batch[t.name] for t in self.model.input_tensors}
        if self.virtual_stages > 1:
            # forward-only interleaved schedule: same round-robin
            # stage->device layout + device-major packed rows the 1F1B
            # training path uses
            logits, aux = pipeline_logits_interleaved(
                self.plan, self.pack, params[PACKED], inputs, rng,
                self.mesh, self.pipe_axis, self._data_axis(),
                self.num_microbatches, self.model, training=training,
                seq_length=seq_length, state_pack=self.state_pack,
                state_packed=states.get(STATE_PACKED))
        else:
            logits, aux, st = pipeline_logits(
                self.plan, self.pack, params[PACKED], inputs, rng,
                self.mesh, self.pipe_axis, self._data_axis(),
                self.num_microbatches, self.model, training=training,
                seq_length=seq_length, schedule="gpipe",
                state_pack=self.state_pack,
                state_packed=states.get(STATE_PACKED))
            if st is not None:
                states = {STATE_PACKED: st}
        loss = jnp.asarray(0.0, jnp.float32)
        if self.loss_fn is not None and "label" in batch:
            loss = self.loss_fn(logits, batch["label"])
        loss = loss + aux
        return loss, (logits, dict(states))

    def _data_axis(self) -> Optional[str]:
        return "data" if "data" in self.mesh.shape else None

    # ------- weight/state access hooks (model.get/set_weights/states)
    # weights and functional state share one marshalling path: fetch
    # the packed rows to host, read/write the op's segments, re-place
    def _read_packed(self, pack, packed, op_name, what):
        if pack is None:
            raise KeyError(f"op {op_name!r} has no {what}")
        host = {dt: np.asarray(jax.device_get(a))
                for dt, a in packed.items()}
        out = read_op_weights(pack, host, op_name)
        if not out:
            raise KeyError(f"op {op_name!r} has no {what}")
        return out

    def _write_packed(self, pack, packed, op_name, values, what):
        if pack is None:
            raise KeyError(f"op {op_name!r} has no {what}")
        host = {dt: np.asarray(jax.device_get(a))
                for dt, a in packed.items()}
        new_host = write_op_weights(pack, host, op_name, values)
        return {dt: self._place_packed(a) for dt, a in new_host.items()}

    def get_op_weights(self, state, op_name: str):
        return self._read_packed(self.pack, state.params[PACKED],
                                 op_name, "weights")

    def set_op_weights(self, state, op_name: str, weights) -> None:
        state.params[PACKED] = self._write_packed(
            self.pack, state.params[PACKED], op_name, weights,
            "weights")

    def get_op_states(self, state, op_name: str):
        """Per-op view of functional state (BN running stats) out of
        the packed stage rows."""
        return self._read_packed(
            self.state_pack, state.states.get(STATE_PACKED, {}),
            op_name, "functional state")

    def set_op_states(self, state, op_name: str, values) -> None:
        state.states[STATE_PACKED] = self._write_packed(
            self.state_pack, state.states.get(STATE_PACKED, {}),
            op_name, values, "functional state")

    def get_op_opt_slots(self, state, op_name: str):
        """Per-op view of optimizer slots (packed layout mirrors
        params)."""
        out = {}
        for slot, tree in state.opt_state.items():
            host = {dt: np.asarray(jax.device_get(a))
                    for dt, a in tree[PACKED].items()}
            out[slot] = read_op_weights(self.pack, host, op_name)
        return out
