"""Training services: initializers, optimizers, losses, metrics, executor,
dataloader — TPU-native equivalents of reference src/runtime/{initializer,
optimizer}.cc, src/loss_functions/, src/metrics_functions/,
python/flexflow_dataloader.cc."""
