"""Mixed-precision policy helpers.

The policy (FFConfig.compute_dtype / param_dtype) is the loss-scaling-
free bf16 recipe TPUs are built for: float parameters and optimizer
state live in `param_dtype` (f32 master weights by default), and the
jitted step casts params + float activations to `compute_dtype` on the
way in — bf16 matmuls ride the MXU at ~2x the f32 rate while halving
HBM and collective bytes. Gradients flow back through the cast (the
cast's transpose upcasts cotangents), so the optimizer applies f32
updates to f32 masters and bf16's ~8-bit mantissa never accumulates
into the weights. What stays f32 inside the step: softmax/logsumexp,
losses, metrics, BN/LN statistics, and matmul accumulators
(`preferred_element_type` — the flash-attention convention; bf16 needs
no loss scaling because its exponent range equals f32's).

No reference analog: FlexFlow trains f32 end-to-end (DATA_TYPE floats,
include/config.h). This module is deliberately tiny and dependency-free
(config.py imports it during validation).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# dtypes accepted as a step compute/param dtype. f64 excluded: jax
# demotes it without jax_enable_x64 and the cost model has no peak for
# it; f16 included for GPU-backend experiments (bf16 is the TPU dtype).
_FLOAT_DTYPES = ("float32", "bfloat16", "float16")


def resolve_dtype(value, knob: str = "dtype"):
    """Normalize a user-supplied dtype (string, np/jnp dtype, or type)
    to a jnp.dtype, rejecting anything outside the float policy set."""
    try:
        dt = jnp.dtype(value)
    except TypeError as e:
        raise ValueError(f"{knob}: unparseable dtype {value!r}") from e
    if dt.name not in _FLOAT_DTYPES:
        raise ValueError(
            f"{knob} must be one of {_FLOAT_DTYPES}, got {dt.name!r}")
    return dt


def policy_active(config) -> bool:
    """True when the step must cast (compute_dtype != f32). The f32
    default is the no-op fast path: models that opt into bf16 via
    builder `dtype=` arguments (activation-dtype mixed precision) keep
    their exact pre-policy numerics."""
    return jnp.dtype(getattr(config, "compute_dtype", jnp.float32)) \
        != jnp.float32


def is_float_array(x) -> bool:
    return hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)


def cast_floats(tree, dtype):
    """Cast every floating leaf of a pytree to `dtype` (non-float
    leaves — int indices, bool masks — pass through untouched). Inside
    a differentiated function the cast is autodiff-transparent: its
    transpose casts cotangents back up, which is exactly how bf16
    gradients land in the f32 master update."""
    dtype = jnp.dtype(dtype)

    def cast(x):
        if is_float_array(x) and x.dtype != dtype:
            return x.astype(dtype)
        return x

    return jax.tree_util.tree_map(cast, tree)
