"""Async/overlap training runtime: bucketed backward-overlapped gradient
sync + the pipelined host-dispatch window.

The reference FlexFlow's core bet was an async task runtime (Legion)
that hides communication behind compute; our training path compiled to
ONE jitted step whose data-parallel gradient all-reduces XLA was free to
sink into a single combined sync after the whole backward pass. This
module makes the overlap structural:

* ``grad_buckets`` partitions the walk's weighted ops into contiguous
  buckets by cumulative master-parameter bytes (``FFConfig.
  grad_bucket_mb``; 0 = legacy monolithic sync). The SAME partition
  function feeds the executor's sync points and the simulator's
  bucket-granular sync tasks, so the MCMC search prices exactly the
  overlap the executor delivers.

* ``make_bucket_tagger`` builds the sync-point op threaded through the
  differentiated region: a ``custom_vjp`` identity over the bucketed
  parameter subtree whose BACKWARD rule walls each bucket's weight
  cotangents behind an ``optimization_barrier`` the moment they are
  complete, chaining buckets in backward-completion order through a
  data token. Forward and backward are identities, so gradients stay
  BIT-identical to the monolithic path (same reduction set, donation
  untouched); what changes is the HLO structure XLA schedules: each
  bucket's data-axis all-reduce is anchored at its bucket boundary
  inside the backward pass instead of being free to coalesce into one
  end-of-backward sync, so it runs concurrently with the remaining
  backward compute.

* ``DispatchWindow`` is the host half: a depth-N in-flight window over
  dispatched step results (``FFConfig.train_dispatch_depth``) so the
  fit loop retrieves step N's host-side metrics while step N+1 runs on
  device — the host never sits in a blocking fetch for the NEWEST
  dispatch except at epoch/checkpoint boundaries, and device-side
  metric handles stay bounded instead of accumulating for a whole
  epoch.
"""

from __future__ import annotations

import collections
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp


def eligible_sparse_ops(model) -> set:
    """Names of embedding-family ops the executor routes through the
    sparse row-update path (mirror of ``Executor._sparse_table_ops``,
    shared so the simulator's bucket partition matches the executor's
    without holding an executor). Before compile() assigns an optimizer
    the set is empty — the conservative (dense) reading the cost model
    already uses."""
    from ..ops.embedding import DistributedEmbedding, Embedding
    cfg = model.config
    opt = getattr(model, "optimizer", None)
    mode = None
    if opt is not None:
        try:
            mode = opt.sparse_mode()
        except Exception:
            mode = None
    allowed = mode == "exact" or (
        mode == "lazy" and getattr(cfg, "sparse_embedding_lazy", False))
    out = set()
    if getattr(cfg, "sparse_embedding_updates", True) and allowed:
        input_uids = {t.uid for t in model.input_tensors}
        for op in model.ops:
            if isinstance(op, (Embedding, DistributedEmbedding)) \
                    and all(t.uid in input_uids for t in op.inputs):
                out.add(op.name)
    return out


# auto_bucket_mb bounds: never fewer than one bucket or more than this
# many (beyond ~32 the per-bucket launch latency dominates any overlap
# win), and never a bucket outside [1, 64] MiB (below 1 MiB a v5-class
# all-reduce is pure latency; above 64 MiB the last bucket's sync can
# no longer hide behind any remaining backward).
AUTO_MAX_BUCKETS = 32
AUTO_MIN_MB = 1.0
AUTO_MAX_MB = 64.0
# fraction of the estimated backward time the per-bucket launch
# latencies may consume before we stop splitting finer
AUTO_LATENCY_FRACTION = 0.1


def auto_bucket_mb(model, mesh=None, machine=None) -> float:
    """Machine-model-derived gradient-sync bucket size, used when
    FFConfig.grad_bucket_mb is unset (None = auto).

    The granularity trade is bandwidth-vs-latency: the TOTAL sync bytes
    and the total backward compute are fixed, so splitting finer only
    adds per-bucket all-reduce launch latency while anchoring syncs
    earlier in the backward. We size buckets from the machine model —
    effectively interconnect bandwidth x the expected backward slice a
    bucket must hide under: estimate the backward time (2x forward
    FLOPs at the calibrated MXU rate), allow AUTO_LATENCY_FRACTION of
    it for per-bucket launch latency (2(a-1) ICI hops per ring
    all-reduce), split the dense master bytes into that many buckets,
    and floor each bucket at the interconnect's bandwidth-latency
    product (a smaller bucket's all-reduce is pure latency — nothing
    for the backward to overlap). No data axis (or no dense weights)
    resolves to 0 = monolithic: there is no sync to overlap.

    Deterministic for a given (model, mesh): the executor (real step)
    and the simulator (search pricing) both resolve through
    resolve_bucket_mb, so they partition identically and the resolved
    value — not the None sentinel — folds into the cost-cache machine
    fingerprint."""
    data = int(mesh.shape.get("data", 1)) if mesh is not None else 1
    if data <= 1:
        return 0.0
    sparse = eligible_sparse_ops(model)
    total_bytes = sum(
        float(op.weight_bytes()) for op in model.ops
        if op.name not in sparse and op.weight_specs()
        and op.weight_bytes() > 0)
    if total_bytes <= 0:
        return 0.0
    if machine is None:
        from ..search.machine_model import default_machine_model
        machine = default_machine_model(mesh)
    eff = machine.efficiency.get("matmul", 0.5)
    t_bwd = 2.0 * sum(float(op.flops()) for op in model.ops) \
        / max(machine.peak_flops_for(None) * eff, 1.0)
    per_bucket_lat = 2.0 * (data - 1) * machine.spec.ici_latency
    n = max(1, min(AUTO_MAX_BUCKETS,
                   int(AUTO_LATENCY_FRACTION * t_bwd
                       / max(per_bucket_lat, 1e-12))))
    bw = machine.spec.ici_bandwidth \
        * machine.efficiency.get("collective", 0.75)
    floor_bytes = bw * per_bucket_lat   # bandwidth-latency product
    bucket_bytes = max(total_bytes / n, floor_bytes)
    return float(min(max(bucket_bytes / (1 << 20), AUTO_MIN_MB),
                     AUTO_MAX_MB))


def resolve_bucket_mb(config, model, mesh=None, machine=None) -> float:
    """The ONE resolution point for FFConfig.grad_bucket_mb: explicit
    values (including 0 = monolithic) are authoritative; None
    auto-tunes from the machine model (auto_bucket_mb). Both the
    executor's sync-point partition and the simulator's bucket pricing
    — and the cost-cache fingerprint — use the value returned here."""
    raw = getattr(config, "grad_bucket_mb", None)
    if raw is not None:
        return float(raw)
    try:
        return auto_bucket_mb(model, mesh=mesh, machine=machine)
    except Exception:
        # a half-built model (no ops yet) or an exotic mesh must not
        # break compile — fall back to the legacy monolithic sync
        return 0.0


def grad_buckets(model, bucket_mb: float,
                 sparse_ops: Optional[set] = None
                 ) -> List[Tuple[List[str], float]]:
    """Walk-order contiguous gradient-sync buckets.

    Returns ``[(member op names, master-param bytes), ...]`` over the
    ops that contribute DENSE float gradients to the data-parallel sync
    (weighted ops minus the sparse-update tables, whose row gradients
    scatter outside the bucketed reduction). A bucket closes once its
    cumulative ``op.weight_bytes()`` (the f32-declared master basis —
    strategy-independent, so executor and simulator always agree)
    reaches ``bucket_mb`` MiB. ``bucket_mb <= 0`` returns [] (legacy
    monolithic sync)."""
    if bucket_mb is None or bucket_mb <= 0:
        return []
    if sparse_ops is None:
        sparse_ops = eligible_sparse_ops(model)
    limit = float(bucket_mb) * (1 << 20)
    buckets: List[Tuple[List[str], float]] = []
    cur: List[str] = []
    cur_bytes = 0.0
    for op in model.ops:
        if op.name in sparse_ops or not op.weight_specs():
            continue
        w = float(op.weight_bytes())
        if w <= 0:
            continue
        cur.append(op.name)
        cur_bytes += w
        if cur_bytes >= limit:
            buckets.append((cur, cur_bytes))
            cur, cur_bytes = [], 0.0
    if cur:
        buckets.append((cur, cur_bytes))
    return buckets


def make_bucket_tagger(buckets: Sequence[Sequence[str]]):
    """Build the per-step gradient sync-point op: ``tag(subtree)`` is an
    identity over ``{op_name: {weight_name: array}}`` whose backward
    groups each bucket's cotangents behind an ``optimization_barrier``,
    chained bucket-to-bucket in backward-completion order (reverse walk
    order) through a scalar token so XLA can neither merge the buckets'
    all-reduces into one end-of-backward sync nor reorder them past each
    other. Values pass through untouched — gradients are bit-identical
    to the untagged walk."""
    order = [tuple(b) for b in buckets]

    @jax.custom_vjp
    def tag(tree):
        return tree

    def _fwd(tree):
        return tree, None

    def _bwd(_, ct):
        out = dict(ct)
        # the token is DATA-dependent on every earlier (in backward
        # order) bucket's cotangents: each barrier's outputs depend on
        # all its inputs, so feeding bucket k's token into bucket k-1's
        # barrier pins the issue order to grad-completion order.
        token = jnp.zeros((), jnp.float32)
        for bucket in reversed(order):
            names = [n for n in bucket if n in out]
            if not names:
                continue
            sub = {n: out[n] for n in names}
            sub, token = jax.lax.optimization_barrier((sub, token))
            out.update(sub)
        return (out,)

    tag.defvjp(_fwd, _bwd)
    return tag


class DispatchWindow:
    """Depth-N in-flight window over dispatched train-step results.

    ``push(entry)`` records one dispatch's (device-array) result; once
    more than ``depth - 1`` results are un-retrieved, the OLDEST is
    pulled to host (``jax.device_get``) — blocking at most on a step
    that is already ``depth - 1`` dispatches behind the newest, which
    the device has typically long finished. So:

      depth 1  -> fully synchronous (fetch right after each dispatch;
                  the legacy blocking loop, train_bench's sync arm)
      depth 2  -> retrieve step N while step N+1 runs (the default)
      depth 0  -> unbounded (never fetch until drain(); the old
                  epoch-bulk behavior — device handles grow with the
                  epoch)

    ``drain()`` fetches everything left (epoch/checkpoint boundaries,
    and the fit loop's finally on a mid-epoch fault) and returns the
    retrieved entries in push order. ``fetch_waits_s`` records the host
    time spent blocked in each fetch — the number train_report turns
    into dispatch-gap statistics."""

    def __init__(self, depth: int, telemetry=None):
        self.depth = max(0, int(depth))
        self._pending: collections.deque = collections.deque()
        self._done: List = []
        self.fetch_waits_s: List[float] = []
        self.max_in_flight = 0
        # optional utils/telemetry bus: each fetch becomes a span on
        # the ("train", "fetch") track — the host time blocked on a
        # device result, next to fit's dispatch spans
        self._telemetry = telemetry

    def _fetch_oldest(self) -> None:
        entry = self._pending.popleft()
        t0 = time.perf_counter()
        self._done.append(jax.device_get(entry))
        t1 = time.perf_counter()
        self.fetch_waits_s.append(t1 - t0)
        if self._telemetry is not None and self._telemetry.enabled:
            self._telemetry.span(("train", "fetch"), "fetch_wait",
                                 t0, t1)

    def push(self, entry) -> None:
        self._pending.append(entry)
        if len(self._pending) > self.max_in_flight:
            self.max_in_flight = len(self._pending)
        if self.depth > 0:
            while len(self._pending) > self.depth - 1:
                self._fetch_oldest()

    def pending(self) -> int:
        return len(self._pending)

    def drain(self) -> List:
        while self._pending:
            self._fetch_oldest()
        out = self._done
        self._done = []
        return out
