"""ProgramRegistry: one owner for every jitted program in the system.

The stack's zero-recompile discipline used to be enforced ad hoc per
subsystem — serve's fixed-shape mixed program snapshotted a process-wide
jax.monitoring counter around each call, the executor cached jitted
train steps on attributes, and `compile_counts()` was the max of two
imperfect proxies (monitoring events and distinct shape signatures).
None of that helped a COLD replica: an autoscaler scale-up with no
parked replica, or a cross-process fabric worker, pays the full
first-request compile storm.

This module factors the discipline into one object:

- ``register(name, static_argnums=...)`` declares a program family
  (serve's "mixed"/"export"/..., the executor's "train_step[...]").
- ``call(name, fn, *args)`` resolves the family + argument signature to
  a compiled executable: cache hit -> dispatch, miss -> AOT
  ``fn.lower(*args).compile()`` (timed, counted) then dispatch. The
  count is EXACT per family — a compile cannot hide from it the way it
  could from the monitoring snapshot (e.g. compiles triggered inside
  warmup_handoff / adapter load on a jax without the monitoring module).
- ``save(dir)`` / ``load_warm()`` serialize the compiled executables
  (``jax.experimental.serialize_executable``) keyed by a program
  FINGERPRINT folding model arch, lane widths, kv dtype/pool geometry,
  adapter rank/slots, tp degree and jax/backend version — a cold
  process deserializes its programs before the first request and boots
  warm (compile_counts() == 0). Corrupt/truncated stores warn and fall
  back to compiling, mirroring search/cost_cache.py's corrupt-store
  discipline; a restored executable that rejects its first call (stale
  cache from an incompatible runtime) is dropped and recompiled with a
  warning, never crashing the engine.

When a cache dir is armed the registry also points JAX's persistent
compilation cache at ``<dir>/xla`` (best-effort) — the belt under the
AOT braces: even a program the snapshot missed compiles from the XLA
disk cache instead of from scratch.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import time
import warnings
from typing import Any, Dict, Optional

import jax

_STORE_VERSION = 1
_STORE_SUFFIX = ".ffprog"

# jax_compilation_cache_dir is process-global config: arm it once, for
# the first registry that asks, and leave it alone after (two engines
# with different dirs must not thrash the global)
_xla_cache_armed = False


def fingerprint_hash(fp: Dict[str, Any]) -> str:
    """Stable short hash of a fingerprint dict (the cost_cache.py
    machine_fingerprint idiom): canonical-JSON then sha256."""
    blob = json.dumps(fp, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def _leaf_sig(leaf) -> tuple:
    """Signature of one flattened argument leaf. Arrays key on
    (shape, dtype, weak_type, sharding spec) — what jit's own cache
    keys on, minus the committed-device identity (a host numpy array
    and an uncommitted device array lower identically). Non-array
    leaves (static python scalars like the export/import n_pools) key
    on their VALUE, exactly as static_argnums demands."""
    if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
        sh = getattr(leaf, "sharding", None)
        spec = getattr(sh, "spec", None)
        if spec is None:
            tok = ""
        else:
            # trailing None entries are implicit (PartitionSpec('x',)
            # == PartitionSpec('x', None) to jit) — strip them so
            # equivalent shardings key identically
            t = tuple(spec)
            while t and t[-1] is None:
                t = t[:-1]
            tok = str(t)
        return ("a", tuple(leaf.shape), str(leaf.dtype),
                bool(getattr(leaf, "weak_type", False)), tok)
    return ("s", repr(leaf))


class ProgramRegistry:
    """Shape signatures, compile counting and AOT executable caching
    for a set of named program families (one registry per engine /
    executor; families are e.g. serve's six serving functions)."""

    def __init__(self, fingerprint: Dict[str, Any],
                 cache_dir: Optional[str] = None):
        self.fingerprint = dict(fingerprint)
        self.fp_hash = fingerprint_hash(self.fingerprint)
        self.cache_dir = cache_dir
        self._statics: Dict[str, tuple] = {}          # family -> argnums
        self._compiled: Dict[tuple, Any] = {}         # (family, sig) ->
        self._restored_keys: set = set()              # Compiled
        self._compiles: Dict[str, int] = {}
        self._restored: Dict[str, int] = {}
        self._compile_s: Dict[str, float] = {}
        self._dirty = False
        if cache_dir:
            self._arm_xla_cache(cache_dir)

    @staticmethod
    def _arm_xla_cache(cache_dir: str) -> None:
        global _xla_cache_armed
        if _xla_cache_armed:
            return
        try:
            jax.config.update("jax_compilation_cache_dir",
                              os.path.join(cache_dir, "xla"))
            _xla_cache_armed = True
        except Exception:   # config knob absent on this jax — AOT
            pass            # serialization still covers warm boot

    # ---------------- registration / resolution -----------------------
    def register(self, name: str, *, static_argnums: tuple = ()) -> None:
        self._statics[name] = tuple(static_argnums)
        self._compiles.setdefault(name, 0)
        self._restored.setdefault(name, 0)
        self._compile_s.setdefault(name, 0.0)

    def families(self) -> tuple:
        return tuple(self._statics)

    def signature(self, args, extra_key: Optional[str] = None) -> str:
        leaves, treedef = jax.tree_util.tree_flatten(args)
        parts = [str(treedef)]
        parts.extend(repr(_leaf_sig(l)) for l in leaves)
        if extra_key is not None:
            parts.append(extra_key)
        return hashlib.sha256(
            "\x1f".join(parts).encode()).hexdigest()[:24]

    def _compile(self, name: str, fn, args) -> Any:
        t0 = time.perf_counter()
        compiled = fn.lower(*args).compile()
        self._compile_s[name] = self._compile_s.get(name, 0.0) \
            + (time.perf_counter() - t0)
        self._compiles[name] = self._compiles.get(name, 0) + 1
        self._dirty = True
        return compiled

    def call(self, name: str, fn, *args, extra_key: Optional[str] = None):
        """Resolve (family, signature) to a compiled executable and
        dispatch it. New signature -> AOT compile (exact counting);
        restored executable that rejects the call -> warn, drop, and
        recompile (stale-cache rejection: a bad cache costs a compile
        and a warning, never a crash). `extra_key` folds caller context
        the arguments cannot express into the cache key — the executor
        uses it for build-variant tokens (sparse routing, scan vs
        unroll, optimizer hyperparameters) whose flip changes the
        program without changing any argument shape."""
        if name not in self._statics:
            self.register(name)
        statics = self._statics.get(name, ())
        if not hasattr(fn, "lower"):   # not a jit wrapper: dispatch
            return fn(*args)           # directly (fallback path)
        key = (name, self.signature(args, extra_key))
        compiled = self._compiled.get(key)
        if compiled is None:
            compiled = self._compile(name, fn, args)
            self._compiled[key] = compiled
        dyn = [a for i, a in enumerate(args) if i not in statics]
        try:
            return compiled(*dyn)
        except (TypeError, ValueError) as e:
            if key not in self._restored_keys:
                raise
            # deserialized from a snapshot whose runtime disagrees
            # with ours in a way the fingerprint did not fold —
            # reject the stale entry and compile fresh
            warnings.warn(
                f"program cache: restored {name!r} executable rejected "
                f"its first call ({e}); recompiling", stacklevel=2)
            self._restored_keys.discard(key)
            self._restored[name] = max(0, self._restored.get(name, 1) - 1)
            compiled = self._compile(name, fn, args)
            self._compiled[key] = compiled
            return compiled(*dyn)

    # ---------------- accounting ---------------------------------------
    def compile_counts(self) -> Dict[str, int]:
        """EXACT compiles per registered family this process performed
        (restored-from-snapshot executables count zero — that is the
        warm-boot contract)."""
        return {name: self._compiles.get(name, 0)
                for name in self._statics}

    def restored_counts(self) -> Dict[str, int]:
        return {name: self._restored.get(name, 0)
                for name in self._statics}

    def compile_seconds(self) -> float:
        return float(sum(self._compile_s.values()))

    def boot_record(self) -> Dict[str, Any]:
        """What booting this registry cost — the autoscaler's cold-vs-
        warm price and the `replica_boot` span payload."""
        return {
            "fingerprint": self.fp_hash,
            "restored": int(sum(self._restored.values())),
            "compiles": int(sum(self._compiles.values())),
            "compile_s": self.compile_seconds(),
            "families": {n: {"compiles": self._compiles.get(n, 0),
                             "restored": self._restored.get(n, 0),
                             "compile_s": round(
                                 self._compile_s.get(n, 0.0), 4)}
                         for n in self._statics},
        }

    # ---------------- persistence --------------------------------------
    def _store_path(self, cache_dir: Optional[str] = None) -> str:
        d = cache_dir if cache_dir is not None else self.cache_dir
        return os.path.join(d, self.fp_hash + _STORE_SUFFIX)

    def save(self, cache_dir: Optional[str] = None) -> int:
        """Serialize every compiled executable to
        ``<dir>/<fp_hash>.ffprog`` (atomic temp-then-replace, the
        checkpoint.py discipline) plus a human-readable manifest.
        Merges with a valid existing store for the same fingerprint
        (two engines over one dir each contribute their programs).
        Returns the number of entries written."""
        d = cache_dir if cache_dir is not None else self.cache_dir
        if not d:
            return 0
        os.makedirs(d, exist_ok=True)
        path = self._store_path(d)
        entries: Dict[tuple, dict] = {}
        old = self._read_store(path)
        if old is not None:
            for e in old.get("entries", []):
                entries[(e["family"], e["sig"])] = e
        from jax.experimental.serialize_executable import serialize
        for (family, sig), compiled in self._compiled.items():
            try:
                payload, in_tree, out_tree = serialize(compiled)
            except Exception as e:   # an unserializable executable is
                warnings.warn(       # skipped, not fatal
                    f"program cache: could not serialize {family!r} "
                    f"({e}); skipping", stacklevel=2)
                continue
            entries[(family, sig)] = {
                "family": family, "sig": sig,
                "statics": list(self._statics.get(family, ())),
                "payload": payload, "in_tree": in_tree,
                "out_tree": out_tree,
                "compile_s": self._compile_s.get(family, 0.0),
            }
        blob = pickle.dumps({
            "version": _STORE_VERSION,
            "fingerprint": self.fingerprint,
            "fp_hash": self.fp_hash,
            "jax": jax.__version__,
            "entries": list(entries.values()),
        })
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        self._write_manifest(d, len(entries))
        self._dirty = False
        return len(entries)

    def _write_manifest(self, d: str, n_entries: int) -> None:
        """Best-effort human-readable sidecar: which fingerprints live
        in this dir and what they hold (the store itself is pickle)."""
        path = os.path.join(d, "manifest.json")
        try:
            doc = {}
            if os.path.exists(path):
                with open(path) as f:
                    doc = json.load(f)
            if not isinstance(doc, dict):
                doc = {}
        except (OSError, json.JSONDecodeError, UnicodeDecodeError):
            doc = {}
        doc[self.fp_hash] = {
            "entries": n_entries,
            "families": sorted(self._statics),
            "jax": jax.__version__,
            "fingerprint": {k: str(v)
                            for k, v in self.fingerprint.items()},
        }
        try:
            tmp = f"{path}.tmp-{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1, sort_keys=True)
            os.replace(tmp, path)
        except OSError:
            pass

    def _read_store(self, path: str) -> Optional[dict]:
        """Read + validate a store file. Any corruption (truncated
        pickle, wrong type, wrong version, foreign fingerprint) warns
        and returns None — the caller compiles cold. Mirrors
        cost_cache.py: a bad cache costs a warning, never a crash."""
        if not os.path.exists(path):
            return None
        try:
            with open(path, "rb") as f:
                doc = pickle.loads(f.read())
            if (not isinstance(doc, dict)
                    or doc.get("version") != _STORE_VERSION
                    or not isinstance(doc.get("entries"), list)):
                raise ValueError("malformed program store")
            if doc.get("fp_hash") != self.fp_hash:
                # a DIFFERENT program fingerprint under the same file
                # name: treat as a miss (and as corrupt for merge —
                # save() will overwrite wholesale)
                return None
        except Exception as e:
            warnings.warn(
                f"program cache: unreadable store {path!r} ({e}); "
                f"booting cold", stacklevel=2)
            return None
        return doc

    def load_warm(self, cache_dir: Optional[str] = None) -> int:
        """Deserialize every stored executable for this fingerprint.
        Returns the number restored (0 on miss/corruption — never
        raises). Call AFTER register() so family static-argnums are
        known."""
        d = cache_dir if cache_dir is not None else self.cache_dir
        if not d:
            return 0
        doc = self._read_store(self._store_path(d))
        if doc is None:
            return 0
        from jax.experimental.serialize_executable import \
            deserialize_and_load
        n = 0
        for e in doc["entries"]:
            try:
                family = e["family"]
                key = (family, e["sig"])
                compiled = deserialize_and_load(
                    e["payload"], e["in_tree"], e["out_tree"])
            except Exception as exc:
                warnings.warn(
                    f"program cache: could not deserialize a "
                    f"{e.get('family')!r} executable ({exc}); it will "
                    f"be recompiled", stacklevel=2)
                continue
            if family not in self._statics:
                self.register(family,
                              static_argnums=tuple(e.get("statics", ())))
            self._compiled[key] = compiled
            self._restored_keys.add(key)
            self._restored[family] = self._restored.get(family, 0) + 1
            n += 1
        return n

    @classmethod
    def load(cls, cache_dir: str,
             fingerprint: Dict[str, Any]) -> "ProgramRegistry":
        """Build a registry for `fingerprint` and warm it from
        `cache_dir` in one step (the cold-replica boot path)."""
        reg = cls(fingerprint, cache_dir=cache_dir)
        reg.load_warm()
        return reg
