"""Checkpoint / resume via orbax.

The reference has NO training-state serialization (SURVEY.md section 5:
"no model-state serialization to disk"); the closest artifacts are host
get/set of weights and strategy files. This is the planned-in recovery
story: full TrainState (params, states, opt_state, step) saved with
orbax, with optional async saves so the step loop never blocks.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np

from .executor import TrainState


def _checkpointer(use_async: bool = False):
    import orbax.checkpoint as ocp
    if use_async:
        return ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
    return ocp.Checkpointer(ocp.StandardCheckpointHandler())


def save_checkpoint(path: str, state: TrainState,
                    use_async: bool = False, force: bool = True,
                    checkpointer=None):
    """Save a TrainState to `path` (a directory).

    With use_async=True the write happens in a background thread and the
    AsyncCheckpointer is RETURNED — the caller must keep it and call
    wait_until_finished() (or close()) before relying on the checkpoint
    or exiting; the checkpoint is uncommitted until then. Pass the
    returned checkpointer back as `checkpointer` on subsequent saves to
    reuse it (orbax serializes against the in-flight save itself; one
    background thread for the whole loop instead of one per save)."""
    ckptr = checkpointer or _checkpointer(use_async)
    payload = {
        "params": state.params,
        "states": state.states,
        "opt_state": state.opt_state,
        "step": state.step,
    }
    ckptr.save(os.path.abspath(path), payload, force=force)
    if use_async:
        return ckptr
    ckptr.close()
    return None


def restore_checkpoint(path: str, state: TrainState) -> TrainState:
    """Restore into the structure (and shardings) of `state`.

    An INFERENCE-compiled model (opt_state == {}) restores a TRAINING
    checkpoint by reading params/states/step only — the on-disk
    optimizer slots are skipped, not structure-mismatched, so the
    train -> checkpoint -> serve flow works (reference COMP_MODE
    semantics; its nearest artifact was host weight import)."""
    import orbax.checkpoint as ocp
    ckptr = _checkpointer(False)
    target = {
        "params": state.params,
        "states": state.states,
        "opt_state": state.opt_state,
        "step": state.step,
    }
    if not state.opt_state:
        partial = {k: v for k, v in target.items() if k != "opt_state"}
        # the PyTree handler reads the Standard layout and supports
        # partial restore (skip the on-disk optimizer slots entirely)
        import inspect
        pt = ocp.Checkpointer(ocp.PyTreeCheckpointHandler())
        if "partial_restore" in inspect.signature(
                ocp.args.PyTreeRestore).parameters:
            restored = pt.restore(
                os.path.abspath(path),
                args=ocp.args.PyTreeRestore(item=partial,
                                            partial_restore=True))
        else:
            # older orbax: no partial_restore kwarg; an empty transforms
            # dict is the legacy spelling of "restore only the keys in
            # item", and it requires explicit per-leaf restore_args
            restored = pt.restore(
                os.path.abspath(path),
                args=ocp.args.PyTreeRestore(
                    item=partial,
                    restore_args=ocp.checkpoint_utils.
                    construct_restore_args(partial),
                    transforms={}))
        pt.close()
        restored["opt_state"] = {}
    else:
        restored = ckptr.restore(
            os.path.abspath(path),
            args=ocp.args.StandardRestore(target))
    ckptr.close()
    return TrainState(restored["params"], restored["states"],
                      restored["opt_state"], restored["step"])


def save_model(model, path: str, use_async: bool = False):
    """Returns the AsyncCheckpointer when use_async=True (see
    save_checkpoint), else None."""
    return save_checkpoint(path, model.state, use_async=use_async)


def restore_model(model, path: str) -> None:
    model.state = restore_checkpoint(path, model.state)
    # resync the per-step training-rng mirror so the restored run's
    # stochastic ops (dropout) continue the exact stream of the
    # uninterrupted one (FFModel._train_rng keys on this counter)
    model._host_step = int(model.state.step)
