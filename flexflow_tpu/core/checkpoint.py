"""Checkpoint / resume via orbax, crash-safe.

The reference has NO training-state serialization (SURVEY.md section 5:
"no model-state serialization to disk"); the closest artifacts are host
get/set of weights and strategy files. This is the planned-in recovery
story: full TrainState (params, states, opt_state, step) saved with
orbax, with optional async saves so the step loop never blocks.

Crash safety (docs/robustness.md): every save lands in a `<path>.tmp`
staging directory and is PROMOTED onto `<path>` with atomic renames
only once fully written — a process killed at any instant leaves
either the previous complete checkpoint or none at the final name,
never a truncated one. Resume scans (FFModel.fit) therefore only ever
see committed state, and a kill-mid-save run resumes from the newest
committed epoch with a loss trajectory bit-identical to an
uninterrupted run (tests/test_faults.py). The promote point carries a
fault-injection site ("ckpt.commit", utils/faults) so chaos tests can
stage the kill deterministically.
"""

from __future__ import annotations

import json
import os
import shutil
from typing import Optional

import jax
import numpy as np

from ..utils.faults import default_injector
from .executor import TrainState


def _checkpointer(use_async: bool = False):
    import orbax.checkpoint as ocp
    if use_async:
        return ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
    return ocp.Checkpointer(ocp.StandardCheckpointHandler())


def _promote(tmp: str, final: str) -> None:
    """Swing `final` to the fully-written `tmp` directory. Each step is
    a whole-directory rename, so no reader ever observes a
    partially-written checkpoint at `final`: a kill before the swap
    leaves the old checkpoint, a kill inside the two-rename window
    leaves it recoverable at `<final>.old` (readers run
    :func:`recover_promoted` first), and a kill after leaves the new
    one plus a stale `.old` the next promote sweeps."""
    old = final + ".old"
    if os.path.isdir(old) and os.path.isdir(final):
        shutil.rmtree(old)      # stale leftover from a killed promote
    if os.path.isdir(final):
        os.rename(final, old)
    # the narrow not-atomic window: final is absent, the previous
    # checkpoint complete at .old, the new one complete at tmp
    default_injector().fire("ckpt.swap")
    os.rename(tmp, final)
    if os.path.isdir(old):
        shutil.rmtree(old)


def recover_promoted(path: str) -> None:
    """Heal a promote killed inside its rename window: if nothing is
    committed at `path` but a complete previous checkpoint sits at
    `<path>.old`, swing it back. Idempotent; called by every reader
    (restore_checkpoint, fit's resume scan)."""
    if not os.path.isdir(path) and os.path.isdir(path + ".old"):
        os.rename(path + ".old", path)


def _payload(state: TrainState) -> dict:
    return {
        "params": state.params,
        "states": state.states,
        "opt_state": state.opt_state,
        "step": state.step,
    }


class AsyncSaver:
    """Async checkpointing with DEFERRED atomic promotes.

    orbax's AsyncCheckpointer writes in a background thread; the
    promote of save N happens when save N+1 starts (orbax would
    serialize against the in-flight write there anyway) or at
    wait_until_finished()/close(). Until its promote, a save is
    invisible at the final path — exactly the crash contract of the
    sync path, stretched over the async pipeline."""

    def __init__(self):
        self._ckptr = _checkpointer(use_async=True)
        self._pending: Optional[tuple] = None

    def save(self, path: str, state: TrainState,
             force: bool = True) -> None:
        self._commit_pending()
        path = os.path.abspath(path)
        default_injector().fire("ckpt.save")
        self._ckptr.save(path + ".tmp", _payload(state), force=force)
        self._pending = (path + ".tmp", path)

    def _commit_pending(self) -> None:
        if self._pending is None:
            return
        tmp, final = self._pending
        self._ckptr.wait_until_finished()
        # the staged kill point: tmp is complete, final not yet swung
        default_injector().fire("ckpt.commit")
        _promote(tmp, final)
        self._pending = None

    def wait_until_finished(self) -> None:
        self._commit_pending()

    def close(self) -> None:
        self._commit_pending()
        self._ckptr.close()


def save_checkpoint(path: str, state: TrainState,
                    use_async: bool = False, force: bool = True,
                    checkpointer=None):
    """Save a TrainState to `path` (a directory), atomically: the write
    lands in `<path>.tmp` and is renamed onto `path` only when
    complete, so a kill at any instant leaves no truncated checkpoint
    visible at `path`.

    With use_async=True the write happens in a background thread and an
    :class:`AsyncSaver` is RETURNED — the caller must keep it and call
    wait_until_finished() (or close()) before relying on the checkpoint
    or exiting; the checkpoint is uncommitted (invisible at `path`)
    until then. Pass the returned saver back as `checkpointer` on
    subsequent saves to reuse it (one background thread for the whole
    loop instead of one per save)."""
    if use_async:
        saver = checkpointer if checkpointer is not None else AsyncSaver()
        saver.save(path, state, force=force)
        return saver
    path = os.path.abspath(path)
    default_injector().fire("ckpt.save")
    ckptr = checkpointer or _checkpointer(False)
    ckptr.save(path + ".tmp", _payload(state), force=force)
    # the staged kill point: tmp is complete, path not yet swung
    default_injector().fire("ckpt.commit")
    _promote(path + ".tmp", path)
    if checkpointer is None:
        ckptr.close()
    return None


def atomic_write_json(path: str, obj,
                      fault_site: str = "ckpt.commit") -> None:
    """temp-then-os.replace JSON write: the file at `path` is either
    the previous complete content or the new complete content, never a
    truncation. The shared primitive for every small host-side state
    file (data-loader state, tools' artifacts that need the
    guarantee); `fault_site` names the staged kill point."""
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(obj, f)
        f.flush()
        os.fsync(f.fileno())
    default_injector().fire(fault_site)
    os.replace(tmp, path)


def restore_checkpoint(path: str, state: TrainState) -> TrainState:
    """Restore into the structure (and shardings) of `state`.

    An INFERENCE-compiled model (opt_state == {}) restores a TRAINING
    checkpoint by reading params/states/step only — the on-disk
    optimizer slots are skipped, not structure-mismatched, so the
    train -> checkpoint -> serve flow works (reference COMP_MODE
    semantics; its nearest artifact was host weight import)."""
    import orbax.checkpoint as ocp
    recover_promoted(os.path.abspath(path))
    ckptr = _checkpointer(False)
    target = {
        "params": state.params,
        "states": state.states,
        "opt_state": state.opt_state,
        "step": state.step,
    }
    if not state.opt_state:
        partial = {k: v for k, v in target.items() if k != "opt_state"}
        # the PyTree handler reads the Standard layout and supports
        # partial restore (skip the on-disk optimizer slots entirely)
        import inspect
        pt = ocp.Checkpointer(ocp.PyTreeCheckpointHandler())
        if "partial_restore" in inspect.signature(
                ocp.args.PyTreeRestore).parameters:
            restored = pt.restore(
                os.path.abspath(path),
                args=ocp.args.PyTreeRestore(item=partial,
                                            partial_restore=True))
        else:
            # older orbax: no partial_restore kwarg; an empty transforms
            # dict is the legacy spelling of "restore only the keys in
            # item", and it requires explicit per-leaf restore_args
            restored = pt.restore(
                os.path.abspath(path),
                args=ocp.args.PyTreeRestore(
                    item=partial,
                    restore_args=ocp.checkpoint_utils.
                    construct_restore_args(partial),
                    transforms={}))
        pt.close()
        restored["opt_state"] = {}
    else:
        restored = ckptr.restore(
            os.path.abspath(path),
            args=ocp.args.StandardRestore(target))
    ckptr.close()
    return TrainState(restored["params"], restored["states"],
                      restored["opt_state"], restored["step"])


def save_model(model, path: str, use_async: bool = False):
    """Returns the AsyncCheckpointer when use_async=True (see
    save_checkpoint), else None."""
    return save_checkpoint(path, model.state, use_async=use_async)


def restore_model(model, path: str) -> None:
    model.state = restore_checkpoint(path, model.state)
    # resync the per-step training-rng mirror so the restored run's
    # stochastic ops (dropout) continue the exact stream of the
    # uninterrupted one (FFModel._train_rng keys on this counter)
    model._host_step = int(model.state.step)
