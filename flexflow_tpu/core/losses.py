"""Loss functions.

Reference: src/loss_functions/loss_functions.cu — sparse-CCE via
subtract-one-hot kernel, CCE, MSE, with the gradient scaled by 1/num_parts
when the logit tensor is partitioned (loss_functions.cu:127-160). On TPU
that scale factor is unnecessary: we define losses as *means over the
global batch* and differentiate the whole step, so sharding never changes
the math.
"""

from __future__ import annotations

from typing import Callable, Dict

import jax
import jax.numpy as jnp

LOSS_SPARSE_CCE = "sparse_categorical_crossentropy"
LOSS_CCE = "categorical_crossentropy"
LOSS_MSE = "mean_squared_error"
LOSS_BCE = "binary_crossentropy"
LOSS_IDENTITY = "identity"


def flatten_sparse_labels(preds, labels):
    """Normalize sparse int labels against predictions: (batch,) /
    (batch, 1) labels pass through; PER-POSITION labels (batch, t...)
    matching preds (batch, t..., vocab) — the seq2seq teacher-forcing
    case (reference nmt/ trains per-timestep softmaxes,
    softmax_data_parallel.cu) — flatten BOTH so each position scores as
    one sample. Single source of truth for loss AND metrics: they must
    agree on which positions they score."""
    labels = labels.astype(jnp.int32)
    if (labels.ndim >= 2 and labels.ndim == preds.ndim - 1
            and labels.shape == preds.shape[:-1]):
        return preds.reshape(-1, preds.shape[-1]), labels.reshape(-1)
    return preds, labels.reshape(labels.shape[0])


def sparse_categorical_crossentropy(logits_or_probs, labels,
                                    from_logits: bool = False):
    """labels: int (batch,) / (batch, 1) or per-position (see
    flatten_sparse_labels). The reference applies this to *softmax
    outputs* (the graph ends in Softmax, loss takes probs)."""
    preds, labels = flatten_sparse_labels(logits_or_probs, labels)
    if from_logits:
        logp = jax.nn.log_softmax(preds, axis=-1)
    else:
        logp = jnp.log(jnp.clip(preds, 1e-12, 1.0))
    # mode="clip" (labels are in-bounds by contract): the "fill" default
    # emits an OOB-validity select that GSPMD's partitioning of the
    # gather misfires on when the class dim is model-sharded, silently
    # corrupting the per-sample nll (same hazard as the embedding
    # gathers, ops/embedding.py)
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=-1,
                               mode="clip")
    return jnp.mean(nll)


def categorical_crossentropy(probs, labels, from_logits: bool = False):
    if from_logits:
        logp = jax.nn.log_softmax(probs, axis=-1)
    else:
        logp = jnp.log(jnp.clip(probs, 1e-12, 1.0))
    return -jnp.mean(jnp.sum(labels * logp, axis=-1))


def mean_squared_error(preds, targets, from_logits: bool = False):
    return jnp.mean(jnp.square(preds.astype(jnp.float32)
                               - targets.astype(jnp.float32)))


def binary_crossentropy(preds, targets, from_logits: bool = False):
    if from_logits:
        return jnp.mean(jnp.maximum(preds, 0) - preds * targets
                        + jnp.log1p(jnp.exp(-jnp.abs(preds))))
    p = jnp.clip(preds, 1e-7, 1 - 1e-7)
    return -jnp.mean(targets * jnp.log(p) + (1 - targets) * jnp.log(1 - p))


def identity(preds, targets, from_logits: bool = False):
    """Mean of predictions — used when the graph computes its own loss."""
    return jnp.mean(preds)


LOSSES: Dict[str, Callable] = {
    LOSS_SPARSE_CCE: sparse_categorical_crossentropy,
    "sparse_crossentropy": sparse_categorical_crossentropy,
    LOSS_CCE: categorical_crossentropy,
    LOSS_MSE: mean_squared_error,
    "mse": mean_squared_error,
    LOSS_BCE: binary_crossentropy,
    LOSS_IDENTITY: identity,
}


def resolve(name_or_fn):
    if callable(name_or_fn):
        return name_or_fn
    return LOSSES[name_or_fn]
