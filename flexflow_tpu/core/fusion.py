"""Graph fusion pass — the TPU-native reading of the reference FusedOp.

The reference's `apply_fusion` (src/runtime/model.cc:1472-1549) packs
consecutive ops with identical ParallelConfigs into one `FusedOp`
(src/ops/fused.cu) so the group launches as a single Legion task. On TPU,
XLA already fuses elementwise work into matmuls, so the pass's payoff
moves to the two places op granularity still matters:

  1. The executor pins a `with_sharding_constraint` on every op output;
     for ops interior to a same-strategy chain that pin is redundant and
     can block GSPMD from picking cheaper intermediate layouts. Fusion
     marks interior ops so only group boundaries are constrained.
  2. The search simulator models one task per op; a fused group costs
     one compute task (sum of member times, boundary comm only) exactly
     like the reference simulates a FusedOp as one task.

A group is a chain: op B joins producer A's group iff A and B resolve to
the same op-strategy axis map, A has exactly one in-graph consumer, and B
has exactly one in-graph producer (the chain restriction mirrors the
reference's "same ParallelConfig + contiguous" rule, fused.cu:61).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..parallel.pconfig import Strategy


def _strategy_key(strategy: Strategy, op_name: str) -> Tuple:
    s = strategy.for_op(op_name)
    return tuple(sorted((k, str(v)) for k, v in s.axis_map.items()))


def compute_fusion_groups(model, strategy: Optional[Strategy]
                          ) -> List[List[str]]:
    """Partition model.ops (topological order) into same-strategy chains.

    Returns a list of groups, each a list of op names in execution order;
    singleton groups are included so the result is a partition.
    """
    from ..search.simulator import op_edges  # canonical edge derivation

    strategy = strategy or Strategy()
    producer, edges = op_edges(model)
    n_consumers: Dict[str, int] = {}
    for src, _dst in edges:
        n_consumers[src.name] = n_consumers.get(src.name, 0) + 1

    group_of: Dict[str, int] = {}
    groups: List[List[str]] = []
    for op in model.ops:
        in_producers = {producer[t.uid].name
                        for t in op.inputs if t.uid in producer}
        join = None
        if len(in_producers) == 1:
            (pname,) = in_producers
            if (n_consumers.get(pname, 0) == 1
                    and _strategy_key(strategy, pname)
                    == _strategy_key(strategy, op.name)):
                join = group_of[pname]
        if join is None:
            group_of[op.name] = len(groups)
            groups.append([op.name])
        else:
            group_of[op.name] = join
            groups[join].append(op.name)
    return groups


def boundary_ops(groups: List[List[str]]) -> set:
    """Names of ops that end a fused group (where sharding is pinned)."""
    return {g[-1] for g in groups}


def conv_sibling_groups(model) -> List[List]:
    """Groups of Conv2D ops that read the SAME input tensor with the
    SAME geometry — the 1x1 branch heads of an Inception module.

    Such siblings execute as one conv with kernels concatenated along
    channel-out (ops/conv.py merged_conv_forward): exact numerics, much
    better MXU lane occupancy when each branch's cout is a poor fit for
    the 128-lane tile. Members are returned in model.ops order; the
    first is the group leader (executes the merged conv at its walk
    position; the rest pop their pre-sliced output).

    Grouping requires identical kernel/stride/padding/activation/
    use_bias and groups == 1 (feature_group_count partitions cin, which
    concatenation along cout would scramble).
    """
    by_key: Dict[Tuple, List] = {}
    for op in model.ops:
        if getattr(op, "op_type", None) != "conv2d":
            continue
        if op.groups != 1:
            continue
        key = (op.inputs[0].uid, op.kernel, op.stride, op.padding,
               op.activation, op.use_bias)
        by_key.setdefault(key, []).append(op)
    return [g for g in by_key.values() if len(g) > 1]
