"""Parameter initializers.

Reference: src/runtime/initializer.cc + initializer_kernel.cu (curand-based
Glorot/Zero/Constant/Uniform/Norm tasks launched per parameter,
initializer.cc:16-330). Here each is a pure function of a PRNG key; the
executor folds a per-parameter key out of the model seed, so results are
reproducible and device-count independent.
"""

from __future__ import annotations

import math
from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp


def _fans(shape: Tuple[int, ...]) -> Tuple[int, int]:
    """fan_in/fan_out matching the reference's GlorotUniform task
    (initializer.cc): dense (in,out); conv (out,in,kh,kw) uses
    receptive-field scaling."""
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    if len(shape) == 4:  # conv OIHW
        rf = shape[2] * shape[3]
        return shape[1] * rf, shape[0] * rf
    # attention (in, heads, d) etc.: fold trailing dims
    fan_in = shape[0]
    fan_out = 1
    for s in shape[1:]:
        fan_out *= s
    return fan_in, fan_out


def glorot_uniform(key, shape, dtype=jnp.float32, fan_in=None, fan_out=None):
    if fan_in is None or fan_out is None:
        fan_in, fan_out = _fans(shape)
    scale = math.sqrt(6.0 / (fan_in + fan_out))
    return jax.random.uniform(key, shape, dtype, -scale, scale)


def zeros(key, shape, dtype=jnp.float32):
    return jnp.zeros(shape, dtype)


def ones(key, shape, dtype=jnp.float32):
    return jnp.ones(shape, dtype)


def make_constant(value: float):
    def init(key, shape, dtype=jnp.float32):
        return jnp.full(shape, value, dtype)
    return init


def make_uniform(minv: float, maxv: float, seed: int = 0):
    def init(key, shape, dtype=jnp.float32):
        return jax.random.uniform(key, shape, dtype, minv, maxv)
    return init


def make_normal(mean: float = 0.0, stddev: float = 1.0, seed: int = 0):
    def init(key, shape, dtype=jnp.float32):
        return mean + stddev * jax.random.normal(key, shape, dtype)
    return init


def he_normal(key, shape, dtype=jnp.float32, fan_in=None, fan_out=None):
    if fan_in is None:
        fan_in, _ = _fans(shape)
    return jax.random.normal(key, shape, dtype) * math.sqrt(2.0 / fan_in)


INITIALIZERS: Dict[str, Callable] = {
    "glorot": glorot_uniform,
    "glorot_uniform": glorot_uniform,
    "zeros": zeros,
    "zero": zeros,
    "ones": ones,
    "he_normal": he_normal,
    "norm": make_normal(),
    "normal": make_normal(),
}


def resolve(name_or_fn) -> Callable:
    if callable(name_or_fn):
        return name_or_fn
    return INITIALIZERS[name_or_fn]
