"""Optimizers: SGD (w/ momentum, nesterov) and Adam.

Reference: src/runtime/optimizer.cc + optimizer_kernel.cu — each optimizer
has PS and NCCL task variants; the PS path broadcasts updated weights via a
prefetch index launch (optimizer.cc:122-134), the NCCL path all-reduces
grads inside the update kernel (optimizer_kernel.cu:113-180, 296-350). On
TPU both collapse: gradients of sharded/replicated params already carry the
right partial-sum semantics and GSPMD inserts the reduction, so the update
is a pure elementwise pytree map (runs on the VPU, fully fused by XLA).

Implemented natively (not via optax) so the update rule exactly matches the
reference kernels (e.g. SGD's `weight_decay` is L2-added-to-grad, and
Adam's epsilon-inside-sqrt placement follows optimizer_kernel.cu).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp


def coalesce_rows(idx, g, vocab: int):
    """Static-shape duplicate coalescing for sparse row updates: sort
    the indices, segment-sum gradients of equal indices, and park unused
    slots at an out-of-range row (scatters use mode='drop').

    Returns (uidx, gsum) with the SAME length n as the input — slot j
    holds a unique row id and the summed gradient of all its duplicates
    (or row=vocab, g=0 padding). Needed because stateful row rules
    (momentum, Adam) are not additive: applying the rule per-duplicate
    differs from applying it once to the summed gradient, which is what
    the dense path computes (torch coalesces sparse grads the same way).
    """
    n = idx.shape[0]
    order = jnp.argsort(idx)
    sidx = idx[order]
    sg = g[order]
    newseg = jnp.concatenate([jnp.ones((1,), jnp.int32),
                              (sidx[1:] != sidx[:-1]).astype(jnp.int32)])
    seg = jnp.cumsum(newseg) - 1          # 0..u-1 ranks, static shape
    gsum = jax.ops.segment_sum(sg, seg, num_segments=n)
    uidx = jnp.full((n,), vocab, dtype=sidx.dtype)  # padding = OOB row
    uidx = uidx.at[seg].set(sidx)          # last dupe wins; same value
    return uidx, gsum


class Optimizer:
    name = "optimizer"

    def init_state(self, params) -> Any:
        raise NotImplementedError

    def update(self, params, grads, state, step, lr_scale=1.0) -> tuple:
        """Returns (new_params, new_state). `lr_scale` is a runtime
        (traced) multiplier on the base lr — the LR-schedule hook
        (model.set_learning_rate / keras LearningRateScheduler) without
        recompiling the step."""
        raise NotImplementedError

    def sparse_mode(self):
        """How `sparse_update` relates to the dense rule:
        - "exact": identical result (plain SGD — scatter-add IS the
          dense update restricted to the touched rows);
        - "lazy": touched rows get the exact rule on COALESCED gradients,
          untouched rows keep stale state (momentum does not decay, Adam
          m/v do not advance) — torch.optim.SparseAdam semantics;
        - None: no sparse form (weight decay touches every row).
        The executor uses "exact" freely and "lazy" only when
        FFConfig.sparse_embedding_lazy opts in."""
        return None

    def sparse_update(self, w, idx, g, slots, step, lr_scale=1.0):
        """Scatter-apply the update for the touched rows only: `w` is the
        full (vocab, dim) table, `idx` (n,) row ids (duplicates allowed),
        `g` (n, dim) the gradient of those gathered rows, `slots` this
        table's optimizer-state arrays (e.g. {"v": (vocab, dim)}), `step`
        the global step counter. Returns (new_w, new_slots). The TPU
        analog of the reference's scatter-add embedding backward +
        per-table update (src/ops/embedding.cu), skipping the dense
        zeros+scatter+axpy sweep over millions of untouched rows."""
        raise NotImplementedError


class SGDOptimizer(Optimizer):
    """Reference: sgd_update kernel (optimizer_kernel.cu:24-60):
    g += weight_decay * w; v = momentum * v + g; w -= lr * (nesterov ?
    g + momentum*v : v)."""

    name = "sgd"

    def __init__(self, lr: float = 0.01, momentum: float = 0.0,
                 nesterov: bool = False, weight_decay: float = 0.0):
        self.lr = lr
        self.momentum = momentum
        self.nesterov = nesterov
        self.weight_decay = weight_decay

    def init_state(self, params):
        if self.momentum == 0.0:
            return {}
        return {"v": jax.tree_util.tree_map(jnp.zeros_like, params)}

    def update(self, params, grads, state, step, lr_scale=1.0):
        lr = jnp.asarray(self.lr, jnp.float32) * lr_scale

        def upd(w, g, v=None):
            g = g.astype(jnp.float32) + self.weight_decay * w.astype(jnp.float32)
            if v is None:
                neww = w.astype(jnp.float32) - lr * g
                return neww.astype(w.dtype), None
            v = self.momentum * v + g
            if self.nesterov:
                step_dir = g + self.momentum * v
            else:
                step_dir = v
            neww = w.astype(jnp.float32) - lr * step_dir
            return neww.astype(w.dtype), v

        if self.momentum == 0.0:
            new_params = jax.tree_util.tree_map(
                lambda w, g: upd(w, g)[0], params, grads)
            return new_params, state
        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_v = treedef.flatten_up_to(state["v"])
        new_p, new_v = [], []
        for w, g, v in zip(flat_p, flat_g, flat_v):
            nw, nv = upd(w, g, v)
            new_p.append(nw)
            new_v.append(nv)
        return (jax.tree_util.tree_unflatten(treedef, new_p),
                {"v": jax.tree_util.tree_unflatten(treedef, new_v)})

    def sparse_mode(self):
        # w -= lr * g row-wise is EXACTLY the dense rule when there is no
        # momentum (no per-row state to carry) and no weight decay (decay
        # touches every row, not just the gathered ones); duplicate
        # indices accumulate commutatively through scatter-add, matching
        # the dense scatter-of-sums. With momentum the velocity of
        # untouched rows would decay in the dense rule -> lazy only.
        if self.weight_decay != 0.0:
            return None
        return "exact" if self.momentum == 0.0 else "lazy"

    def sparse_update(self, w, idx, g, slots, step, lr_scale=1.0):
        lr = jnp.asarray(self.lr, jnp.float32) * lr_scale
        if self.momentum == 0.0:
            upd = (-lr) * g.astype(jnp.float32)
            return w.at[idx].add(upd.astype(w.dtype)), slots
        vocab = w.shape[0]
        uidx, gsum = coalesce_rows(idx, g.astype(jnp.float32), vocab)
        v_rows = slots["v"].at[uidx].get(mode="fill", fill_value=0.0)
        v_rows = self.momentum * v_rows + gsum
        step_dir = gsum + self.momentum * v_rows if self.nesterov \
            else v_rows
        new_w = w.at[uidx].add((-lr * step_dir).astype(w.dtype),
                               mode="drop")
        new_v = slots["v"].at[uidx].set(v_rows, mode="drop")
        return new_w, {"v": new_v}


class AdamOptimizer(Optimizer):
    """Reference: adam_update kernel (optimizer_kernel.cu:200-260) with
    bias-corrected alpha_t precomputed on host (optimizer.cc `next()`):
    m = b1*m + (1-b1)*g; v = b2*v + (1-b2)*g^2;
    w -= alpha_t * m / (sqrt(v) + eps)."""

    name = "adam"

    def __init__(self, lr: float = 0.001, beta1: float = 0.9,
                 beta2: float = 0.999, weight_decay: float = 0.0,
                 epsilon: float = 1e-8):
        self.lr = lr
        self.beta1 = beta1
        self.beta2 = beta2
        self.weight_decay = weight_decay
        self.epsilon = epsilon

    def init_state(self, params):
        z = jax.tree_util.tree_map(
            lambda w: jnp.zeros(w.shape, jnp.float32), params)
        return {"m": z, "v": jax.tree_util.tree_map(jnp.zeros_like, z)}

    def update(self, params, grads, state, step, lr_scale=1.0):
        t = step.astype(jnp.float32) + 1.0
        alpha_t = self.lr * lr_scale * jnp.sqrt(1.0 - self.beta2 ** t) / (
            1.0 - self.beta1 ** t)

        def upd(w, g, m, v):
            g = g.astype(jnp.float32) + self.weight_decay * w.astype(jnp.float32)
            m = self.beta1 * m + (1 - self.beta1) * g
            v = self.beta2 * v + (1 - self.beta2) * g * g
            neww = w.astype(jnp.float32) - alpha_t * m / (jnp.sqrt(v) + self.epsilon)
            return neww.astype(w.dtype), m, v

        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_m = treedef.flatten_up_to(state["m"])
        flat_v = treedef.flatten_up_to(state["v"])
        new_p, new_m, new_v = [], [], []
        for w, g, m, v in zip(flat_p, flat_g, flat_m, flat_v):
            nw, nm, nv = upd(w, g, m, v)
            new_p.append(nw)
            new_m.append(nm)
            new_v.append(nv)
        return (
            jax.tree_util.tree_unflatten(treedef, new_p),
            {"m": jax.tree_util.tree_unflatten(treedef, new_m),
             "v": jax.tree_util.tree_unflatten(treedef, new_v)},
        )

    def sparse_mode(self):
        # lazy-Adam: touched rows advance m/v and step with the bias-
        # corrected alpha_t; untouched rows keep stale m/v (torch
        # SparseAdam). Weight decay would touch every row -> dense.
        return "lazy" if self.weight_decay == 0.0 else None

    def sparse_update(self, w, idx, g, slots, step, lr_scale=1.0):
        t = step.astype(jnp.float32) + 1.0
        alpha_t = self.lr * lr_scale * jnp.sqrt(1.0 - self.beta2 ** t) / (
            1.0 - self.beta1 ** t)
        vocab = w.shape[0]
        uidx, gsum = coalesce_rows(idx, g.astype(jnp.float32), vocab)
        m = slots["m"].at[uidx].get(mode="fill", fill_value=0.0)
        v = slots["v"].at[uidx].get(mode="fill", fill_value=0.0)
        m = self.beta1 * m + (1 - self.beta1) * gsum
        v = self.beta2 * v + (1 - self.beta2) * gsum * gsum
        delta = -alpha_t * m / (jnp.sqrt(v) + self.epsilon)
        return (w.at[uidx].add(delta.astype(w.dtype), mode="drop"),
                {"m": slots["m"].at[uidx].set(m, mode="drop"),
                 "v": slots["v"].at[uidx].set(v, mode="drop")})
