"""FFModel: the graph-builder + training driver.

Mirrors the reference `FFModel` public surface (include/model.h:266-536 —
one builder method per layer type, then compile/fit/forward/backward/
update/zero_gradients) so reference examples translate 1:1, while the
implementation is TPU-native: compile() produces jitted JAX steps instead
of Legion partitions/launchers (SURVEY.md section 7).
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from .config import CompMode, FFConfig
from .core.executor import Executor, TrainState
from .core.optimizers import AdamOptimizer, Optimizer, SGDOptimizer
from .op import Op
from .ops import (
    LSTM,
    Aggregate,
    MoEFFN,
    PipelineBlocks,
    BatchMatmul,
    BatchNorm,
    Concat,
    Conv2D,
    Dropout,
    ElementBinary,
    ElementUnary,
    Embedding,
    Flat,
    GroupBy,
    Linear,
    MultiHeadAttention,
    Pool2D,
    Reduce,
    Reshape,
    Reverse,
    Softmax,
    Split,
    TopK,
    Transpose,
)
from .parallel.mesh import default_mesh, make_mesh
from .parallel.sharding import place_global
from .parallel.pconfig import OpStrategy, Strategy
from .tensor import Tensor


def _resolve_steps_per_dispatch(spd, grad_accum_steps: int = 1) -> int:
    """"auto" -> 8 steps per device dispatch on TPU backends (where
    dispatch latency is real), 1 elsewhere and under grad accumulation
    (its grouping carries the semantics). The one rule for fit() and
    evaluate(). The reference traces every iteration
    (begin/end_trace, alexnet.cc:106-111); this is the
    dispatch-grouped analog as a default rather than an opt-in."""
    if spd == "auto":
        return (8 if (jax.devices()[0].platform == "tpu"
                      and grad_accum_steps <= 1) else 1)
    return int(spd)


class FFModel:
    def __init__(self, config: Optional[FFConfig] = None,
                 mesh: Optional[Mesh] = None,
                 strategy: Optional[Strategy] = None):
        self.config = config or FFConfig()
        self.ops: List[Op] = []
        self.input_tensors: List[Tensor] = []
        self._name_counts: Dict[str, int] = {}
        self.mesh = mesh
        self.strategy = strategy
        self.executor: Optional[Executor] = None
        self.state: Optional[TrainState] = None
        self.simulator = None  # set by calibrate_simulator()
        self.search_stats = None  # set by search.mcmc.optimize*
        # (profiling.search_report renders it)
        self.last_train_stats = None  # set by fit()
        self.telemetry = None         # set by fit() (utils/telemetry)
        # (profiling.train_report renders it)
        self.label_tensor: Optional[Tensor] = None
        # pretrained weights staged by frontends before compile()
        # (applied after init_state; reference Parameter::set_weights role)
        self.imported_weights: Dict[str, Dict[str, np.ndarray]] = {}
        # non-trainable state staged the same way (BN running stats)
        self.imported_states: Dict[str, Dict[str, np.ndarray]] = {}
        self._rng = jax.random.PRNGKey(self.config.seed)

    # ---------------- tensors ----------------
    def create_tensor(self, shape: Sequence[int], dtype=jnp.float32,
                      name: Optional[str] = None) -> Tensor:
        t = Tensor(tuple(shape), dtype,
                   name=name or self._fresh_name("input"), is_input=True)
        self.input_tensors.append(t)
        return t

    def _fresh_name(self, base: str) -> str:
        n = self._name_counts.get(base, 0)
        self._name_counts[base] = n + 1
        return base if n == 0 else f"{base}_{n}"

    def add_op(self, op: Op) -> Op:
        op.finalize()
        self.ops.append(op)
        return op

    # ---------------- layer builders (include/model.h:276-410) ----------
    def conv2d(self, input: Tensor, out_channels: int, kernel_h: int,
               kernel_w: int, stride_h: int, stride_w: int, padding_h: int,
               padding_w: int, activation=None, groups: int = 1,
               use_bias: bool = True, name: Optional[str] = None,
               kernel_initializer="glorot", bias_initializer="zeros") -> Tensor:
        op = Conv2D(self, name or self._fresh_name("conv2d"), [input],
                    out_channels, kernel_h, kernel_w, stride_h, stride_w,
                    padding_h, padding_w, activation or "none", groups,
                    use_bias, kernel_initializer, bias_initializer)
        return self.add_op(op).output

    def dense(self, input: Tensor, out_channels: int, activation=None,
              use_bias: bool = True, name: Optional[str] = None,
              kernel_initializer="glorot", bias_initializer="zeros") -> Tensor:
        op = Linear(self, name or self._fresh_name("dense"), [input],
                    out_channels, activation or "none", use_bias,
                    kernel_initializer, bias_initializer)
        return self.add_op(op).output

    def embedding(self, input: Tensor, num_entries: int, out_dim: int,
                  aggr: str = "sum", name: Optional[str] = None,
                  kernel_initializer="glorot", dtype=None) -> Tensor:
        op = Embedding(self, name or self._fresh_name("embedding"), [input],
                       num_entries, out_dim, aggr, kernel_initializer,
                       dtype=dtype)
        return self.add_op(op).output

    def distributed_embedding(self, inputs: Sequence[Tensor],
                              num_entries: int, out_dim: int,
                              aggr: str = "sum",
                              name: Optional[str] = None,
                              kernel_initializer="glorot",
                              dtype=None) -> List[Tensor]:
        """E same-vocab embedding bags as one table-axis-shardable stacked
        weight — the executable form of the reference's per-device table
        placement (DLRM strategies, dlrm_strategy.cc:1-50). Returns one
        (batch, out_dim) tensor per input, in order."""
        from .ops import DistributedEmbedding
        op = DistributedEmbedding(
            self, name or self._fresh_name("dist_embedding"), list(inputs),
            num_entries, out_dim, aggr, kernel_initializer, dtype)
        self.add_op(op)
        return list(op.outputs)

    def pool2d(self, input: Tensor, kernel_h: int, kernel_w: int,
               stride_h: int, stride_w: int, padding_h: int, padding_w: int,
               pool_type: str = "max", activation=None,
               name: Optional[str] = None) -> Tensor:
        op = Pool2D(self, name or self._fresh_name("pool2d"), [input],
                    kernel_h, kernel_w, stride_h, stride_w, padding_h,
                    padding_w, pool_type, activation or "none")
        return self.add_op(op).output

    def batch_norm(self, input: Tensor, relu: bool = True,
                   name: Optional[str] = None) -> Tensor:
        op = BatchNorm(self, name or self._fresh_name("batch_norm"),
                       [input], relu)
        return self.add_op(op).output

    def layer_norm(self, input: Tensor, eps: float = 1e-5,
                   elementwise_affine: bool = True,
                   name: Optional[str] = None) -> Tensor:
        from .ops import LayerNorm
        op = LayerNorm(self, name or self._fresh_name("layer_norm"),
                       [input], eps, elementwise_affine)
        return self.add_op(op).output

    def reduce_mean(self, input: Tensor, axis: int, keepdims: bool = False,
                    name: Optional[str] = None) -> Tensor:
        op = Reduce(self, name or self._fresh_name("reduce_mean"),
                    [input], "mean", axis, keepdims)
        return self.add_op(op).output

    def reduce_sum(self, input: Tensor, axis: int, keepdims: bool = False,
                   name: Optional[str] = None) -> Tensor:
        op = Reduce(self, name or self._fresh_name("reduce_sum"),
                    [input], "sum", axis, keepdims)
        return self.add_op(op).output

    def reduce_max(self, input: Tensor, axis: int, keepdims: bool = False,
                   name: Optional[str] = None) -> Tensor:
        op = Reduce(self, name or self._fresh_name("reduce_max"),
                    [input], "max", axis, keepdims)
        return self.add_op(op).output

    def batch_matmul(self, a: Tensor, b: Tensor,
                     a_seq_length_dim: int = -1, b_seq_length_dim: int = -1,
                     name: Optional[str] = None) -> Tensor:
        op = BatchMatmul(self, name or self._fresh_name("batch_matmul"),
                         [a, b], a_seq_length_dim, b_seq_length_dim)
        return self.add_op(op).output

    def dropout(self, input: Tensor, rate: float, seed: int = 0,
                name: Optional[str] = None) -> Tensor:
        op = Dropout(self, name or self._fresh_name("dropout"), [input],
                     rate, seed)
        return self.add_op(op).output

    def multihead_attention(self, query: Tensor, key: Tensor, value: Tensor,
                            embed_dim: int, num_heads: int, kdim: int = 0,
                            vdim: int = 0, dropout: float = 0.0,
                            bias: bool = True, add_bias_kv: bool = False,
                            add_zero_attn: bool = False,
                            causal: bool = False,
                            name: Optional[str] = None,
                            kernel_initializer="glorot",
                            use_flash=None) -> Tensor:
        op = MultiHeadAttention(
            self, name or self._fresh_name("attention"), [query, key, value],
            embed_dim, num_heads, kdim, vdim, dropout, bias, add_bias_kv,
            add_zero_attn, causal, kernel_initializer, use_flash)
        return self.add_op(op).output

    # elementwise unary (model.h exp/relu/sigmoid/tanh/elu/scalar ops)
    def _unary(self, mode, input, name=None, scalar=None) -> Tensor:
        op = ElementUnary(self, name or self._fresh_name(mode), [input],
                          mode, scalar)
        return self.add_op(op).output

    def exp(self, input, name=None):
        return self._unary("exp", input, name)

    def relu(self, input, name=None):
        return self._unary("relu", input, name)

    def sigmoid(self, input, name=None):
        return self._unary("sigmoid", input, name)

    def tanh(self, input, name=None):
        return self._unary("tanh", input, name)

    def elu(self, input, name=None):
        return self._unary("elu", input, name)

    def gelu(self, input, name=None):
        return self._unary("gelu", input, name)

    def identity(self, input, name=None):
        return self._unary("identity", input, name)

    def scalar_multiply(self, input, scalar, name=None):
        return self._unary("scalar_multiply", input, name, scalar=scalar)

    # elementwise binary
    def _binary(self, mode, a, b, name=None) -> Tensor:
        op = ElementBinary(self, name or self._fresh_name(mode), [a, b], mode)
        return self.add_op(op).output

    def add(self, a, b, name=None):
        return self._binary("add", a, b, name)

    def subtract(self, a, b, name=None):
        return self._binary("subtract", a, b, name)

    def multiply(self, a, b, name=None):
        return self._binary("multiply", a, b, name)

    def divide(self, a, b, name=None):
        return self._binary("divide", a, b, name)

    def max(self, a, b, name=None):
        return self._binary("max", a, b, name)

    def min(self, a, b, name=None):
        return self._binary("min", a, b, name)

    # shape ops
    def concat(self, tensors: Sequence[Tensor], axis: int,
               name: Optional[str] = None) -> Tensor:
        op = Concat(self, name or self._fresh_name("concat"), list(tensors),
                    axis)
        return self.add_op(op).output

    def split(self, input: Tensor, sizes: Union[int, Sequence[int]],
              axis: int, name: Optional[str] = None) -> List[Tensor]:
        if isinstance(sizes, int):
            total = input.shape[axis % len(input.shape)]
            assert total % sizes == 0
            sizes = [total // sizes] * sizes
        op = Split(self, name or self._fresh_name("split"), [input],
                   list(sizes), axis)
        return list(self.add_op(op).outputs)

    def flat(self, input: Tensor, name: Optional[str] = None) -> Tensor:
        op = Flat(self, name or self._fresh_name("flat"), [input])
        return self.add_op(op).output

    def reshape(self, input: Tensor, shape: Sequence[int],
                name: Optional[str] = None) -> Tensor:
        op = Reshape(self, name or self._fresh_name("reshape"), [input],
                     tuple(shape))
        return self.add_op(op).output

    def transpose(self, input: Tensor, perm: Sequence[int],
                  name: Optional[str] = None) -> Tensor:
        op = Transpose(self, name or self._fresh_name("transpose"), [input],
                       list(perm))
        return self.add_op(op).output

    def reverse(self, input: Tensor, axis: int,
                name: Optional[str] = None) -> Tensor:
        op = Reverse(self, name or self._fresh_name("reverse"), [input], axis)
        return self.add_op(op).output

    def top_k(self, input: Tensor, k: int, sorted: bool = True,
              name: Optional[str] = None) -> Tuple[Tensor, Tensor]:
        op = TopK(self, name or self._fresh_name("topk"), [input], k, sorted)
        self.add_op(op)
        return op.outputs[0], op.outputs[1]

    def softmax(self, input: Tensor, axis: int = -1,
                name: Optional[str] = None) -> Tensor:
        op = Softmax(self, name or self._fresh_name("softmax"), [input], axis)
        return self.add_op(op).output

    def group_by(self, data: Tensor, assign: Tensor, n: int, alpha: float,
                 name: Optional[str] = None) -> List[Tensor]:
        op = GroupBy(self, name or self._fresh_name("group_by"),
                     [data, assign], n, alpha)
        return list(self.add_op(op).outputs)

    def aggregate(self, gate_preds: Tensor, gate_assign: Tensor,
                  exp_preds: Sequence[Tensor], n: int,
                  name: Optional[str] = None) -> Tensor:
        op = Aggregate(self, name or self._fresh_name("aggregate"),
                       [gate_preds, gate_assign] + list(exp_preds), n)
        return self.add_op(op).output


    def moe_ffn(self, input: Tensor, num_experts: int, k: int,
                hidden_dim: int, out_dim: int = None,
                capacity_factor: float = 1.25, activation="relu",
                aux_loss_weight: float = 1e-2,
                name: Optional[str] = None) -> Tensor:
        """Fused expert-parallel MoE FFN (TPU-first EP; the composable
        reference path softmax+topk+group_by+aggregate also exists)."""
        op = MoEFFN(self, name or self._fresh_name("moe_ffn"), [input],
                    num_experts, k, hidden_dim, out_dim, capacity_factor,
                    activation, aux_loss_weight)
        return self.add_op(op).output


    def pipeline_blocks(self, input: Tensor, block_builder, num_layers: int,
                        num_microbatches: int = 4,
                        name: Optional[str] = None) -> Tensor:
        """Stack of identical shape-preserving blocks with first-class
        pipeline parallelism (GPipe schedule when the strategy maps the
        `layer` axis to a mesh `pipe` axis). block_builder(sub_model, t)
        builds one block with the normal layer API."""
        op = PipelineBlocks(self, name or self._fresh_name("pipeline"),
                            [input], block_builder, num_layers,
                            num_microbatches)
        return self.add_op(op).output

    def lstm(self, input: Tensor, hidden_size: int,
             return_sequences: bool = True,
             name: Optional[str] = None, use_pallas=None) -> Tensor:
        op = LSTM(self, name or self._fresh_name("lstm"), [input],
                  hidden_size, return_sequences, use_pallas=use_pallas)
        return self.add_op(op).output

    # ---------------- compile / train ----------------
    @property
    def final_tensor(self) -> Tensor:
        return self.ops[-1].outputs[0]

    def compile(self, optimizer: Optional[Optimizer] = None,
                loss_type: Optional[str] = "sparse_categorical_crossentropy",
                metrics: Optional[Sequence[str]] = None,
                comp_mode: str = CompMode.TRAINING,
                mesh: Optional[Mesh] = None,
                strategy: Optional[Strategy] = None) -> None:
        """Reference: FFModel::compile (model.cc:1551-1796). Runs strategy
        search when config.search_budget > 0, builds the executor, and
        initializes parameters (sharded per strategy)."""
        self.config.validate()  # catch post-construction field edits
        if mesh is not None:
            self.mesh = mesh
        if strategy is not None:
            self.strategy = strategy
        if optimizer is None:
            optimizer = SGDOptimizer(lr=self.config.learning_rate)
        self.optimizer = optimizer

        if self.strategy is None and self.config.import_strategy_file:
            self.strategy = self._load_strategy_file(
                self.config.import_strategy_file)

        if self.config.search_budget > 0:
            if self.config.search_mesh_shapes:
                # joint (strategy, mesh-factorization) search — the
                # degree dimension of the reference's space (model.cc:512)
                from .search.mcmc import optimize_with_mesh
                self.strategy, self.mesh = optimize_with_mesh(
                    self, budget=self.config.search_budget,
                    alpha=self.config.search_alpha)
            else:
                from .search.mcmc import optimize
                self.strategy = optimize(
                    self, budget=self.config.search_budget,
                    alpha=self.config.search_alpha)
            if self.config.export_strategy_file:
                self.strategy.save(self.config.export_strategy_file)

        # a search-discovered interleaved pipeline rides the strategy's
        # `pipeline` block (pins cannot express v stages per device) —
        # apply it to the config knobs the auto-cut lowering below
        # reads, so --import replays the whole exported plan
        pl = (getattr(self.strategy, "pipeline", None)
              if self.strategy is not None else None)
        if pl:
            if not isinstance(pl, dict) \
                    or not isinstance(pl.get("stages"), int) \
                    or pl["stages"] < 1:
                # Strategy.load validates files; this guards strategies
                # constructed in code with a malformed block
                raise ValueError(
                    f"strategy.pipeline must be a dict with an int "
                    f"\"stages\" >= 1 (got {pl!r})")
            self.config.pipeline_stages = pl["stages"]
            self.config.pipeline_virtual_stages = int(
                pl.get("virtual_stages", 1))
            self.config.pipeline_schedule = pl.get(
                "schedule", self.config.pipeline_schedule)
            self.config.pipeline_microbatches = int(pl.get(
                "microbatches", self.config.pipeline_microbatches))
            self.config.validate()

        # device-explicit placement lowering. Per-table ids on
        # distributed_embedding execute via the slot layout
        # (ops/embedding.py apply_placement). Whole-op pins on other ops
        # execute as PIPELINE STAGES: stage order = device-id order,
        # microbatches stream over the mesh pipe axis
        # (core/staged.py; the executable analog of slice_task routing,
        # mapper.cc:346-440). Pins that cannot form a forward pipeline
        # (or lack a matching mesh axis) fall back to replication with
        # a warning.
        stage_of = None
        pipe_axis = None
        vstages_applied = False
        if self.strategy is not None and self.mesh is not None:
            from .parallel.graph_pipeline import (
                assignment_from_pins, build_stage_plan, pick_pipe_axis)
            try:
                stage_of = assignment_from_pins(self, self.strategy)
                if stage_of is not None:
                    build_stage_plan(self, stage_of)  # viability check
            except (ValueError, NotImplementedError) as e:
                import warnings
                warnings.warn(
                    f"strategy pins ops to explicit devices but the "
                    f"placement cannot execute as a pipeline "
                    f"({e}); falling back to replication")
                stage_of = None
            if stage_of is not None:
                n_stages = max(stage_of.values()) + 1
                if n_stages < 2:
                    import warnings
                    warnings.warn(
                        "strategy pins every op to one device; a "
                        "single-stage placement has no pipelined "
                        "lowering — executing as plain (replicated) "
                        "SPMD")
                    stage_of = None
                else:
                    pipe_axis = pick_pipe_axis(self.mesh, n_stages)
                    if pipe_axis is None:
                        import warnings
                        warnings.warn(
                            f"strategy pins ops across {n_stages} "
                            f"devices but the mesh {self.mesh.shape} "
                            f"has no non-data axis of that size to "
                            f"pipeline over; executing as replication")
                        stage_of = None
        if stage_of is None and self.config.pipeline_stages > 1:
            from .parallel.graph_pipeline import (
                balanced_stages, pick_pipe_axis)
            # interleaving: v round-robin stage chunks per pipe device
            # (Megatron virtual stages; executes under 1f1b)
            vstages = max(1, self.config.pipeline_virtual_stages)
            vstages_applied = True
            stage_of = balanced_stages(
                self, self.config.pipeline_stages * vstages)
            n_stages = max(stage_of.values()) + 1  # clamped to op count
            if n_stages % vstages != 0:
                raise ValueError(
                    f"pipeline_virtual_stages={vstages} needs "
                    f"{self.config.pipeline_stages * vstages} stages "
                    f"but this graph only supports {n_stages} (too few "
                    f"ops); lower the stage or virtual-stage count")
            pipe_axis = (pick_pipe_axis(self.mesh, n_stages // vstages)
                         if self.mesh is not None else None)
            if pipe_axis is None:
                raise ValueError(
                    f"pipeline_stages={self.config.pipeline_stages} "
                    f"(=> {n_stages} stages for this graph) needs a "
                    f"mesh axis of size "
                    f"{max(1, n_stages // vstages)} to pipeline over "
                    f"(mesh: {self.mesh.shape if self.mesh else None})")
        if (stage_of is None and self.strategy is not None
                and self.mesh is None):
            # meshless compile: pins cannot execute at all — surface it
            # (the mesh path warns through the lowering above)
            pinned = [op.name for op in self.ops
                      if self.strategy.for_op(op.name).device_ids
                      and op.op_type != "distributed_embedding"]
            if pinned:
                import warnings
                warnings.warn(
                    f"strategy pins {pinned} to explicit devices but "
                    f"there is no mesh; placement is ignored "
                    f"(replicated single-device execution)")

        if self.config.pipeline_virtual_stages > 1 \
                and not vstages_applied:
            import warnings
            warnings.warn(
                "pipeline_virtual_stages > 1 only applies to auto-cut "
                "pipelines (--pipeline-stages); this compile's stages "
                "come from pins or no pipeline at all — interleaving "
                "was NOT applied")

        # Executor validates comp_mode; assign OURS only after it
        # succeeds so a rejected compile leaves the previous mode live
        if stage_of is not None and pipe_axis is not None:
            from .core.staged import StagedExecutor
            self.executor = StagedExecutor(
                self, optimizer, loss_type, metrics, mesh=self.mesh,
                strategy=self.strategy, comp_mode=comp_mode,
                stage_of=stage_of, pipe_axis=pipe_axis,
                num_microbatches=self.config.pipeline_microbatches,
                schedule=self.config.pipeline_schedule)
        else:
            self.executor = Executor(
                self, optimizer, loss_type, metrics,
                mesh=self.mesh, strategy=self.strategy,
                comp_mode=comp_mode)
        self.comp_mode = comp_mode
        self.state = self.executor.init_state(self._next_rng())
        self._host_step = 0  # mirrors state.step for the train rng
        for op_name, ws in self.imported_weights.items():
            self.set_weights(op_name, ws)
        for op_name, ss in self.imported_states.items():
            self.set_states(op_name, ss)

    def _load_strategy_file(self, path: str) -> Strategy:
        """--import-strategy dispatch: our JSON format, the reference's
        FFProtoBuf .pb artifacts, or strategy.cc's text stream."""
        from .parallel.strategy_io import load_reference_strategy_file
        if not path.endswith(".pb"):
            try:
                return Strategy.load(path)
            except (ValueError, UnicodeDecodeError):
                pass  # not our JSON: try the reference text format
        if self.mesh is None:
            raise ValueError(
                f"importing the reference strategy format from {path!r} "
                f"needs a mesh (splits/device ids resolve against mesh "
                f"axes); pass mesh= or use the native JSON format")
        return load_reference_strategy_file(self, self.mesh, path)

    def _next_rng(self):
        self._rng, sub = jax.random.split(self._rng)
        return sub

    def _train_rng(self):
        """Per-step training rng (dropout etc.), keyed on a host-side
        step mirror instead of a split chain so a checkpoint-resumed run
        reproduces the exact stream of the uninterrupted one (the mirror
        is re-synced from state.step at resume, fit())."""
        sub = jax.random.fold_in(self._rng, self._host_step)
        self._host_step += 1
        return sub

    # reference-parity train-loop primitives (model.cc:1414-1461). On TPU
    # forward/backward/update are one fused jitted step; these methods keep
    # the imperative API by staging a batch and running the step on update.
    def init_layers(self):
        if self.state is None:
            self.compile()

    def forward(self, batch: Dict[str, np.ndarray]):
        batch = self.executor.shard_batch(batch)
        logits, metrics = self.executor.eval_step(self.state, batch)
        return logits

    def zero_gradients(self):
        pass  # gradients are pure values on TPU; nothing to zero

    def compile_counts(self) -> Dict[str, int]:
        """Exact compiles per train-program family this process
        performed (the executor's ProgramRegistry query — the serving
        engines' zero-recompile instrument, extended to fit). Empty
        before the first train dispatch; a step resolved from a
        --program-cache-dir snapshot counts zero."""
        return self.executor.compile_counts()

    def train_batch(self, batch: Dict[str, np.ndarray]):
        """One optimizer step; returns metrics dict of scalars."""
        batch = self.executor.shard_batch(batch)
        self.state, metrics = self.executor.train_step(
            self.state, batch, self._train_rng())
        return metrics

    def train_batches(self, batches: Sequence[Dict[str, np.ndarray]]):
        """Run len(batches) optimizer steps in ONE device dispatch
        (`lax.scan` over the step axis) — the TPU analog of the
        reference's per-iteration Legion trace replay (begin_trace/
        end_trace, alexnet.cc:106-111): dependence analysis and dispatch
        cost are paid once for the whole group, not per step. Essential
        through a remote-TPU tunnel where each dispatch costs
        milliseconds. The RNG stream is identical to calling
        `train_batch` len(batches) times.

        Returns the metrics dict with a leading (K,) step axis on every
        value (one bulk `jax.device_get` fetches the whole group —
        per-step slicing would reintroduce a dispatch per scalar).

        `batches` may also be a group pre-staged by `stage_batches`
        (reused across calls without re-staging — the synthetic-data
        training-loop pattern, reference `syntheticInput`
        config.h:131)."""
        if isinstance(batches, dict):  # pre-staged by stage_batches
            stacked = batches
            k = int(next(iter(stacked.values())).shape[0])
        else:
            k = len(batches)
            if k == 0:
                return {}
            stacked = self.executor.shard_batch_stacked(list(batches))
        rngs = jnp.stack([jax.random.fold_in(self._rng, self._host_step + i)
                          for i in range(k)])
        self._host_step += k
        self.state, metrics = self.executor.train_step_multi(
            self.state, stacked, rngs)
        return metrics

    def train_batch_accum(self, microbatches:
                          Sequence[Dict[str, np.ndarray]]):
        """ONE optimizer step over K microbatches (gradient
        accumulation): gradients are computed per microbatch under
        `lax.scan`, summed, and applied once — the large-batch result
        without K x the activation memory. Sparse embedding rows
        concatenate across microbatches into a single scatter update, so
        the step equals a K x-sized batch exactly (BN stats advance per
        microbatch). Returns one metrics dict (loss = mean; sum-style
        metrics folded over the group)."""
        k = len(microbatches)
        if k == 0:
            return {}
        stacked = self.executor.shard_batch_stacked(list(microbatches))
        # ONE optimizer step -> _host_step advances by ONE (it mirrors
        # state.step, which checkpoint resume resyncs from); the K
        # microbatch keys are sub-keys of this step's key (double
        # fold_in), so they never collide with other steps' streams
        base = jax.random.fold_in(self._rng, self._host_step)
        rngs = jnp.stack([jax.random.fold_in(base, i) for i in range(k)])
        self._host_step += 1
        self.state, metrics = self.executor.train_step_accum(
            self.state, stacked, rngs)
        return metrics

    def stage_batches(self, batches: Sequence[Dict[str, np.ndarray]]):
        """Pre-stage K batches as one stacked device-resident group for
        repeated `train_batches` calls. One host->device transfer total;
        pass the result to `train_batches` as many times as needed."""
        return self.executor.shard_batch_stacked(list(batches))

    def calibrate_simulator(self, batch: Optional[Dict] = None,
                            steps: int = 10):
        """Ground the execution simulator in a real measured step (the
        analog of the reference grounding every simulated cost in real
        on-device kernel timings, src/runtime/model.cu:20-62): measure
        `steps` training steps, set the simulator's end-to-end time
        scale, and keep it as `self.simulator` for later queries.

        Returns (measured_step_seconds, predicted_step_seconds) where the
        prediction is the simulator's PRE-calibration estimate — the
        number to hold against the MLSys'19 <30% simulator-error envelope
        (BASELINE.md). Requires compile() first."""
        from .parallel.mesh import single_device_mesh
        from .search.measure import calibrated_machine_model
        from .search.simulator import Simulator

        assert self.executor is not None, "compile() before calibrating"
        if batch is None:
            from .core.dataloader import synthetic_batch
            batch = synthetic_batch(self)
        mesh = self.mesh or single_device_mesh()
        sim = Simulator(
            self, mesh,
            calibrated_machine_model(
                mesh, machine_file=self.config.machine_model_file))
        strategy = self.strategy or Strategy()
        predicted = sim.simulate(strategy)
        # warmup (jit compile), then measure; a device->host scalar fetch
        # delimits timing (block_until_ready does not sync through the
        # remote TPU tunnel)
        m = self.train_batch(batch)
        float(m["loss"])
        t0 = time.perf_counter()
        for _ in range(steps):
            m = self.train_batch(batch)
        float(m["loss"])
        measured = (time.perf_counter() - t0) / steps
        sim.calibrate_end_to_end(strategy, measured)
        self.simulator = sim
        return measured, predicted

    def fit(self, x: Dict[str, np.ndarray], y: np.ndarray,
            batch_size: Optional[int] = None, epochs: Optional[int] = None,
            shuffle: bool = True, verbose: bool = True,
            checkpoint_dir: Optional[str] = None,
            checkpoint_every: int = 1,
            steps_per_dispatch="auto",
            prefetch: bool = False,
            grad_accum_steps: int = 1):
        """Keras-style fit over host numpy arrays (reference:
        base_model.py:195-255 + _train loop :347-424).

        `checkpoint_dir` enables the elastic-recovery story the reference
        lacks (SURVEY 5: no failure handling): the full TrainState is
        saved asynchronously every `checkpoint_every` epochs, and a
        re-run with the same directory resumes from the newest epoch —
        kill the process at any point and simply run it again.

        `grad_accum_steps=K` turns each group of K consecutive
        microbatches into ONE optimizer step (train_batch_accum):
        effective batch K*batch_size without the activation memory.

        `steps_per_dispatch="auto"` (default) groups 8 steps per device
        dispatch on TPU backends and 1 elsewhere — the reference traces
        EVERY training iteration (begin/end_trace, alexnet.cc:106-111),
        and this is the dispatch-grouped analog; pass an int to pin."""
        steps_per_dispatch = _resolve_steps_per_dispatch(
            steps_per_dispatch, grad_accum_steps)
        if grad_accum_steps > 1 and steps_per_dispatch > 1:
            raise ValueError(
                "grad_accum_steps and steps_per_dispatch are both dispatch "
                "groupings; use one or the other")
        bs = batch_size or self.config.batch_size
        ep = epochs or self.config.epochs
        names = list(x.keys())
        n = len(y)
        steps = n // bs
        # persistent across fit() calls so per-epoch shuffles differ even
        # when a wrapper drives one epoch at a time (keras frontend);
        # _fit_epochs_drawn counts permutations already consumed so a
        # checkpoint resume replays exactly the missing prefix
        fit_loader = None  # local: bound to this call's x/y arrays
        if not hasattr(self, "_fit_rng"):
            self._fit_rng = np.random.RandomState(self.config.seed)
            self._fit_epochs_drawn = 0
        rng = self._fit_rng

        def draw_perm():
            self._fit_epochs_drawn += 1
            return rng.permutation(n)

        # pipelined host dispatch (core/overlap.DispatchWindow): up to
        # `train_dispatch_depth` dispatches stay in flight before the
        # OLDEST step's metrics are pulled to host, so retrieval of step
        # N overlaps device execution of step N+1 — the host never
        # blocks on the newest dispatch except at epoch/checkpoint
        # boundaries (window drain). Each dispatch is a marked fault
        # site ("train.dispatch") fired BEFORE the jitted call so an
        # injected fault never consumes the donated state buffers.
        from .core.overlap import DispatchWindow
        from .utils import faults as _faults
        from .utils.telemetry import telemetry_for, train_metrics
        inj = _faults.injector_for(self.config)
        # observability (utils/telemetry.py): dispatch/fetch spans on
        # the train tracks, the metrics registry train_report renders
        # from, and the per-epoch simulator-drift sample (measured
        # step time vs the overlap-exact graph's prediction). All
        # host-side — telemetry on vs off trains bit-identically.
        tel = telemetry_for(self.config)
        self.telemetry = tel
        # re-price the drift prediction per fit(): the strategy, mesh
        # or bucket layout may have changed since the last fit, and a
        # transient pricing failure must not latch None forever
        self.__dict__.pop("_drift_predicted_step_s", None)
        _compiles = None
        if tel.enabled:
            # process-wide backend-compile counter (the serve engine's
            # zero-recompile instrument): an epoch whose window saw a
            # compile (epoch 0's jit, a mid-fit new shape signature)
            # must not feed the drift calibrator — compile seconds are
            # not step time, and one contaminated sample poisons the
            # regime average
            from .serve.engine import _CompileEvents
            if _CompileEvents.install():
                _compiles = _CompileEvents
        win = DispatchWindow(
            getattr(self.config, "train_dispatch_depth", 2),
            telemetry=tel)
        gaps: List[float] = []   # host time between dispatches (prep)
        n_dispatches = [0]
        last_end = [None]

        def _dispatch(fn, *args):
            t = time.perf_counter()
            if last_end[0] is not None:
                gaps.append(t - last_end[0])
            inj.fire("train.dispatch")
            out = fn(*args)
            last_end[0] = time.perf_counter()
            n_dispatches[0] += 1
            if tel.enabled:
                tel.span(("train", "dispatch"), "dispatch", t,
                         last_end[0],
                         args={"dispatch": n_dispatches[0] - 1})
            return out

        history = []
        start_epoch = 0
        ckptr = None  # one async checkpointer reused across the run
        if checkpoint_dir:
            from .core.checkpoint import restore_model, save_checkpoint
            # the name filter also skips uncommitted crash leftovers:
            # save_checkpoint stages into `epoch_N.tmp` / `epoch_N.old`
            # and only an atomic promote produces a bare `epoch_N`, so
            # a kill-mid-save run resumes from the newest COMMITTED
            # epoch (docs/robustness.md). A promote killed inside its
            # rename window strands the committed dir at `.old` —
            # recover those first so the scan can see them.
            if os.path.isdir(checkpoint_dir):
                from .core.checkpoint import recover_promoted
                for d in os.listdir(checkpoint_dir):
                    if d.startswith("epoch_") and d.endswith(".old"):
                        recover_promoted(
                            os.path.join(checkpoint_dir, d[:-len(".old")]))
            done = sorted(
                int(d[len("epoch_"):]) for d in (
                    os.listdir(checkpoint_dir)
                    if os.path.isdir(checkpoint_dir) else [])
                if d.startswith("epoch_")
                and d[len("epoch_"):].isdigit())
            while done:
                # a committed dir can still be damaged out-of-band
                # (disk fault, manual edit): fall back epoch by epoch
                # rather than failing the whole run
                try:
                    restore_model(self, os.path.join(
                        checkpoint_dir, f"epoch_{done[-1]}"))
                    start_epoch = done[-1] + 1
                    break
                except Exception as e:
                    import warnings
                    warnings.warn(
                        f"checkpoint epoch_{done[-1]} unreadable "
                        f"({type(e).__name__}: {e}); falling back to "
                        f"the previous epoch")
                    done.pop()
            if start_epoch:
                # replay ONLY the missing prefix of the shuffle stream so
                # resumed epochs see the permutations the uninterrupted
                # run would have (a same-object continuation has already
                # consumed _fit_epochs_drawn of them)
                if shuffle:
                    while self._fit_epochs_drawn < start_epoch:
                        draw_perm()
                if verbose:
                    print(f"resuming from {checkpoint_dir} at epoch "
                          f"{start_epoch}")
        try:
            for epoch in range(start_epoch, ep):
                idx = draw_perm() if shuffle else np.arange(n)
                t0 = time.time()
                t0pc = time.perf_counter()
                compiles0 = _compiles.count if _compiles else 0
                spd = max(1, steps_per_dispatch)

                if prefetch:
                    # host row-gather on the native loader's background
                    # thread (double-buffered, csrc/dataloader.cc) — the
                    # prefetch analog of the reference's next_batch index
                    # launches — driven by fit's OWN permutation so the
                    # checkpoint-resume shuffle replay is unchanged
                    if fit_loader is None:
                        from .core.dataloader import DataLoaderSet
                        fit_loader = DataLoaderSet(
                            {**{k: x[k] for k in names}, "label": y},
                            bs, mesh=self.mesh, shuffle=False,
                            dtypes=self.executor.declared_input_dtypes)
                    it = fit_loader.iter_with_order(idx)

                    def mk_batch(s):
                        return next(it)
                else:
                    def mk_batch(s):
                        sel = idx[s * bs:(s + 1) * bs]
                        batch = {k: x[k][sel] for k in names}
                        batch["label"] = y[sel]
                        return batch

                # full groups go through the scanned multi-step (one
                # dispatch per group, trace-replay analog) or the
                # accumulation step (one UPDATE per group). Tails differ:
                # for dispatch grouping the split is semantics-neutral so
                # the tail takes single steps (only two program shapes
                # compile); for ACCUMULATION the grouping IS the
                # semantics, so the tail is accumulated as one smaller
                # group rather than demoted to microbatch-sized updates.
                # epoch_metrics entries: (metrics, loss_weight) where
                # loss_weight = microbatches represented by the entry's
                # (mean) loss; None = per-step stacked losses.
                gas = max(1, grad_accum_steps)
                group = gas if gas > 1 else spd
                if group == 1:
                    # plain single-step path: no scan-of-1 wrapper, no
                    # per-step np.stack — leaner default dispatch
                    for s in range(steps):
                        win.push(
                            (_dispatch(self.train_batch, mk_batch(s)),
                             1))
                    tail = []
                else:
                    for s0 in range(0, steps - steps % group, group):
                        mbs = [mk_batch(s) for s in range(s0, s0 + group)]
                        if gas > 1:
                            win.push((_dispatch(self.train_batch_accum,
                                                mbs), len(mbs)))
                        else:
                            win.push((_dispatch(self.train_batches,
                                                mbs), None))
                    tail = list(range(steps - steps % group, steps))
                if tail and gas > 1:
                    mbs = [mk_batch(s) for s in tail]
                    win.push((_dispatch(self.train_batch_accum, mbs),
                              len(mbs)))
                else:
                    for s in tail:
                        win.push(
                            (_dispatch(self.train_batch, mk_batch(s)),
                             1))
                # fold metrics on host (reference: UPDATE_METRICS future
                # fold). The dispatch window already pulled all but the
                # last depth-1 entries while later steps ran on device;
                # the epoch-boundary drain fetches the remainder —
                # per-scalar float(v) would issue steps*keys tiny
                # transfers (ruinous through a TPU tunnel); reference
                # folds through futures too (model.cc:2084-2108).
                epoch_metrics = win.drain()
                agg = {}
                loss_terms = 0
                for m, w in epoch_metrics:
                    for k, v in m.items():
                        if k == "loss":
                            # weight each entry's (mean) loss by the
                            # microbatches it represents so the epoch
                            # loss is the true per-microbatch mean
                            if w is None:  # (K,) per-step losses
                                agg[k] = agg.get(k, 0.0) + float(np.sum(v))
                                loss_terms += int(np.size(v))
                            else:
                                agg[k] = agg.get(k, 0.0) + float(v) * w
                                loss_terms += w
                        else:
                            agg[k] = agg.get(k, 0.0) + float(np.sum(v))
                dt = time.time() - t0
                if tel.enabled:
                    t1pc = time.perf_counter()
                    tel.span(("train", "epoch"), f"epoch {epoch}",
                             t0pc, t1pc, args={"steps": steps})
                    # the train half of the drift calibrator: measured
                    # wall per step (dispatch + device + fetch, the
                    # number a capacity planner sees) against the
                    # overlap-exact task graph's prediction for this
                    # model/mesh/bucket layout
                    # an epoch containing a backend compile records no
                    # drift sample (when the compile counter is
                    # unavailable, the first epoch — where the cold
                    # jit lives — is skipped instead)
                    compiled = (_compiles.count > compiles0 if _compiles
                                else epoch == start_epoch)
                    if steps and not compiled:
                        pred = self._predicted_step_s()
                        if pred and pred[0]:
                            tel.record_drift(
                                "train",
                                f"bs={bs} group={group} "
                                f"accum={grad_accum_steps}",
                                pred[0], (t1pc - t0pc) / steps,
                                breakdown=pred[1])
                out = {"epoch": epoch,
                       "loss": agg.get("loss", 0.0) / max(1, loss_terms),
                       "throughput": steps * bs / dt}
                if "correct" in agg:
                    out["accuracy"] = agg["correct"] / agg["count"]
                history.append(out)
                if verbose:
                    acc = (f" accuracy={out['accuracy']:.4f}"
                           if "accuracy" in out else "")
                    print(f"epoch {epoch}: loss={out['loss']:.4f}{acc} "
                          f"({out['throughput']:.1f} samples/s)")
                if checkpoint_dir \
                        and (epoch + 1) % max(1, checkpoint_every) == 0:
                    # reused AsyncCheckpointer: orbax serializes against
                    # the in-flight save itself
                    ckptr = save_checkpoint(
                        os.path.join(checkpoint_dir, f"epoch_{epoch}"),
                        self.state, use_async=True, checkpointer=ckptr)
        finally:
            # drain the window even on a mid-epoch fault: in-flight
            # dispatches already mutated self.state, so their results
            # must be consumed (not leaked as device handles) before
            # the exception propagates
            in_flight_at_exit = win.pending()
            try:
                win.drain()
            except Exception:
                pass
            self.last_train_stats = self._train_stats(
                win, gaps, n_dispatches[0], in_flight_at_exit)
            if tel.enabled:
                # fold into the canonical registry train_report renders
                # from, then flush the Chrome trace when --trace-out
                # asked for one (the finally runs on faults too, so
                # chaos runs leave a trace behind)
                train_metrics(self.last_train_stats,
                              registry=tel.metrics)
                trace_out = getattr(self.config, "trace_out", None)
                if trace_out:
                    try:
                        tel.export_chrome_trace(trace_out)
                    except OSError:
                        pass  # an unwritable path must not fail fit
            if ckptr is not None:  # commit in-flight saves even on
                ckptr.wait_until_finished()  # Ctrl-C / mid-epoch errors
                ckptr.close()
            if fit_loader is not None:  # release the native prefetch
                fit_loader.close()      # thread + double buffers
            # snapshot freshly compiled train executables to
            # --program-cache-dir (core/programs.py) so the next
            # process over this config resolves fit's step from disk
            # instead of recompiling (no-op when unarmed/clean)
            try:
                self.executor.save_programs()
            except Exception:
                pass  # an unwritable cache dir must not fail fit
        return history

    def _train_stats(self, win, gaps, n_dispatches, in_flight_at_exit):
        """Overlap-runtime instrumentation for one fit() run — rendered
        by utils/profiling.train_report."""
        waits = sorted(win.fetch_waits_s)
        sg = sorted(gaps)
        buckets = (self.executor.grad_bucket_info()
                   if hasattr(self.executor, "grad_bucket_info")
                   else {"count": 0, "bucket_mb": 0.0, "bytes": []})
        dp = (self.mesh.shape.get("data", 1)
              if self.mesh is not None else 1)
        nb = buckets["count"]
        # structural estimate: every bucket except the last-completing
        # one can hide its all-reduce behind remaining backward compute
        est_hidden = (1.0 - 1.0 / nb) if (nb > 1 and dp > 1) else 0.0
        return {
            "dispatches": n_dispatches,
            "dispatch_depth": win.depth,
            "max_in_flight": win.max_in_flight,
            "in_flight_at_exit": in_flight_at_exit,
            "pending_after_drain": win.pending(),
            "dispatch_gap_s_mean": (sum(sg) / len(sg)) if sg else 0.0,
            "dispatch_gap_s_p50": sg[len(sg) // 2] if sg else 0.0,
            "dispatch_gap_s_max": sg[-1] if sg else 0.0,
            "fetch_wait_s_total": sum(waits),
            "fetch_wait_s_max": waits[-1] if waits else 0.0,
            "grad_buckets": buckets,
            "data_parallel": dp,
            "est_comm_hidden": est_hidden,
        }

    def _predicted_step_s(self) -> Optional[tuple]:
        """(predicted seconds per training step, per-task-class
        breakdown) for THIS model on its mesh/strategy — the
        overlap-exact task graph the strategy search prices
        (search/simulator.Simulator), which is exactly what the
        telemetry drift calibrator must compare measured steps against
        (the breakdown is the attribution vector drift_report folds
        per task class). Cached on the model for the duration
        of one fit() — fit's prologue drops the cache, so a strategy/
        mesh/bucket change between fits re-prices and a transient
        failure cannot latch None forever; None when the model/mesh
        cannot be priced (drift simply goes unrecorded)."""
        if not hasattr(self, "_drift_predicted_step_s"):
            try:
                from .parallel.pconfig import Strategy
                from .search.simulator import Simulator
                mesh = self.mesh
                if mesh is None:
                    mesh = make_mesh((1,), ("data",))
                sim = Simulator(self, mesh)
                strat = (self.strategy if self.strategy is not None
                         else Strategy())
                self._drift_predicted_step_s = (
                    float(sim.simulate(strat)),
                    sim.step_breakdown(strat))
            except Exception:
                self._drift_predicted_step_s = None
        return self._drift_predicted_step_s

    def memory_ledger(self) -> dict:
        """Per-device HBM byte accounting for training — params and
        optimizer state from the LIVE device buffers (shard-aware
        nbytes, search/explain.pytree_device_bytes) next to the
        simulator's HBM-penalty input (Simulator.memory_per_device —
        weights + optimizer mirror + activation estimate per op), with
        the residual reported as the activation estimate. Components
        land as ``train_hbm_bytes{component=...}`` gauges when a fit()
        telemetry bus is live."""
        from .search.explain import pytree_device_bytes
        params = opt = 0.0
        if self.state is not None:
            params = pytree_device_bytes(self.state.params)
            opt = pytree_device_bytes(self.state.opt_state)
        sim_bytes = None
        try:
            from .parallel.pconfig import Strategy
            from .search.simulator import Simulator
            mesh = self.mesh
            if mesh is None:
                mesh = make_mesh((1,), ("data",))
            sim = Simulator(self, mesh)
            sim_bytes = float(sim.memory_per_device(
                self.strategy if self.strategy is not None
                else Strategy()))
            hbm = float(sim.mm.spec.hbm_capacity)
        except Exception:
            hbm = None
        ledger = {
            "params_bytes": params,
            "optimizer_bytes": opt,
            "live_bytes": params + opt,
            "sim_hbm_input_bytes": sim_bytes,
            # the cost model's activation/workspace share: its memory
            # input beyond the live persistent buffers
            "activation_est_bytes": (max(0.0, sim_bytes - params - opt)
                                     if sim_bytes is not None else None),
        }
        if hbm:
            ledger["hbm_capacity_bytes"] = hbm
            ledger["hbm_utilization"] = (
                (sim_bytes if sim_bytes is not None
                 else params + opt) / hbm)
        tel = self.telemetry
        if tel is not None and tel.enabled:
            for comp in ("params", "optimizer", "live"):
                tel.metrics.set("train_hbm_bytes",
                                ledger[f"{comp}_bytes"],
                                component=comp)
            if sim_bytes is not None:
                tel.metrics.set("train_hbm_bytes", sim_bytes,
                                component="sim_hbm_input")
        return ledger

    def evaluate(self, x: Dict[str, np.ndarray], y: np.ndarray,
                 batch_size: Optional[int] = None,
                 steps_per_dispatch="auto"):
        bs = batch_size or self.config.batch_size
        names = list(x.keys())
        n = len(y)
        steps = max(1, n // bs)
        spd = max(1, _resolve_steps_per_dispatch(steps_per_dispatch))
        step_metrics = []

        def mk_batch(s):
            sel = slice(s * bs, (s + 1) * bs)
            batch = {k: x[k][sel] for k in names}
            batch["label"] = y[sel]
            return batch

        # grouped read-only dispatches (scan), single-step ragged tail
        for s0 in range(0, steps - steps % spd, spd):
            stacked = self.executor.shard_batch_stacked(
                [mk_batch(s) for s in range(s0, s0 + spd)])
            step_metrics.append(
                self.executor.eval_step_multi(self.state, stacked))
        for s in range(steps - steps % spd, steps):
            sharded = self.executor.shard_batch(mk_batch(s))
            _, m = self.executor.eval_step(self.state, sharded)
            step_metrics.append(m)  # device scalars; convert once at end
        step_metrics = jax.device_get(step_metrics)  # one bulk transfer
        agg: Dict[str, float] = {}
        for m in step_metrics:
            for k, v in m.items():
                # scalar (single-step) or (K,)-stacked (grouped)
                agg[k] = agg.get(k, 0.0) + float(np.sum(v))
        out = {"loss": agg.get("loss", 0.0) / steps}
        if "correct" in agg:
            out["accuracy"] = agg["correct"] / agg["count"]
        return out

    def create_data_loader(self, tensor_or_name, data) -> "SingleDataLoader":
        """Reference parity: FFModel.create_data_loader (cbinding :1618)
        — one loader per (tensor, full numpy dataset)."""
        from .core.dataloader import SingleDataLoader
        name = (tensor_or_name if isinstance(tensor_or_name, str)
                else tensor_or_name.name)
        return SingleDataLoader(name, data, self.config.batch_size,
                                mesh=self.mesh)

    # ---------------- weight access (reference Parameter::get/set) ------
    def get_weights(self, op_name: str) -> Dict[str, np.ndarray]:
        """Host copy of an op's weights (reference Parameter::get_weights,
        model.cu:439-452). Under multi-controller SPMD a weight sharded
        across processes is all-gathered — a COLLECTIVE, so call from
        every process (the normal SPMD discipline)."""
        if hasattr(self.executor, "get_op_weights"):
            # staged (pipelined) executor: weights live flat-packed in
            # per-stage rows; the hook unpacks the op's view
            return self.executor.get_op_weights(self.state, op_name)
        op = next((o for o in self.ops if o.name == op_name), None)
        out = {}
        for k, v in self.state.params[op_name].items():
            if isinstance(v, jax.Array) and not v.is_fully_addressable \
                    and not v.is_fully_replicated:
                # genuinely cross-process-sharded: only a collective can
                # materialize it (replicated weights fetch locally —
                # no communication, callable from one process alone)
                from jax.experimental import multihost_utils
                out[k] = np.asarray(
                    multihost_utils.process_allgather(v, tiled=True))
            else:
                out[k] = np.asarray(v)
            if k == "kernel" and hasattr(op, "to_table_order"):
                # placed stacked embeddings expose TABLE order (pads
                # dropped) — a balanced placement permutes slots, and a
                # raw slot-order copy into another layout would install
                # the wrong rows with no shape error
                out[k] = op.to_table_order(out[k])
        return out

    def set_weights(self, op_name: str, weights: Dict[str, np.ndarray]):
        if hasattr(self.executor, "set_op_weights"):
            self.executor.set_op_weights(self.state, op_name, weights)
            return
        cur = self.state.params[op_name]
        op = next((o for o in self.ops if o.name == op_name), None)
        for k, v in weights.items():
            if (k == "kernel" and hasattr(op, "from_table_order")
                    and getattr(op, "placement", None)
                    and v.shape[0] == op.num_tables
                    and tuple(v.shape[1:]) == tuple(cur[k].shape[1:])):
                # TABLE-ordered kernel (the get_weights form): scatter
                # into the placed slot layout, pads untouched
                v = op.from_table_order(
                    v, np.asarray(cur[k], dtype=np.dtype(cur[k].dtype))
                    if cur[k].is_fully_addressable
                    else np.zeros(cur[k].shape, np.dtype(cur[k].dtype)))
            assert cur[k].shape == v.shape, (op_name, k, cur[k].shape, v.shape)
            # convert on HOST, then device_put with the parameter's
            # sharding: only each device's shard transfers, and the
            # strategy's placement survives (a bare jnp.asarray would
            # stage the whole array on the default device — an OOM for
            # weights that are sharded precisely because they don't fit)
            host = np.asarray(v, dtype=np.dtype(cur[k].dtype))
            cur[k] = place_global(host, cur[k].sharding)

    def set_states(self, op_name: str, states: Dict[str, np.ndarray]):
        """Host set of non-trainable op state (e.g. BN running stats) —
        same role as set_weights for the reference's non-Parameter
        regions."""
        if hasattr(self.executor, "set_op_states"):
            self.executor.set_op_states(self.state, op_name, states)
            return
        cur = self.state.states[op_name]
        for k, v in states.items():
            assert cur[k].shape == v.shape, (op_name, k, cur[k].shape, v.shape)
            host = np.asarray(v, dtype=np.dtype(cur[k].dtype))
            cur[k] = place_global(host, cur[k].sharding)

    def set_learning_rate(self, lr: float) -> None:
        """Runtime LR control (reference keras LearningRateScheduler,
        python/flexflow/keras/callbacks.py:49-62, which rewrote the
        config's lr each epoch): rescales the compiled step's TRACED
        lr input, so a schedule never recompiles the step."""
        base = float(getattr(self.optimizer, "lr", 0.0) or 0.0)
        if base == 0.0:
            raise ValueError(
                "optimizer has no nonzero base lr to schedule against")
        self.executor._lr_scale = float(lr) / base

    def get_learning_rate(self) -> float:
        base = float(getattr(self.optimizer, "lr", 0.0) or 0.0)
        return base * float(getattr(self.executor, "_lr_scale", 1.0))

    def get_states(self, op_name: str) -> Dict[str, np.ndarray]:
        """Host view of non-trainable op state (e.g. BN running
        stats)."""
        if hasattr(self.executor, "get_op_states"):
            return self.executor.get_op_states(self.state, op_name)
        return {k: np.asarray(jax.device_get(v))
                for k, v in self.state.states[op_name].items()}

    def summary(self) -> str:
        lines = [f"{'op':30s} {'type':20s} {'output':24s} {'params':>12s}"]
        total = 0
        for op in self.ops:
            n = sum(int(np.prod(s.shape)) for s in op.weight_specs().values())
            total += n
            lines.append(f"{op.name:30s} {op.op_type:20s} "
                         f"{str(op.outputs[0].shape):24s} {n:>12,d}")
        lines.append(f"total params: {total:,d}")
        return "\n".join(lines)
