"""Native runtime bindings.

The reference keeps its runtime (simulator, search loop, data loader) in
C++ behind a flat C API consumed by Python via cffi
(python/flexflow_c.h + flexflow_cbinding.py). This package does the
same with ctypes: `csrc/` holds the C++ sources and `flexflow_tpu_c.h`
the C API; the shared library is built on first use with g++ (cached by
source mtime) and every caller has a pure-Python fallback, so the
framework degrades gracefully on machines without a toolchain.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sys
import threading
from typing import Optional

# csrc/ lives inside the package (shipped as package-data in the wheel,
# pyproject.toml), so installed copies can build the native runtime too
_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_CSRC = os.path.join(_PKG_ROOT, "csrc")
_BUILD_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_build")
_LIB_PATH = os.path.join(_BUILD_DIR, "libflexflow_tpu_native.so")

_SOURCES = ("simulator.cc", "mcmc.cc", "dataloader.cc", "embedding_bag.cc")
_HEADERS = ("flexflow_tpu_c.h", "sim_core.h")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _needs_build() -> bool:
    if not os.path.exists(_LIB_PATH):
        return True
    lib_mtime = os.path.getmtime(_LIB_PATH)
    for f in _SOURCES + _HEADERS:
        p = os.path.join(_CSRC, f)
        if os.path.exists(p) and os.path.getmtime(p) > lib_mtime:
            return True
    return False


def build(verbose: bool = False) -> str:
    """Compile csrc/ into the shared library; returns its path.

    Compiles to a process-unique temp path and renames into place so
    concurrent builders (pytest-xdist, multi-process JAX) never expose a
    half-written library to ctypes.CDLL."""
    os.makedirs(_BUILD_DIR, exist_ok=True)
    tmp_path = f"{_LIB_PATH}.{os.getpid()}.tmp"
    cmd = ["g++", "-O3", "-fPIC", "-shared", "-std=c++17", "-Wall",
           "-I", _CSRC,
           *(os.path.join(_CSRC, s) for s in _SOURCES),
           "-o", tmp_path, "-lpthread"]
    if verbose:
        print("[native]", " ".join(cmd), file=sys.stderr)
    try:
        subprocess.run(cmd, check=True, capture_output=not verbose)
        os.replace(tmp_path, _LIB_PATH)
    finally:
        if os.path.exists(tmp_path):
            os.unlink(tmp_path)
    return _LIB_PATH


def _declare(lib: ctypes.CDLL) -> None:
    i32p = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    f64p = ctypes.POINTER(ctypes.c_double)
    vpp = ctypes.POINTER(ctypes.c_void_p)

    lib.ffsim_simulate.restype = ctypes.c_double
    lib.ffsim_simulate.argtypes = [ctypes.c_int32, f64p, i32p, i32p, i32p]

    lib.ffsearch_mcmc.restype = ctypes.c_double
    lib.ffsearch_mcmc.argtypes = [
        ctypes.c_int32, i32p, i32p,
        f64p, f64p, f64p, f64p, f64p, f64p,
        i32p, i32p, i32p, i32p, f64p, f64p, f64p, ctypes.c_int32,
        ctypes.c_int32, i32p, i32p, i32p, i32p,
        ctypes.c_int32, ctypes.c_double, ctypes.c_uint64,
        ctypes.c_int32, ctypes.c_int32,
        ctypes.c_double, ctypes.c_double, ctypes.c_double, i32p, i32p]

    lib.ffsearch_simulate_assignment.restype = ctypes.c_double
    lib.ffsearch_simulate_assignment.argtypes = [
        ctypes.c_int32, i32p,
        f64p, f64p, f64p, f64p, f64p, f64p,
        i32p, i32p, i32p, i32p, f64p, f64p, f64p, ctypes.c_int32,
        ctypes.c_int32, i32p, i32p,
        ctypes.c_int32, ctypes.c_double, ctypes.c_double,
        ctypes.c_double, i32p]

    lib.ffdl_create.restype = ctypes.c_void_p
    lib.ffdl_create.argtypes = [ctypes.c_int32, vpp, i64p,
                                ctypes.c_int64, ctypes.c_int32,
                                ctypes.c_int32]
    lib.ffdl_start_epoch.restype = None
    lib.ffdl_start_epoch.argtypes = [ctypes.c_void_p, i64p]
    lib.ffdl_num_batches.restype = ctypes.c_int32
    lib.ffdl_num_batches.argtypes = [ctypes.c_void_p]
    lib.ffdl_next_batch.restype = ctypes.c_int32
    lib.ffdl_next_batch.argtypes = [ctypes.c_void_p, vpp, i32p]
    lib.ffdl_destroy.restype = None
    lib.ffdl_destroy.argtypes = [ctypes.c_void_p]

    f32p = ctypes.POINTER(ctypes.c_float)
    lib.ffdl_embedding_bag.restype = None
    lib.ffdl_embedding_bag.argtypes = [
        f32p, ctypes.c_int64, ctypes.c_int32, i64p, ctypes.c_int64,
        ctypes.c_int32, ctypes.c_int32, f32p]

    lib.flexflow_tpu_native_version.restype = ctypes.c_char_p
    lib.flexflow_tpu_native_version.argtypes = []


def get_lib() -> Optional[ctypes.CDLL]:
    """The native library, building it if stale; None if unavailable
    (no toolchain / build failure — callers fall back to Python)."""
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        if os.environ.get("FLEXFLOW_TPU_NO_NATIVE"):
            _load_failed = True
            return None
        try:
            if _needs_build():
                build()
            lib = ctypes.CDLL(_LIB_PATH)
            _declare(lib)
            _lib = lib
        except (OSError, subprocess.CalledProcessError) as e:
            detail = ""
            stderr = getattr(e, "stderr", None)
            if stderr:
                if isinstance(stderr, bytes):
                    stderr = stderr.decode(errors="replace")
                detail = f"\n{stderr.strip()}"
            print(f"[flexflow_tpu.native] falling back to Python "
                  f"implementations ({e}){detail}", file=sys.stderr)
            _load_failed = True
    return _lib


def available() -> bool:
    return get_lib() is not None
