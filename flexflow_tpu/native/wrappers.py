"""Thin numpy-level wrappers over the native C API."""

from __future__ import annotations

import ctypes
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import get_lib


def _i32(a) -> np.ndarray:
    return np.ascontiguousarray(a, dtype=np.int32)


def _i64(a) -> np.ndarray:
    return np.ascontiguousarray(a, dtype=np.int64)


def _f64(a) -> np.ndarray:
    return np.ascontiguousarray(a, dtype=np.float64)


def _p(a: np.ndarray):
    ct = {np.dtype(np.int32): ctypes.c_int32,
          np.dtype(np.int64): ctypes.c_int64,
          np.dtype(np.float64): ctypes.c_double}[a.dtype]
    return a.ctypes.data_as(ctypes.POINTER(ct))


def simulate_taskgraph(durations: Sequence[float], resources: Sequence[int],
                       dep_indptr: Sequence[int],
                       dep_indices: Sequence[int]) -> float:
    """Native event-loop makespan; raises if the library is unavailable."""
    lib = get_lib()
    assert lib is not None, "native library unavailable"
    d = _f64(durations)
    r = _i32(resources)
    ip = _i32(dep_indptr)
    ix = _i32(dep_indices) if len(dep_indices) else np.zeros(1, np.int32)
    out = lib.ffsim_simulate(len(d), _p(d), _p(r), _p(ip), _p(ix))
    assert out >= 0, "cycle in task graph"
    return out


class CostTable:
    """Flattened per-(op, candidate) cost arrays for the native search.

    Beyond the scalar costs, a candidate may carry an explicit device
    placement (OpStrategy.device_ids — CSR place/place_ids) and/or
    PipelineCost fields for GPipe event-loop expansion; `finalize()`
    freezes the ragged placement lists into the CSR arrays the C API
    takes. `n_devices` is the mesh device count (device resources)."""

    def __init__(self, n_cands: Sequence[int], n_devices: int = 1):
        self.n_cands = _i32(n_cands)
        self.offsets = _i32(np.concatenate([[0], np.cumsum(n_cands)]))
        self.n_devices = int(n_devices)
        total = int(self.offsets[-1])
        self.fwd = np.zeros(total)
        self.bwd = np.zeros(total)
        self.fwd_comm = np.zeros(total)
        self.bwd_comm = np.zeros(total)
        self.sync = np.zeros(total)
        self.mem = np.zeros(total)
        self._place: List[List[int]] = [[] for _ in range(total)]
        self.pipe_stages = np.zeros(total, np.int32)
        self.pipe_mb = np.zeros(total, np.int32)
        self.pipe_fwd_stage = np.zeros(total)
        self.pipe_bwd_stage = np.zeros(total)
        self.pipe_hop = np.zeros(total)
        self.place_off: Optional[np.ndarray] = None
        self.place_ids: Optional[np.ndarray] = None

    def set(self, op: int, cand: int, cost,
            devices: Optional[Sequence[int]] = None) -> None:
        i = int(self.offsets[op]) + cand
        self.fwd[i] = cost.fwd
        # the native task graph has no separate update task: fold the
        # optimizer-update sweep into bwd, exactly as the Python
        # simulator serializes it onto the device after backward
        self.bwd[i] = cost.bwd + getattr(cost, "update", 0.0)
        self.fwd_comm[i] = cost.fwd_comm
        self.bwd_comm[i] = cost.bwd_comm
        self.sync[i] = cost.sync
        self.mem[i] = cost.mem
        if devices:
            self._place[i] = [int(d) for d in devices]
        pc = getattr(cost, "pipeline", None)
        if pc is not None:
            self.pipe_stages[i] = pc.stages
            self.pipe_mb[i] = pc.microbatches
            self.pipe_fwd_stage[i] = pc.fwd_stage
            self.pipe_bwd_stage[i] = pc.bwd_stage
            self.pipe_hop[i] = pc.hop
        self.place_off = None  # invalidate frozen CSR

    def finalize(self) -> None:
        if self.place_off is not None:
            return
        self.place_off = _i32(np.concatenate(
            [[0], np.cumsum([len(p) for p in self._place])]))
        flat = [d for p in self._place for d in p]
        self.place_ids = _i32(flat) if flat else np.zeros(1, np.int32)


def mcmc_search(table: CostTable,
                edges: Sequence[Tuple[int, int]],
                prop_match: Optional[List[List[int]]],
                budget: int, alpha: float, seed: int,
                enable_propagation: bool, overlap_backward_sync: bool,
                hbm_capacity: float, time_scale: float,
                init_cand: Sequence[int],
                step_overhead: float = 0.0) -> Tuple[np.ndarray, float]:
    """Run the native annealing loop; returns (best candidate per op,
    best simulated step seconds)."""
    lib = get_lib()
    assert lib is not None, "native library unavailable"
    table.finalize()
    n_ops = len(table.n_cands)
    e_src = _i32([e[0] for e in edges])
    e_dst = _i32([e[1] for e in edges])
    if prop_match is None:
        prop_match = [[-1] * int(table.n_cands[s]) for s, _ in edges]
    prop_off = _i32(np.concatenate(
        [[0], np.cumsum([len(m) for m in prop_match])])) if edges else \
        np.zeros(1, np.int32)
    prop_flat = _i32([v for m in prop_match for v in m]) if edges else \
        np.zeros(1, np.int32)
    if len(e_src) == 0:
        e_src = np.zeros(1, np.int32)
        e_dst = np.zeros(1, np.int32)
    init = _i32(init_cand)
    best = np.zeros(n_ops, np.int32)
    cost = lib.ffsearch_mcmc(
        n_ops, _p(table.n_cands), _p(table.offsets),
        _p(table.fwd), _p(table.bwd), _p(table.fwd_comm),
        _p(table.bwd_comm), _p(table.sync), _p(table.mem),
        _p(table.place_off), _p(table.place_ids),
        _p(table.pipe_stages), _p(table.pipe_mb),
        _p(table.pipe_fwd_stage), _p(table.pipe_bwd_stage),
        _p(table.pipe_hop), table.n_devices,
        len(edges), _p(e_src), _p(e_dst), _p(prop_off), _p(prop_flat),
        budget, alpha, seed, int(enable_propagation),
        int(overlap_backward_sync), hbm_capacity, time_scale,
        step_overhead, _p(init), _p(best))
    return best, float(cost)


def simulate_assignment(table: CostTable, edges: Sequence[Tuple[int, int]],
                        assignment: Sequence[int],
                        overlap_backward_sync: bool, hbm_capacity: float,
                        time_scale: float,
                        step_overhead: float = 0.0) -> float:
    lib = get_lib()
    assert lib is not None, "native library unavailable"
    table.finalize()
    n_ops = len(table.n_cands)
    e_src = _i32([e[0] for e in edges]) if edges else np.zeros(1, np.int32)
    e_dst = _i32([e[1] for e in edges]) if edges else np.zeros(1, np.int32)
    a = _i32(assignment)
    return float(lib.ffsearch_simulate_assignment(
        n_ops, _p(table.offsets),
        _p(table.fwd), _p(table.bwd), _p(table.fwd_comm),
        _p(table.bwd_comm), _p(table.sync), _p(table.mem),
        _p(table.place_off), _p(table.place_ids),
        _p(table.pipe_stages), _p(table.pipe_mb),
        _p(table.pipe_fwd_stage), _p(table.pipe_bwd_stage),
        _p(table.pipe_hop), table.n_devices,
        len(edges), _p(e_src), _p(e_dst),
        int(overlap_backward_sync), hbm_capacity, time_scale,
        step_overhead, _p(a)))


class NativePrefetchLoader:
    """Background-thread batch gatherer over C-contiguous host arrays.

    Gathers shuffled rows of every array into double-buffered contiguous
    batch buffers on a native thread, overlapping the gather for batch
    i+1 with device dispatch of batch i."""

    def __init__(self, arrays: Dict[str, np.ndarray], batch_size: int,
                 drop_last: bool = True):
        lib = get_lib()
        assert lib is not None, "native library unavailable"
        self._lib = lib
        self.names = list(arrays.keys())
        self.arrays = [np.ascontiguousarray(arrays[k]) for k in self.names]
        n = {len(a) for a in self.arrays}
        assert len(n) == 1, "arrays must have equal sample counts"
        self.n_samples = n.pop()
        self.batch_size = batch_size
        self.row_bytes = _i64([
            a.nbytes // max(1, len(a)) for a in self.arrays])
        self.row_shapes = [a.shape[1:] for a in self.arrays]
        self.dtypes = [a.dtype for a in self.arrays]
        ptrs = (ctypes.c_void_p * len(self.arrays))(
            *[a.ctypes.data_as(ctypes.c_void_p).value for a in self.arrays])
        self._h = lib.ffdl_create(len(self.arrays), ptrs, _p(self.row_bytes),
                                  self.n_samples, batch_size, int(drop_last))
        assert self._h, "ffdl_create failed"

    def start_epoch(self, order: Optional[np.ndarray] = None) -> None:
        if order is None:
            order = np.arange(self.n_samples, dtype=np.int64)
        order = _i64(order)
        assert len(order) == self.n_samples
        self._lib.ffdl_start_epoch(self._h, _p(order))

    @property
    def num_batches(self) -> int:
        return int(self._lib.ffdl_num_batches(self._h))

    def next_batch(self) -> Optional[Dict[str, np.ndarray]]:
        """Next batch as zero-copy views into the native double buffer
        (valid until the following next_batch); None at epoch end."""
        k = len(self.arrays)
        out = (ctypes.c_void_p * k)()
        rows = ctypes.c_int32(0)
        idx = self._lib.ffdl_next_batch(self._h, out, ctypes.byref(rows))
        if idx < 0:
            return None
        batch = {}
        for i, name in enumerate(self.names):
            shape = (rows.value,) + self.row_shapes[i]
            nbytes = int(np.prod(shape)) * self.dtypes[i].itemsize
            buf = (ctypes.c_char * nbytes).from_address(out[i])
            batch[name] = np.frombuffer(buf, dtype=self.dtypes[i]).reshape(
                shape)
        return batch

    def close(self) -> None:
        if getattr(self, "_h", None):
            self._lib.ffdl_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def embedding_bag(table: np.ndarray, indices: np.ndarray,
                  mode: str = "sum") -> np.ndarray:
    """Host-side embedding-bag (native when available, numpy fallback).

    table (V, D) float32; indices (B, L) int — negative entries are
    padding. The data-pipeline role of the reference's AVX2 CPU
    embedding-bag (src/ops/embedding_avx2.cc): pre-reduce multi-hot
    categorical features before the batch ships to the device."""
    table = np.ascontiguousarray(table, np.float32)
    idx = _i64(indices)
    assert table.ndim == 2 and idx.ndim == 2
    assert mode in ("sum", "mean")
    b, bag = idx.shape
    v, d = table.shape
    lib = get_lib()
    if lib is not None:
        out = np.empty((b, d), np.float32)
        lib.ffdl_embedding_bag(
            table.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            ctypes.c_int64(v), ctypes.c_int32(d), _p(idx),
            ctypes.c_int64(b), ctypes.c_int32(bag),
            ctypes.c_int32(0 if mode == "sum" else 1),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        return out
    valid = (idx >= 0) & (idx < v)
    gathered = np.where(valid[..., None], table[np.clip(idx, 0, v - 1)], 0.0)
    out = gathered.sum(axis=1)
    if mode == "mean":
        out /= np.maximum(valid.sum(axis=1, keepdims=True), 1)
    return out
