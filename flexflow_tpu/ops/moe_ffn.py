"""Fused Mixture-of-Experts FFN with expert parallelism.

The reference composes MoE from softmax + TopK + GroupBy + per-expert
dense ops + Aggregate, all placed by the strategy machinery but with NO
expert-parallel dispatch (SURVEY.md 2.4: "no all-to-all EP dispatch").
This op provides the TPU-first EP path: expert weights are stacked with a
leading `expert` axis; when the strategy maps that axis to a mesh axis,
GSPMD turns the dispatch/combine einsums into all-to-alls over ICI.

GShard-style: top-k gating, capacity-bounded dense dispatch masks, and a
load-balancing auxiliary loss added to the objective.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..op import CHANNEL, EXPERT, SAMPLE, SEQ, Op, OpContext, WeightSpec, register_op
from .common import AC_MODE_RELU, apply_activation
from .moe import (
    dispatch_indices,
    dispatch_mask,
    sorted_combine,
    sorted_dispatch,
    use_sorted_dispatch,
)


@register_op
class MoEFFN(Op):
    """input (..., D) -> output (..., out_dim) through num_experts
    two-layer FFNs with top-k routing."""

    op_type = "moe_ffn"
    has_aux_loss = True  # excluded from remat (ctx side-channel)

    def __init__(self, model, name, inputs, num_experts: int, k: int,
                 hidden_dim: int, out_dim: int = None,
                 capacity_factor: float = 1.25,
                 activation=AC_MODE_RELU, aux_loss_weight: float = 1e-2,
                 kernel_initializer: str = "glorot"):
        super().__init__(model, name, inputs)
        self.num_experts = int(num_experts)
        self.k = int(k)
        self.hidden_dim = int(hidden_dim)
        self.in_dim = inputs[0].shape[-1]
        self.out_dim = int(out_dim) if out_dim else self.in_dim
        self.capacity_factor = float(capacity_factor)
        self.activation = activation
        self.aux_loss_weight = aux_loss_weight
        self.kernel_initializer = kernel_initializer
        n_tokens = 1
        for s in inputs[0].shape[:-1]:
            n_tokens *= s
        self.n_tokens = n_tokens
        self.capacity = max(
            1, int(self.capacity_factor * self.k * n_tokens
                   / self.num_experts))
        self.attrs = {"num_experts": num_experts, "k": k,
                      "hidden_dim": hidden_dim, "out_dim": self.out_dim,
                      "capacity": self.capacity}

    def output_shapes(self):
        return [tuple(self.inputs[0].shape[:-1]) + (self.out_dim,)]

    def weight_specs(self):
        e, d, h, o = self.num_experts, self.in_dim, self.hidden_dim, self.out_dim
        return {
            "gate": WeightSpec((d, e), initializer=self.kernel_initializer,
                               axes=(CHANNEL, None)),
            "w1": WeightSpec((e, d, h), initializer=self.kernel_initializer,
                             axes=(EXPERT, None, None), fan_in=d, fan_out=h),
            "b1": WeightSpec((e, h), initializer="zeros",
                             axes=(EXPERT, None)),
            "w2": WeightSpec((e, h, o), initializer=self.kernel_initializer,
                             axes=(EXPERT, None, None), fan_in=h, fan_out=o),
            "b2": WeightSpec((e, o), initializer="zeros",
                             axes=(EXPERT, None)),
        }

    def forward(self, params, xs, ctx: OpContext):
        (x,) = xs
        orig_shape = x.shape
        d = orig_shape[-1]
        tokens = x.reshape(-1, d)  # (N, D)
        n = tokens.shape[0]
        e, cap, k = self.num_experts, self.capacity, self.k

        logits = jnp.dot(tokens, params["gate"].astype(tokens.dtype),
                         preferred_element_type=jnp.float32)  # (N, E)
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        gate_vals, assign = jax.lax.top_k(probs, k)  # (N, k)
        # renormalize the selected gates
        gate_vals = gate_vals / jnp.clip(
            jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9)

        xrep = jnp.repeat(tokens, k, axis=0)  # (N*k, D) slot-major
        sorted_path = use_sorted_dispatch(
            self.model, n * k, e, cap,
            expert_sharded=ctx.mesh_axis_size(EXPERT) > 1)
        if sorted_path:
            # scalable routing: no (S, E, C) mask (VERDICT r3 #8) —
            # identical semantics (stable argsort ranks = cumsum ranks)
            pos, kept = dispatch_indices(assign.astype(jnp.int32), e, cap)
            expert_in = sorted_dispatch(xrep, pos, kept, e, cap)
        else:
            mask = dispatch_mask(assign.astype(jnp.int32), e, cap)
            expert_in = jnp.einsum("snc,sd->ncd", mask,
                                   xrep.astype(jnp.float32)).astype(x.dtype)

        # per-expert FFN — batched over the (shardable) expert axis
        h = jnp.einsum("ecd,edh->ech", expert_in,
                       params["w1"].astype(x.dtype),
                       preferred_element_type=jnp.float32).astype(x.dtype)
        h = apply_activation(h + params["b1"][:, None, :].astype(x.dtype),
                             self.activation)
        out_e = jnp.einsum("ech,eho->eco", h, params["w2"].astype(x.dtype),
                           preferred_element_type=jnp.float32).astype(x.dtype)
        out_e = out_e + params["b2"][:, None, :].astype(x.dtype)

        # combine: weight each slot by its (renormalized) gate value
        if sorted_path:
            combined = sorted_combine(out_e, pos, kept).astype(jnp.float32)
        else:
            combined = jnp.einsum("snc,nco->so", mask,
                                  out_e.astype(jnp.float32))  # (N*k, O)
        combined = combined.reshape(n, k, self.out_dim)
        out = jnp.sum(combined * gate_vals[..., None], axis=1)

        if ctx.training:
            # GShard load-balancing loss: E * sum_e f_e * p_e where f_e is
            # the fraction of tokens whose top-1 goes to e and p_e the mean
            # gate probability of e.
            top1 = jax.nn.one_hot(assign[:, 0], e, dtype=jnp.float32)
            f = jnp.mean(top1, axis=0)
            p = jnp.mean(probs, axis=0)
            ctx.aux_loss = (self.aux_loss_weight * e
                            * jnp.sum(f * p)).astype(jnp.float32)

        return [out.astype(x.dtype).reshape(orig_shape[:-1] + (self.out_dim,))]

    def output_axes(self):
        n = len(self.outputs[0].shape)
        axes = [None] * n
        axes[0] = SAMPLE
        if n == 3:
            axes[1] = SEQ
        return [tuple(axes)]

    input_axes = output_axes

    def flops(self) -> float:
        # gate + 2 FFN GEMMs over dispatched capacity
        gate = 2.0 * self.n_tokens * self.in_dim * self.num_experts
        ffn = (2.0 * self.num_experts * self.capacity
               * (self.in_dim * self.hidden_dim
                  + self.hidden_dim * self.out_dim))
        dispatch = 2.0 * self.n_tokens * self.k * self.num_experts * self.capacity
        return gate + ffn + dispatch
