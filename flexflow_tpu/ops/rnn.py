"""LSTM layer.

Reference: nmt/lstm.cu (574 LoC) — cuDNN RNN API over per-timestep Legion
tasks, with `SharedVariable` weights spanning timesteps (nmt/rnn.h:60-160).
The reference builds its *own* mini-framework for this (nmt/); per
SURVEY.md section 7 step 8 we instead make LSTM an ordinary op of the main
framework: `lax.scan` over time — XLA compiles the recurrence into a single
fused loop — with the gate matmuls batched into one (D+H, 4H) GEMM per step
so they hit the MXU. A Pallas cell kernel can slot in under the same op.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..op import CHANNEL_IN, CHANNEL_OUT, SAMPLE, SEQ, Op, OpContext, WeightSpec, register_op


@register_op
class LSTM(Op):
    """input (B, T, D) -> output (B, T, H); single layer, unidirectional.

    Gate layout in the fused kernel: [i, f, g, o] along the 4H axis.
    """

    op_type = "lstm"

    def __init__(self, model, name, inputs, hidden_size: int,
                 return_sequences: bool = True,
                 kernel_initializer: str = "glorot",
                 use_pallas=None):
        super().__init__(model, name, inputs)
        self.hidden_size = int(hidden_size)
        self.in_dim = inputs[0].shape[-1]
        self.return_sequences = return_sequences
        self.kernel_initializer = kernel_initializer
        # tri-state like attention's use_flash: None = scan (default
        # until the kernel is measured profitable on hardware), True =
        # force the Pallas multi-timestep kernel (kernels/lstm_scan.py —
        # wh resident in VMEM across steps instead of re-read from HBM
        # every timestep), False = never.
        self.use_pallas = use_pallas
        self.attrs = {"hidden_size": hidden_size,
                      "return_sequences": return_sequences}

    def output_shapes(self):
        b, t, _ = self.inputs[0].shape
        if self.return_sequences:
            return [(b, t, self.hidden_size)]
        return [(b, self.hidden_size)]

    def weight_specs(self):
        h = self.hidden_size
        return {
            "wx": WeightSpec((self.in_dim, 4 * h),
                             initializer=self.kernel_initializer,
                             axes=(CHANNEL_IN, CHANNEL_OUT)),
            "wh": WeightSpec((h, 4 * h), initializer=self.kernel_initializer,
                             axes=(None, CHANNEL_OUT)),
            "b": WeightSpec((4 * h,), initializer="zeros",
                            axes=(CHANNEL_OUT,)),
        }

    def forward(self, params, xs, ctx: OpContext):
        (x,) = xs
        b, t, _ = x.shape
        h = self.hidden_size
        wx, wh, bias = params["wx"], params["wh"], params["b"]
        # Precompute input contributions for all timesteps in one big GEMM
        # (time-batched: (B*T, D) @ (D, 4H) keeps the MXU busy).
        xg = (jnp.dot(x.reshape(b * t, -1), wx.astype(x.dtype),
                      preferred_element_type=jnp.float32)
              .reshape(b, t, 4 * h) + bias)
        xg = jnp.swapaxes(xg, 0, 1)  # (T, B, 4H) for scan

        use_pallas = self.use_pallas
        if use_pallas is None:
            # session-level A/B knob (tools/tpu_session.sh): flip the
            # undecided default from the environment without editing
            # model code. Read at TRACE time and baked into the compiled
            # step — an already-compiled model will NOT pick up a later
            # env change (jit cache keys don't include env); run each
            # A/B arm in its own process, as the session script does.
            import os
            use_pallas = os.environ.get(
                "FLEXFLOW_TPU_LSTM_PALLAS", "") == "1"
        if use_pallas:
            from ..kernels.lstm_scan import lstm_sequence
            ys = lstm_sequence(xg.astype(x.dtype), wh.astype(x.dtype),
                               jnp.zeros((b, h), x.dtype),
                               jnp.zeros((b, h), x.dtype))
            if self.return_sequences:
                return [jnp.swapaxes(ys, 0, 1)]
            return [ys[-1]]

        def cell(carry, xg_t):
            h_prev, c_prev = carry
            gates = xg_t + jnp.dot(h_prev, wh.astype(h_prev.dtype),
                                   preferred_element_type=jnp.float32)
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i = jax.nn.sigmoid(i)
            f = jax.nn.sigmoid(f)
            g = jnp.tanh(g)
            o = jax.nn.sigmoid(o)
            c = f * c_prev + i * g
            hy = o * jnp.tanh(c)
            return (hy.astype(x.dtype), c.astype(x.dtype)), hy.astype(x.dtype)

        init = (jnp.zeros((b, h), x.dtype), jnp.zeros((b, h), x.dtype))
        (h_last, _), ys = lax.scan(cell, init, xg)
        if self.return_sequences:
            return [jnp.swapaxes(ys, 0, 1)]
        return [h_last]

    def output_axes(self):
        if self.return_sequences:
            return [(SAMPLE, SEQ, CHANNEL_OUT)]
        return [(SAMPLE, CHANNEL_OUT)]

    def input_axes(self):
        return [(SAMPLE, SEQ, CHANNEL_IN)]

    def flops(self) -> float:
        b, t, d = self.inputs[0].shape
        h = self.hidden_size
        return 2.0 * b * t * (d + h) * 4 * h
