"""Elementwise unary/binary ops, dropout, softmax.

Reference: src/ops/element_unary.cu, element_binary.cu, dropout.cu,
softmax.cu. The reference's in-place output machinery
(can_inplace_output + compile-time in-place pass, model.cc:1580-1609) has
no TPU analog: XLA does buffer reuse itself.
"""

from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp

from ..op import SAMPLE, CHANNEL, SEQ, Op, OpContext, WeightSpec, register_op


def _passthrough_axes(shape):
    """Logical axes for rank-preserving ops: (sample, seq, channel) for
    rank-3 sequence tensors, sample-only otherwise (conv NCHW tensors are
    handled by the conv ops' own overrides)."""
    n = len(shape)
    axes = [None] * n
    if n >= 1:
        axes[0] = SAMPLE
    if n == 3:
        axes[1] = SEQ
        axes[2] = CHANNEL
    return [tuple(axes)]


class PassthroughAxesMixin:
    """Shared logical-axis labeling for rank-preserving ops: outputs
    carry the same SAMPLE/SEQ/CHANNEL labels as the input."""

    def output_axes(self):
        return _passthrough_axes(self.outputs[0].shape)

    def input_axes(self):
        return [_passthrough_axes(t.shape)[0] for t in self.inputs]



_UNARY = {
    "relu": jax.nn.relu,
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "elu": jax.nn.elu,
    "exp": jnp.exp,
    "gelu": jax.nn.gelu,
    "identity": lambda x: x,
    "scalar_multiply": None,  # uses attrs["scalar"]
}

_BINARY = {
    "add": jnp.add,
    "subtract": jnp.subtract,
    "multiply": jnp.multiply,
    "divide": jnp.divide,
    "max": jnp.maximum,
    "min": jnp.minimum,
}


@register_op
class ElementUnary(PassthroughAxesMixin, Op):
    op_type = "element_unary"

    def __init__(self, model, name, inputs, mode: str, scalar: float = None):
        super().__init__(model, name, inputs)
        assert mode in _UNARY, f"unknown unary mode {mode}"
        self.mode = mode
        self.scalar = scalar
        self.attrs = {"mode": mode, "scalar": scalar}

    def output_shapes(self):
        return [tuple(self.inputs[0].shape)]

    def forward(self, params, xs, ctx: OpContext):
        (x,) = xs
        if self.mode == "scalar_multiply":
            return [x * self.scalar]
        return [_UNARY[self.mode](x)]

    def flops(self) -> float:
        return float(self.inputs[0].num_elements)




@register_op
class Reduce(Op):
    """Axis reduction (mean/sum/max). No single reference analog — the
    reference reaches reductions through pooling/softmax kernels; this
    is the generic form frontends need (ONNX ReduceMean/Sum/Max, torch
    .mean(dim)); lowers to one jnp reduction."""

    op_type = "reduce"
    _FNS = {"mean": jnp.mean, "sum": jnp.sum, "max": jnp.max}

    def __init__(self, model, name, inputs, mode: str, axis: int,
                 keepdims: bool = False):
        super().__init__(model, name, inputs)
        if mode not in self._FNS:
            raise ValueError(f"unknown reduce mode {mode!r}")
        rank = len(inputs[0].shape)
        axis = axis if axis >= 0 else axis + rank
        if not 0 < axis < rank:
            raise ValueError(
                f"reduce axis {axis} out of range for rank {rank} "
                f"(the sample dim 0 cannot be reduced)")
        self.mode = mode
        self.axis = axis
        self.keepdims = bool(keepdims)
        self.attrs = {"mode": mode, "axis": axis, "keepdims": keepdims}

    def output_shapes(self):
        s = list(self.inputs[0].shape)
        if self.keepdims:
            s[self.axis] = 1
        else:
            s.pop(self.axis)
        return [tuple(s)]

    def forward(self, params, xs, ctx: OpContext):
        (x,) = xs
        from ..core.precision import policy_active
        if self.mode in ("mean", "sum") and x.dtype != jnp.float32 \
                and jnp.issubdtype(x.dtype, jnp.floating) \
                and policy_active(self.model.config):
            # f32 reduction accumulator (mixed-precision policy): a
            # long bf16 sum drifts by O(n * eps); max needs no
            # accumulator. Output returns to the activation dtype.
            # Policy-gated like Softmax above — builder-level bf16
            # under the f32 default keeps exact pre-policy numerics.
            return [self._FNS[self.mode](
                x, axis=self.axis, keepdims=self.keepdims,
                dtype=jnp.float32).astype(x.dtype)]
        return [self._FNS[self.mode](x, axis=self.axis,
                                     keepdims=self.keepdims)]

    def output_axes(self):
        in_axes = list(_passthrough_axes(self.inputs[0].shape)[0])
        if self.keepdims:
            in_axes[self.axis] = None
        else:
            in_axes.pop(self.axis)
        return [tuple(in_axes)]

    def input_axes(self):
        return [_passthrough_axes(self.inputs[0].shape)[0]]

    def flops(self) -> float:
        return float(self.inputs[0].num_elements)


@register_op
class ElementBinary(PassthroughAxesMixin, Op):
    op_type = "element_binary"

    def __init__(self, model, name, inputs, mode: str):
        super().__init__(model, name, inputs)
        assert mode in _BINARY, f"unknown binary mode {mode}"
        # Reference requires same-shape (element_binary.cu: broadcasting NOT
        # general); we allow numpy broadcasting as a superset.
        self.mode = mode
        self.attrs = {"mode": mode}

    def output_shapes(self):
        a, b = self.inputs[0].shape, self.inputs[1].shape
        return [tuple(jnp.broadcast_shapes(a, b))]

    def forward(self, params, xs, ctx: OpContext):
        a, b = xs
        return [_BINARY[self.mode](a, b)]

    def flops(self) -> float:
        return float(self.outputs[0].num_elements)




@register_op
class Dropout(PassthroughAxesMixin, Op):
    """Reference: src/ops/dropout.cu (cuDNN dropout with reserve space —
    here: stateless jax.random.bernoulli keyed off the per-step rng)."""

    op_type = "dropout"

    def __init__(self, model, name, inputs, rate: float, seed: int = 0):
        super().__init__(model, name, inputs)
        self.rate = float(rate)
        self.seed = seed
        self.attrs = {"rate": rate, "seed": seed}

    def output_shapes(self):
        return [tuple(self.inputs[0].shape)]

    def forward(self, params, xs, ctx: OpContext):
        (x,) = xs
        if not ctx.training or self.rate <= 0.0:
            return [x]
        keep = 1.0 - self.rate
        mask = jax.random.bernoulli(ctx.rng, keep, x.shape)
        return [jnp.where(mask, x / keep, 0.0).astype(x.dtype)]




@register_op
class Softmax(PassthroughAxesMixin, Op):
    """Reference: src/ops/softmax.cu (cuDNN accurate-mode softmax =
    max-subtracted, which is exactly jax.nn.softmax)."""

    op_type = "softmax"

    def __init__(self, model, name, inputs, axis: int = -1):
        super().__init__(model, name, inputs)
        self.axis = axis
        self.attrs = {"axis": axis}

    def output_shapes(self):
        return [tuple(self.inputs[0].shape)]

    def forward(self, params, xs, ctx: OpContext):
        (x,) = xs
        from ..core.precision import policy_active
        if x.dtype != jnp.float32 \
                and jnp.issubdtype(x.dtype, jnp.floating) \
                and policy_active(self.model.config):
            # max/exp/sum statistics in f32 (the mixed-precision policy
            # and the flash-attention convention): a bf16 sum over the
            # class dim loses exactly the normalization the loss reads.
            # Output returns to the activation dtype. Gated on the
            # POLICY, not the input dtype alone: builder-level bf16
            # models under the f32 default keep their exact pre-policy
            # numerics (the compatibility promise in core/precision.py).
            return [jax.nn.softmax(x.astype(jnp.float32),
                                   axis=self.axis).astype(x.dtype)]
        return [jax.nn.softmax(x, axis=self.axis)]

    def flops(self) -> float:
        return 5.0 * self.inputs[0].num_elements


@register_op
class LayerNorm(PassthroughAxesMixin, Op):
    """Normalize over the LAST dim with learned scale/bias.

    No reference analog — FlexFlow ships only BatchNorm
    (src/ops/batch_norm.cu); this is a TPU-first addition because
    modern transformer blocks (pre-LN) depend on it. Statistics in f32
    regardless of activation dtype (mirrors BatchNorm here).
    """

    op_type = "layer_norm"

    def __init__(self, model, name, inputs, eps: float = 1e-5,
                 elementwise_affine: bool = True):
        super().__init__(model, name, inputs)
        self.eps = float(eps)
        self.elementwise_affine = elementwise_affine
        self.num_channels = inputs[0].shape[-1]
        self.attrs = {"eps": eps,
                      "elementwise_affine": elementwise_affine}

    def output_shapes(self):
        return [tuple(self.inputs[0].shape)]

    def weight_specs(self):
        if not self.elementwise_affine:
            return {}
        c = self.num_channels
        return {
            "scale": WeightSpec((c,), initializer="ones",
                                axes=(CHANNEL,)),
            "bias": WeightSpec((c,), initializer="zeros",
                               axes=(CHANNEL,)),
        }

    def forward(self, params, xs, ctx: OpContext):
        (x,) = xs
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.var(xf, axis=-1, keepdims=True)
        y = (xf - mean) * jax.lax.rsqrt(var + self.eps)
        if self.elementwise_affine:
            y = y * params["scale"].astype(jnp.float32) \
                + params["bias"].astype(jnp.float32)
        return [y.astype(x.dtype)]

    def flops(self) -> float:
        return 8.0 * self.inputs[0].num_elements
