"""Mixture-of-Experts routing ops: GroupBy (dispatch) and Aggregate
(combine).

Reference: src/ops/group_by.cc (CPU-only scatter of samples to per-expert
tensors with capacity factor `alpha`) and src/ops/aggregate.cc (CPU-only
weighted combine). The reference registers these LOC_PROC (CPU) because
irregular scatter is hostile to GPUs (model.cc:2525-2568).

TPU-native design: GShard-style *dense dispatch*. Routing becomes one-hot
dispatch masks contracted with the data on the MXU — no scatter at all,
fully differentiable, and the expert dimension is a real array axis that
can be sharded over a mesh `expert` axis so GSPMD inserts the all-to-all
(expert parallelism, which the reference lacked — SURVEY.md section 2.4).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..op import EXPERT, SAMPLE, Op, OpContext, register_op


def dispatch_mask(assign: jax.Array, n_experts: int, capacity: int):
    """Build a dense dispatch mask from top-k expert assignments.

    assign: (batch, k) int — expert id per (sample, slot).
    Returns (batch*k, n_experts, capacity) float mask. Slot s of sample b
    routes to position `rank` within its expert's capacity buffer, where
    rank counts earlier (sample, slot) pairs assigned to the same expert;
    overflow beyond capacity is dropped (the reference drops too:
    group_by.cc capacity factor alpha).
    """
    flat = assign.reshape(-1).astype(jnp.int32)  # (B*k,)
    onehot = jax.nn.one_hot(flat, n_experts, dtype=jnp.float32)  # (S, n)
    ranks = jnp.cumsum(onehot, axis=0) * onehot - onehot  # rank within expert
    rank = jnp.sum(ranks, axis=1).astype(jnp.int32)  # (S,)
    keep = (rank < capacity).astype(jnp.float32)
    pos = jax.nn.one_hot(rank, capacity, dtype=jnp.float32)  # (S, cap)
    return onehot[:, :, None] * pos[:, None, :] * keep[:, None, None]


# Above this many mask elements (S * E * C floats) the dense dispatch
# mask is pure HBM waste; the sorted-scatter path does the same routing
# in O(S log S + S * D). Override with FFConfig.moe_dispatch.
DENSE_MASK_ELEMENT_LIMIT = 1 << 22


def dispatch_indices(assign: jax.Array, n_experts: int, capacity: int):
    """Sorted-scatter routing: the same (rank-within-expert, capacity
    drop) semantics as `dispatch_mask` without materializing the
    (S, E, C) mask — the scalable path for large expert counts
    (VERDICT r3 #8; capacity semantics preserved from
    /root/reference/src/ops/group_by.cc:1-381).

    assign: (batch, k) int. Returns (pos (S,), keep (S,)) where
    pos = expert * capacity + rank indexes a flat (E*C, ...) buffer and
    keep masks slots that exceeded their expert's capacity. Ranks count
    earlier slots (original slot order) routed to the same expert —
    jnp.argsort is stable, so this matches the dense mask bit-for-bit.
    """
    flat = assign.reshape(-1).astype(jnp.int32)  # (S,)
    s = flat.shape[0]
    order = jnp.argsort(flat)  # stable: preserves slot order per expert
    sorted_e = flat[order]
    idx = jnp.arange(s, dtype=jnp.int32)
    # index of each sorted run's first element, broadcast via cummax
    boundary = jnp.concatenate(
        [jnp.ones((1,), bool), sorted_e[1:] != sorted_e[:-1]])
    run_start = jax.lax.cummax(jnp.where(boundary, idx, 0))
    rank_sorted = idx - run_start
    rank = jnp.zeros((s,), jnp.int32).at[order].set(rank_sorted)
    # out-of-range expert ids (e.g. -1 padding) silently contribute
    # nothing in the dense path (one_hot zeroes them) — match that
    # here, and DON'T let a negative pos wrap (jnp advanced indexing
    # normalizes negatives before mode="drop" can reject them)
    keep = (rank < capacity) & (flat >= 0) & (flat < n_experts)
    # dropped slots park out of range: scatters use mode="drop",
    # gathers mode="fill" — no valid position is ever clobbered
    pos = jnp.where(keep, flat * capacity + rank,
                    n_experts * capacity)
    return pos, keep


def sorted_dispatch(xrep: jax.Array, pos: jax.Array, keep: jax.Array,
                    n_experts: int, capacity: int):
    """Scatter slot-major tokens (S, D) into (E, C, D) expert buffers.
    Kept positions are unique by construction, so the add is a write."""
    d = xrep.shape[-1]
    masked = jnp.where(keep[:, None], xrep, jnp.zeros_like(xrep))
    buf = jnp.zeros((n_experts * capacity, d), xrep.dtype)
    buf = buf.at[pos].add(masked, mode="drop")
    return buf.reshape(n_experts, capacity, d)


def sorted_combine(out_e: jax.Array, pos: jax.Array, keep: jax.Array):
    """Gather expert outputs (E, C, O) back to slot-major (S, O);
    dropped slots read zeros (same as the dense mask contraction)."""
    flat = out_e.reshape(-1, out_e.shape[-1])
    gathered = flat.at[pos].get(mode="fill", fill_value=0)
    return jnp.where(keep[:, None], gathered, jnp.zeros_like(gathered))


def use_sorted_dispatch(model, n_slots: int, n_experts: int,
                        capacity: int, expert_sharded: bool) -> bool:
    """Dispatch-path policy. "auto": dense masks feed the MXU and lower
    to clean all-to-alls when the expert axis is mesh-sharded (EP), so
    keep them unless the mask itself would be huge; sorted-scatter
    takes over above DENSE_MASK_ELEMENT_LIMIT elements."""
    mode = getattr(getattr(model, "config", None), "moe_dispatch", "auto")
    if mode == "dense":
        return False
    if mode == "sorted":
        return True
    if expert_sharded:
        return False  # einsum -> all-to-all is the EP-friendly lowering
    return n_slots * n_experts * capacity > DENSE_MASK_ELEMENT_LIMIT


@register_op
class GroupBy(Op):
    """inputs: (data (B, D), assign (B, k)); outputs: n tensors (cap, D)."""

    op_type = "group_by"

    def __init__(self, model, name, inputs, n: int, alpha: float):
        super().__init__(model, name, inputs)
        self.n = int(n)
        self.alpha = float(alpha)
        data, assign = inputs
        batch = data.shape[0]
        k = assign.shape[1]
        self.k = k
        # capacity per expert, matching group_by.cc's alpha*k*B/n
        self.capacity = max(1, int(self.alpha * k * batch / self.n))
        self.attrs = {"n": n, "alpha": alpha, "capacity": self.capacity}

    def output_shapes(self):
        d = self.inputs[0].shape[-1]
        return [(self.capacity, d)] * self.n

    def output_dtypes(self):
        return [self.inputs[0].dtype] * self.n

    def forward(self, params, xs, ctx: OpContext):
        data, assign = xs
        xrep = jnp.repeat(data, self.k, axis=0)  # (S, D), slot-major
        if use_sorted_dispatch(self.model, xrep.shape[0], self.n,
                               self.capacity, expert_sharded=False):
            pos, keep = dispatch_indices(assign, self.n, self.capacity)
            expert_in = sorted_dispatch(xrep, pos, keep, self.n,
                                        self.capacity)
        else:
            mask = dispatch_mask(assign, self.n, self.capacity)
            expert_in = jnp.einsum("snc,sd->ncd", mask,
                                   xrep.astype(jnp.float32))
            expert_in = expert_in.astype(data.dtype)
        return [expert_in[i] for i in range(self.n)]

    def output_axes(self):
        return [(SAMPLE, None)] * self.n


@register_op
class Aggregate(Op):
    """inputs: (gate_preds (B,k), assign (B,k), exp_pred_0..n-1 (cap, D));
    output: (B, D) weighted combine. Reference: aggregate.cc."""

    op_type = "aggregate"

    def __init__(self, model, name, inputs, n: int, capacity: int = None,
                 alpha: float = None):
        super().__init__(model, name, inputs)
        self.n = int(n)
        gate, assign = inputs[0], inputs[1]
        self.k = assign.shape[1]
        batch = gate.shape[0]
        if capacity is None:
            capacity = inputs[2].shape[0]
        self.capacity = int(capacity)
        self.attrs = {"n": n, "capacity": self.capacity}

    def output_shapes(self):
        b = self.inputs[0].shape[0]
        d = self.inputs[2].shape[-1]
        return [(b, d)]

    def output_dtypes(self):
        return [self.inputs[2].dtype]

    def forward(self, params, xs, ctx: OpContext):
        gate, assign = xs[0], xs[1]
        experts = jnp.stack(xs[2:], axis=0)  # (n, cap, D)
        mask = dispatch_mask(assign, self.n, self.capacity)  # (S, n, cap)
        gathered = jnp.einsum("snc,ncd->sd", mask,
                              experts.astype(jnp.float32))  # (B*k, D)
        b, k = assign.shape
        gathered = gathered.reshape(b, k, -1)
        out = jnp.sum(gathered * gate[:, :, None].astype(jnp.float32), axis=1)
        return [out.astype(experts.dtype)]

    def output_axes(self):
        return [(SAMPLE, None)]
