"""Shape/data-movement ops: concat, split, reshape, transpose, reverse,
top-k, batch matmul.

Reference: src/ops/{concat,split,reshape,transpose,reverse,topk,
batch_matmul}.cu. All the reference's hand-written strided-copy kernels
become single jnp calls; XLA emits the copies (usually fused away).
"""

from __future__ import annotations

from typing import List, Tuple

import jax
import jax.numpy as jnp

from ..op import SAMPLE, SEQ, Op, OpContext, register_op


@register_op
class Concat(Op):
    op_type = "concat"

    def __init__(self, model, name, inputs, axis: int):
        super().__init__(model, name, inputs)
        self.axis = axis % len(inputs[0].shape)
        self.attrs = {"axis": self.axis}

    def output_shapes(self):
        shape = list(self.inputs[0].shape)
        shape[self.axis] = sum(t.shape[self.axis] for t in self.inputs)
        return [tuple(shape)]

    def forward(self, params, xs, ctx: OpContext):
        axis = self.axis
        if ctx.nhwc_in and axis == 1 and xs[0].ndim == 4:
            # NHWC-resident operands (executor residency pass): the
            # logical channel axis lives at position 3
            axis = 3
        return [jnp.concatenate(xs, axis=axis)]


@register_op
class Split(Op):
    op_type = "split"

    def __init__(self, model, name, inputs, sizes: List[int], axis: int):
        super().__init__(model, name, inputs)
        self.axis = axis % len(inputs[0].shape)
        self.sizes = list(sizes)
        assert sum(self.sizes) == inputs[0].shape[self.axis]
        self.attrs = {"axis": self.axis, "sizes": self.sizes}

    def output_shapes(self):
        out = []
        for s in self.sizes:
            shape = list(self.inputs[0].shape)
            shape[self.axis] = s
            out.append(tuple(shape))
        return out

    def forward(self, params, xs, ctx: OpContext):
        (x,) = xs
        indices = []
        acc = 0
        for s in self.sizes[:-1]:
            acc += s
            indices.append(acc)
        return list(jnp.split(x, indices, axis=self.axis))


@register_op
class Reshape(Op):
    op_type = "reshape"

    def __init__(self, model, name, inputs, shape: Tuple[int, ...]):
        super().__init__(model, name, inputs)
        shape = tuple(int(s) for s in shape)
        n_in = inputs[0].num_elements
        if -1 in shape:
            known = 1
            for s in shape:
                if s != -1:
                    known *= s
            shape = tuple(n_in // known if s == -1 else s for s in shape)
        self.new_shape = shape
        self.attrs = {"shape": shape}

    def output_shapes(self):
        return [self.new_shape]

    def forward(self, params, xs, ctx: OpContext):
        return [xs[0].reshape(self.new_shape)]


@register_op
class Transpose(Op):
    op_type = "transpose"

    def __init__(self, model, name, inputs, perm: List[int]):
        super().__init__(model, name, inputs)
        self.perm = list(perm)
        self.attrs = {"perm": self.perm}

    def output_shapes(self):
        s = self.inputs[0].shape
        return [tuple(s[p] for p in self.perm)]

    def forward(self, params, xs, ctx: OpContext):
        return [jnp.transpose(xs[0], self.perm)]


@register_op
class Reverse(Op):
    op_type = "reverse"

    def __init__(self, model, name, inputs, axis: int):
        super().__init__(model, name, inputs)
        self.axis = axis % len(inputs[0].shape)
        self.attrs = {"axis": self.axis}

    def output_shapes(self):
        return [tuple(self.inputs[0].shape)]

    def forward(self, params, xs, ctx: OpContext):
        return [jnp.flip(xs[0], axis=self.axis)]


@register_op
class TopK(Op):
    """Two outputs (values, indices). Reference: src/ops/topk.cu's bitonic
    per-thread-heap kernel -> lax.top_k (XLA's native TPU sort)."""

    op_type = "topk"

    def __init__(self, model, name, inputs, k: int, sorted: bool = True):
        super().__init__(model, name, inputs)
        self.k = int(k)
        self.sorted = sorted
        self.attrs = {"k": k, "sorted": sorted}

    def output_shapes(self):
        shape = list(self.inputs[0].shape)
        shape[-1] = self.k
        return [tuple(shape), tuple(shape)]

    def output_dtypes(self):
        return [self.inputs[0].dtype, jnp.dtype(jnp.int32)]

    def forward(self, params, xs, ctx: OpContext):
        values, indices = jax.lax.top_k(xs[0], self.k)
        return [values, indices.astype(jnp.int32)]


@register_op
class BatchMatmul(Op):
    """Batched matmul A @ B over leading batch dims.

    Reference: src/ops/batch_matmul.cu — cuBLAS strided-batched GEMM with
    seq_length-aware shape truncation (`a_seq_length_dim`, runtime
    iter_config.seq_length masks, model.h:1029-1047). We reproduce the
    truncation semantics with a mask (dynamic shapes would defeat XLA
    caching; masking keeps the compiled program static).
    """

    op_type = "batch_matmul"

    def __init__(self, model, name, inputs, a_seq_length_dim: int = -1,
                 b_seq_length_dim: int = -1):
        super().__init__(model, name, inputs)
        a, b = inputs
        assert a.shape[:-2] == b.shape[:-2], "batch dims must match"
        assert a.shape[-1] == b.shape[-2], (a.shape, b.shape)
        self.a_seq_length_dim = a_seq_length_dim
        self.b_seq_length_dim = b_seq_length_dim
        self.attrs = {"a_seq_length_dim": a_seq_length_dim,
                      "b_seq_length_dim": b_seq_length_dim}

    def output_shapes(self):
        a, b = self.inputs
        return [tuple(a.shape[:-1]) + (b.shape[-1],)]

    @staticmethod
    def _seq_mask(x, dim, seq_length):
        if dim < 0 or seq_length is None or seq_length < 0:
            return x
        idx = jnp.arange(x.shape[dim])
        shape = [1] * x.ndim
        shape[dim] = -1
        return jnp.where(idx.reshape(shape) < seq_length, x, 0)

    def forward(self, params, xs, ctx: OpContext):
        a, b = xs
        a = self._seq_mask(a, self.a_seq_length_dim, ctx.seq_length)
        b = self._seq_mask(b, self.b_seq_length_dim, ctx.seq_length)
        y = jnp.matmul(a, b, preferred_element_type=jnp.float32)
        return [y.astype(a.dtype)]

    def flops(self) -> float:
        a, b = self.inputs
        batch = 1
        for s in a.shape[:-2]:
            batch *= s
        return 2.0 * batch * a.shape[-2] * a.shape[-1] * b.shape[-1]
