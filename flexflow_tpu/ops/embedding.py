"""Embedding lookup with SUM/AVG aggregation.

Reference: src/ops/embedding.cu (custom gather/scatter-add kernels) plus a
hand-vectorized AVX2 CPU embedding-bag (embedding_avx2.cc:15-296). The op
takes int indices of shape (batch, bag) and produces (batch, out_dim),
aggregating over the bag dimension — DLRM-style embedding bag.

TPU-native design: a plain `take` gather; XLA lowers it to an efficient
one-hot-matmul or dynamic-gather depending on table size. The table's
`vocab` logical axis can be mapped to a mesh axis for DLRM parameter
parallelism (the reference placed whole tables on specific GPUs via
strategies, SURVEY.md 2.3; sharding the vocab dim over ICI is the TPU
generalization).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..op import CHANNEL_OUT, SAMPLE, VOCAB, Op, OpContext, WeightSpec, register_op

AGGR_MODE_NONE = "none"
AGGR_MODE_SUM = "sum"
AGGR_MODE_AVG = "avg"


@register_op
class Embedding(Op):
    op_type = "embedding"

    def __init__(self, model, name, inputs, num_entries: int, out_dim: int,
                 aggr: str = AGGR_MODE_SUM, kernel_initializer: str = "glorot"):
        super().__init__(model, name, inputs)
        self.num_entries = int(num_entries)
        self.out_dim = int(out_dim)
        self.aggr = aggr
        self.kernel_initializer = kernel_initializer
        self.attrs = {"num_entries": num_entries, "out_dim": out_dim,
                      "aggr": aggr}

    def output_shapes(self):
        in_shape = self.inputs[0].shape
        if self.aggr == AGGR_MODE_NONE:
            return [tuple(in_shape) + (self.out_dim,)]
        # (batch, bag) -> (batch, out_dim): aggregate over the bag dim.
        return [(in_shape[0], self.out_dim)]

    def output_dtypes(self):
        return [jnp.dtype(jnp.float32)]

    def weight_specs(self):
        return {
            "kernel": WeightSpec(
                shape=(self.num_entries, self.out_dim),
                initializer=self.kernel_initializer,
                axes=(VOCAB, CHANNEL_OUT),
            )
        }

    def forward(self, params, xs, ctx: OpContext):
        (idx,) = xs
        table = params["kernel"]
        emb = jnp.take(table, idx.astype(jnp.int32), axis=0)
        if self.aggr == AGGR_MODE_SUM:
            emb = jnp.sum(emb, axis=-2)
        elif self.aggr == AGGR_MODE_AVG:
            emb = jnp.mean(emb, axis=-2)
        return [emb]

    def output_axes(self):
        n = len(self.outputs[0].shape)
        axes = [None] * n
        axes[0] = SAMPLE
        axes[-1] = CHANNEL_OUT
        return [tuple(axes)]

    def input_axes(self):
        axes = [None] * len(self.inputs[0].shape)
        axes[0] = SAMPLE
        return [tuple(axes)]

    def flops(self) -> float:
        bag = self.inputs[0].shape[-1] if len(self.inputs[0].shape) > 1 else 1
        return float(self.inputs[0].shape[0] * bag * self.out_dim)
