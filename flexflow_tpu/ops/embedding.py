"""Embedding lookup with SUM/AVG aggregation.

Reference: src/ops/embedding.cu (custom gather/scatter-add kernels) plus a
hand-vectorized AVX2 CPU embedding-bag (embedding_avx2.cc:15-296). The op
takes int indices of shape (batch, bag) and produces (batch, out_dim),
aggregating over the bag dimension — DLRM-style embedding bag.

TPU-native design: a plain `take` gather; XLA lowers it to an efficient
one-hot-matmul or dynamic-gather depending on table size. The table's
`vocab` logical axis can be mapped to a mesh axis for DLRM parameter
parallelism (the reference placed whole tables on specific GPUs via
strategies, SURVEY.md 2.3; sharding the vocab dim over ICI is the TPU
generalization).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..op import (
    CHANNEL_OUT,
    SAMPLE,
    TABLE,
    VOCAB,
    Op,
    OpContext,
    WeightSpec,
    register_op,
)

AGGR_MODE_NONE = "none"
AGGR_MODE_SUM = "sum"
AGGR_MODE_AVG = "avg"


def _slot_gather(tables, ids):
    """(S, vocab, dim) slot-stacked tables x (S, batch, bag) per-slot
    ids -> (S, batch, bag, dim) rows, via ONE flat gather over the
    reshaped (S*vocab, dim) table with slot-offset global row ids.

    Deliberately NOT `vmap(take)`: a batched gather whose OPERAND is
    sharded on its batch (slot) dim trips XLA's SPMD partitioner — the
    vocab index component gets rescaled by the shard factor, so the
    kernel reads row 2*v on a 2-way table axis (NaN under take's
    "fill" OOB default, silently wrong rows under "clip"; the
    combined-mesh dryrun loss=nan, ROADMAP open item). The flat form
    keeps dim 0 sharded (slot blocks stay contiguous, so the layout —
    and the per-device residency the cost model prices — is unchanged)
    and single-dim gathers partition correctly; mode="clip" matches
    XLA's native clamp semantics, and real ids are in-bounds by
    construction (tests/test_distributed_embedding.py pins forward
    equality to the unsharded reference)."""
    S, V, _ = tables.shape
    flat = tables.reshape(S * V, tables.shape[-1])
    gid = ids + (jnp.arange(S, dtype=ids.dtype)[:, None, None] * V)
    return jnp.take(flat, gid, axis=0, mode="clip")


@register_op
class Embedding(Op):
    op_type = "embedding"

    def __init__(self, model, name, inputs, num_entries: int, out_dim: int,
                 aggr: str = AGGR_MODE_SUM, kernel_initializer: str = "glorot",
                 dtype=None):
        super().__init__(model, name, inputs)
        self.num_entries = int(num_entries)
        self.out_dim = int(out_dim)
        self.aggr = aggr
        self.kernel_initializer = kernel_initializer
        # output/activation dtype; the table itself stays f32 (mixed
        # precision: downstream compute follows the activation dtype)
        self.out_dtype = jnp.dtype(dtype) if dtype is not None \
            else jnp.dtype(jnp.float32)
        self.attrs = {"num_entries": num_entries, "out_dim": out_dim,
                      "aggr": aggr}

    def output_shapes(self):
        in_shape = self.inputs[0].shape
        if self.aggr == AGGR_MODE_NONE:
            return [tuple(in_shape) + (self.out_dim,)]
        # (batch, bag) -> (batch, out_dim): aggregate over the bag dim.
        return [(in_shape[0], self.out_dim)]

    def output_dtypes(self):
        return [self.out_dtype]

    def weight_specs(self):
        return {
            "kernel": WeightSpec(
                shape=(self.num_entries, self.out_dim),
                initializer=self.kernel_initializer,
                axes=(VOCAB, CHANNEL_OUT),
            )
        }

    def forward(self, params, xs, ctx: OpContext):
        (idx,) = xs
        if "__rows__" in params:
            # sparse-update path (executor pre-gathered the touched rows
            # outside the differentiated function): the gradient flows to
            # the ROWS, not the full table, and the optimizer applies a
            # scatter update — the TPU analog of the reference's
            # scatter-add embedding backward (src/ops/embedding.cu)
            emb = params["__rows__"]
        else:
            # mode="clip", not the "fill" (NaN) OOB default: fill mode
            # wraps the gather in an OOB-validity select that interacts
            # badly with GSPMD partitioning of sharded gathers (see
            # _slot_gather); clip is XLA's native clamp semantics and
            # partitions cleanly, and real ids are in-bounds anyway.
            emb = jnp.take(params["kernel"], idx.astype(jnp.int32), axis=0,
                           mode="clip")
        if self.aggr == AGGR_MODE_SUM:
            emb = jnp.sum(emb, axis=-2)
        elif self.aggr == AGGR_MODE_AVG:
            emb = jnp.mean(emb, axis=-2)
        return [emb.astype(self.out_dtype)]

    def output_axes(self):
        n = len(self.outputs[0].shape)
        axes = [None] * n
        axes[0] = SAMPLE
        axes[-1] = CHANNEL_OUT
        return [tuple(axes)]

    def input_axes(self):
        axes = [None] * len(self.inputs[0].shape)
        axes[0] = SAMPLE
        return [tuple(axes)]

    def flops(self) -> float:
        bag = self.inputs[0].shape[-1] if len(self.inputs[0].shape) > 1 else 1
        return float(self.inputs[0].shape[0] * bag * self.out_dim)


@register_op
class DistributedEmbedding(Op):
    """E same-vocab embedding bags as ONE stacked (E, vocab, dim) weight
    whose `table` logical axis maps to a mesh axis — the EXECUTABLE form
    of the reference's per-device table placement (DLRM strategies pin
    table i to GPU i, examples/cpp/DLRM/strategies/dlrm_strategy.cc:1-50;
    GSPMD cannot address single devices, so whole-table-per-device
    becomes table-axis sharding: with E == mesh-axis size each device
    holds exactly one vocab-complete table, lookups run concurrently
    where the tables live, and XLA inserts the output all-gather the
    simulator prices for placed ops).

    Inputs: E index tensors of shape (batch, bag); outputs: E tensors of
    shape (batch, dim) in the same order (drop-in for a list of
    `Embedding` ops, models/dlrm.py).

    Device-EXPLICIT placement (reference ParallelConfig.device_ids,
    executed by slice_task mapper.cc:346-440): `apply_placement` lowers
    a per-table device-id tuple from the strategy into a SLOT layout —
    tables are grouped by assigned device, padded to K tables per
    device, and stacked as (n_dev*K, vocab, dim) whose slot axis shards
    over the FULL mesh in device order, so slot block d literally lives
    on mesh.devices.flat[d]. An arbitrary search-placed assignment
    (scattered, skewed, or blocked) then EXECUTES under GSPMD instead of
    falling back to replication; outputs are returned in original table
    order via the inverse slot map."""

    op_type = "distributed_embedding"

    def __init__(self, model, name, inputs, num_entries: int, out_dim: int,
                 aggr: str = AGGR_MODE_SUM,
                 kernel_initializer: str = "glorot", dtype=None):
        super().__init__(model, name, inputs)
        assert len(inputs) >= 1
        bag = inputs[0].shape
        assert len(bag) == 2, (
            f"distributed_embedding inputs must be (batch, bag), got "
            f"{bag}; reshape 1-D indices to (batch, 1)")
        for t in inputs:
            assert tuple(t.shape) == tuple(bag), (
                "all sparse inputs must share (batch, bag) shape")
        self.num_tables = len(inputs)
        self.num_entries = int(num_entries)
        self.out_dim = int(out_dim)
        self.aggr = aggr
        self.kernel_initializer = kernel_initializer
        self.out_dtype = jnp.dtype(dtype) if dtype is not None \
            else jnp.dtype(jnp.float32)
        self.attrs = {"num_tables": self.num_tables,
                      "num_entries": num_entries, "out_dim": out_dim,
                      "aggr": aggr}
        # device-explicit placement state (set at executor build via
        # apply_placement; None = plain table-axis stacking)
        self.placement = None       # per-table device ids
        self._slots = None          # slot -> table index (-1 = pad)
        self._slot_of_table = None  # table -> slot
        self.num_slots = self.num_tables

    def apply_placement(self, device_ids, mesh=None) -> None:
        """Lower per-table `device_ids` to the executable slot layout
        (see class docstring), or reset to plain stacking when None.
        Re-entrant: the executor calls this at every compile so a
        strategy change relays out the weight. A length-1 tuple pins ALL
        tables to that one device (the reference's whole-op pin)."""
        if device_ids is not None and len(device_ids) == 1 \
                and self.num_tables > 1:
            device_ids = tuple(device_ids) * self.num_tables
        if device_ids is not None and mesh is None:
            # meshless compile: a device-explicit placement cannot
            # execute, and building the padded slot layout anyway would
            # only multiply kernel memory — reset to plain stacking
            import warnings
            warnings.warn(
                f"{self.name}: device-explicit placement {device_ids} "
                f"ignored — no mesh to place on (meshless compile)")
            device_ids = None
        if device_ids is None:
            self.placement = None
            self._slots = None
            self._slot_of_table = None
            self.num_slots = self.num_tables
            return
        if len(device_ids) != self.num_tables:
            raise ValueError(
                f"{self.name}: device_ids length {len(device_ids)} != "
                f"num_tables {self.num_tables} (per-table placement "
                f"needs one device id per table, or exactly one id to "
                f"pin all tables)")
        n_dev = int(mesh.size)
        ids = [int(d) for d in device_ids]
        if any(d < 0 or d >= n_dev for d in ids):
            raise ValueError(
                f"{self.name}: device ids {ids} out of range for "
                f"{n_dev} devices")
        groups = [[] for _ in range(n_dev)]
        for t, d in enumerate(ids):
            groups[d].append(t)
        k = max(1, max(len(g) for g in groups))
        if n_dev * k >= 4 * self.num_tables:
            # the slot layout pads every device to the LARGEST group, so
            # a skewed assignment multiplies kernel memory (a (E,v,d)
            # table becomes (n_dev*k,v,d)); the cost model prices this
            # (search/cost_model.py pad factor) — surface it for
            # hand-written strategies too
            import warnings
            warnings.warn(
                f"{self.name}: placement {ids} pads {self.num_tables} "
                f"tables to {n_dev * k} slots ({n_dev * k / self.num_tables:.1f}x "
                f"kernel memory); balance tables across devices to "
                f"avoid the padding")
        slots = []
        for g in groups:
            slots += g + [-1] * (k - len(g))
        self.placement = tuple(ids)
        self._slots = tuple(slots)
        self._slot_of_table = tuple(slots.index(t)
                                    for t in range(self.num_tables))
        self.num_slots = n_dev * k

    def to_table_order(self, kernel):
        """(num_slots, vocab, dim) slot-layout kernel -> (num_tables,
        vocab, dim) in TABLE order (pads dropped) — the user-facing
        layout get_weights returns regardless of placement."""
        if self._slot_of_table is None:
            return kernel
        return kernel[list(self._slot_of_table)]

    def from_table_order(self, kernel_tables, current):
        """Inverse of to_table_order: scatter a table-ordered kernel
        into the slot layout (pad slots keep `current`'s values)."""
        if self._slot_of_table is None:
            return kernel_tables
        out = np.array(current, copy=True)
        for t, s in enumerate(self._slot_of_table):
            out[s] = kernel_tables[t]
        return out

    def slot_ids(self, xs):
        """Stack per-table index arrays into the (num_slots, batch, bag)
        slot order the kernel is laid out in; pad slots read row 0 of
        their (unused) pad table."""
        if self._slots is None:
            cols = xs
        else:
            zero = None
            cols = []
            for t in self._slots:
                if t >= 0:
                    cols.append(xs[t])
                else:
                    if zero is None:
                        zero = jnp.zeros_like(xs[0])
                    cols.append(zero)
        return jnp.stack([c.astype(jnp.int32) for c in cols], axis=0)

    def output_shapes(self):
        bs = self.inputs[0].shape[0]
        if self.aggr == AGGR_MODE_NONE:
            return [tuple(self.inputs[0].shape) + (self.out_dim,)] \
                * self.num_tables
        return [(bs, self.out_dim)] * self.num_tables

    def output_dtypes(self):
        return [self.out_dtype] * self.num_tables

    def weight_specs(self):
        return {
            "kernel": WeightSpec(
                shape=(self.num_slots, self.num_entries, self.out_dim),
                initializer=self.kernel_initializer,
                axes=(TABLE, VOCAB, CHANNEL_OUT),
                fan_in=self.num_entries, fan_out=self.out_dim,
            )
        }

    def forward(self, params, xs, ctx: OpContext):
        if "__rows__" in params:
            emb = params["__rows__"]  # (S, batch, bag, dim) pre-gathered
        else:
            tables = params["kernel"]  # (S, vocab, dim), slot order
            ids = self.slot_ids(xs)
            # flat slot-offset gather (sharded on `table` or
            # device-placed via slots, each device reads only its
            # resident tables and GSPMD gathers the result) —
            # _slot_gather explains why this must not be vmap(take)
            emb = _slot_gather(tables, ids)
        if self.aggr == AGGR_MODE_SUM:
            emb = jnp.sum(emb, axis=-2)
        elif self.aggr == AGGR_MODE_AVG:
            emb = jnp.mean(emb, axis=-2)
        order = (self._slot_of_table if self._slot_of_table is not None
                 else range(self.num_tables))
        return [emb[s].astype(self.out_dtype) for s in order]

    def output_axes(self):
        n = len(self.outputs[0].shape)  # 3-D when aggr == "none"
        axes = [None] * n
        axes[0] = SAMPLE
        axes[-1] = CHANNEL_OUT
        return [tuple(axes)] * self.num_tables

    def input_axes(self):
        axes = [None] * len(self.inputs[0].shape)
        axes[0] = SAMPLE
        return [tuple(axes)] * self.num_tables

    def flops(self) -> float:
        bs, bag = self.inputs[0].shape[0], self.inputs[0].shape[-1]
        return float(self.num_tables * bs * bag * self.out_dim)
