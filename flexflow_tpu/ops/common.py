"""Shared helpers for ops (activation modes, padding math)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

# Activation modes, matching reference ActiMode (ffconst.h).
AC_MODE_NONE = "none"
AC_MODE_RELU = "relu"
AC_MODE_SIGMOID = "sigmoid"
AC_MODE_TANH = "tanh"
AC_MODE_GELU = "gelu"

_ACTIVATIONS = {
    AC_MODE_NONE: lambda x: x,
    AC_MODE_RELU: jax.nn.relu,
    AC_MODE_SIGMOID: jax.nn.sigmoid,
    AC_MODE_TANH: jnp.tanh,
    AC_MODE_GELU: jax.nn.gelu,
}


def apply_activation(x: jax.Array, mode) -> jax.Array:
    if mode is None or mode is False:
        return x
    if callable(mode):
        return mode(x)
    return _ACTIVATIONS[mode](x)


def conv_out_dim(in_size: int, kernel: int, stride: int, pad: int) -> int:
    """Output spatial size, matching the reference's conv shape math
    (src/runtime/model.cc:134-212 sub-tensor computation)."""
    return (in_size + 2 * pad - kernel) // stride + 1
