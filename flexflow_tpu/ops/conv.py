"""Convolution / pooling / batch-norm / flatten.

Reference: src/ops/conv_2d.cu (cuDNN conv with per-shape algorithm
auto-selection — on TPU, XLA picks the conv strategy during compilation, so
the whole algorithm-selection machinery at conv_2d.cu:173-260 disappears),
src/ops/pool_2d.cu, src/ops/batch_norm.cu, src/ops/flat.cu.

Layout: the graph-level API is NCHW to match reference examples 1:1.
`FFConfig.conv_layout = "NHWC"` makes Conv2D/Pool2D/BatchNorm COMPUTE in
NHWC (channels on the TPU's 128-lane minor dim): each op transposes in
and out, and XLA's algebraic simplifier cancels the adjacent pairs
inside conv->bn->pool chains, leaving layout conversions only at chain
boundaries. Logical shapes everywhere stay NCHW.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..op import (
    CHANNEL,
    CHANNEL_IN,
    CHANNEL_OUT,
    HEIGHT,
    SAMPLE,
    WIDTH,
    Op,
    OpContext,
    StateSpec,
    WeightSpec,
    register_op,
)
from .common import AC_MODE_NONE, apply_activation, conv_out_dim


@register_op
class Conv2D(Op):
    op_type = "conv2d"

    def __init__(self, model, name, inputs, out_channels: int,
                 kernel_h: int, kernel_w: int, stride_h: int, stride_w: int,
                 padding_h: int, padding_w: int, activation=AC_MODE_NONE,
                 groups: int = 1, use_bias: bool = True,
                 kernel_initializer: str = "glorot",
                 bias_initializer: str = "zeros"):
        super().__init__(model, name, inputs)
        n, c, h, w = inputs[0].shape
        self.in_channels = c
        self.out_channels = int(out_channels)
        self.kernel = (kernel_h, kernel_w)
        self.stride = (stride_h, stride_w)
        self.padding = (padding_h, padding_w)
        self.groups = groups
        self.activation = activation
        self.use_bias = use_bias
        self.kernel_initializer = kernel_initializer
        self.bias_initializer = bias_initializer
        self.out_h = conv_out_dim(h, kernel_h, stride_h, padding_h)
        self.out_w = conv_out_dim(w, kernel_w, stride_w, padding_w)
        self.attrs = {
            "out_channels": self.out_channels,
            "kernel": self.kernel,
            "stride": self.stride,
            "padding": self.padding,
            "groups": groups,
            "activation": activation,
            "use_bias": use_bias,
        }

    def output_shapes(self):
        n = self.inputs[0].shape[0]
        return [(n, self.out_channels, self.out_h, self.out_w)]

    def weight_specs(self) -> Dict[str, WeightSpec]:
        kh, kw = self.kernel
        specs = {
            "kernel": WeightSpec(
                shape=(self.out_channels, self.in_channels // self.groups, kh, kw),
                initializer=self.kernel_initializer,
                axes=(CHANNEL_OUT, CHANNEL_IN, None, None),
            )
        }
        if self.use_bias:
            specs["bias"] = WeightSpec(
                shape=(self.out_channels,),
                initializer=self.bias_initializer,
                axes=(CHANNEL_OUT,),
            )
        return specs

    def forward(self, params, xs, ctx: OpContext):
        (x,) = xs
        nhwc = self.model.config.conv_layout == "NHWC"
        y = _conv_apply(x, params["kernel"].astype(x.dtype),
                        params["bias"] if self.use_bias else None,
                        self.stride, self.padding, nhwc,
                        self.activation, self.groups,
                        already_nhwc=ctx.nhwc_in)
        if nhwc and not ctx.nhwc_out:
            y = jnp.transpose(y, (0, 3, 1, 2))
        return [y]

    def output_axes(self):
        return [(SAMPLE, CHANNEL_OUT, HEIGHT, WIDTH)]

    def input_axes(self):
        return [(SAMPLE, CHANNEL_IN, HEIGHT, WIDTH)]

    def flops(self) -> float:
        n = self.inputs[0].shape[0]
        kh, kw = self.kernel
        return (2.0 * n * self.out_channels * self.out_h * self.out_w
                * (self.in_channels // self.groups) * kh * kw)


def _conv_apply(x, kernel, bias, stride, padding, nhwc, activation,
                groups=1, already_nhwc=False):
    """Core conv lowering shared by Conv2D.forward and
    merged_conv_forward (so the fused and unfused paths cannot
    diverge). Returns y in COMPUTE layout (NHWC when nhwc, else NCHW);
    the caller transposes back. `already_nhwc` marks an input that the
    executor's residency pass left channels-last.

    No preferred_element_type: the MXU accumulates bf16 convs in f32
    natively, and conv's gradient transpose rejects the mixed
    f32-cotangent/bf16-operand pair the flag would create (unlike
    dot_general's); output dtype follows the activations."""
    ph, pw = padding
    if nhwc and not already_nhwc:
        x = jnp.transpose(x, (0, 2, 3, 1))
    y = lax.conv_general_dilated(
        x,
        kernel,
        window_strides=stride,
        padding=[(ph, ph), (pw, pw)],
        dimension_numbers=(("NHWC", "OIHW", "NHWC") if nhwc
                           else ("NCHW", "OIHW", "NCHW")),
        feature_group_count=groups,
    )
    if bias is not None:
        bshape = (1, 1, 1, -1) if nhwc else (1, -1, 1, 1)
        y = y + bias.reshape(bshape).astype(y.dtype)
    return apply_activation(y, activation)


def merged_conv_forward(ops: List["Conv2D"], params_list, x,
                        nhwc_in=False, nhwc_out=False):
    """Execute sibling Conv2D ops (core/fusion.conv_sibling_groups) as
    ONE conv: kernels concatenate along channel-out, the output splits
    back per member. Exact numerics — each output channel's contraction
    is untouched; only MXU lane packing changes. The trace-time kernel
    concat is a weight-sized copy (KBs for 1x1 convs), dwarfed by the
    conv itself, and autodiff slices the cotangent back to the per-op
    kernels so optimizer/checkpoint state stays per-layer.

    All members share geometry by construction, so the leader's stride/
    padding/activation speak for the group.
    """
    lead = ops[0]
    nhwc = lead.model.config.conv_layout == "NHWC"
    kernel = jnp.concatenate(
        [p["kernel"].astype(x.dtype) for p in params_list], axis=0)
    bias = (jnp.concatenate([p["bias"] for p in params_list])
            if lead.use_bias else None)
    y = _conv_apply(x, kernel, bias, lead.stride, lead.padding, nhwc,
                    lead.activation, already_nhwc=nhwc_in)
    offsets = [0]
    for op in ops:
        offsets.append(offsets[-1] + op.out_channels)
    ch_axis = 3 if nhwc else 1
    outs = []
    for i in range(len(ops)):
        sl = lax.slice_in_dim(y, offsets[i], offsets[i + 1], axis=ch_axis)
        if nhwc and not nhwc_out:
            sl = jnp.transpose(sl, (0, 3, 1, 2))
        outs.append(sl)
    return outs


@register_op
class Pool2D(Op):
    op_type = "pool2d"

    POOL_MAX = "max"
    POOL_AVG = "avg"

    def __init__(self, model, name, inputs, kernel_h, kernel_w, stride_h,
                 stride_w, padding_h, padding_w, pool_type="max",
                 activation=AC_MODE_NONE):
        super().__init__(model, name, inputs)
        n, c, h, w = inputs[0].shape
        self.kernel = (kernel_h, kernel_w)
        self.stride = (stride_h, stride_w)
        self.padding = (padding_h, padding_w)
        self.pool_type = pool_type
        self.activation = activation
        self.out_h = conv_out_dim(h, kernel_h, stride_h, padding_h)
        self.out_w = conv_out_dim(w, kernel_w, stride_w, padding_w)
        self.attrs = {"kernel": self.kernel, "stride": self.stride,
                      "padding": self.padding, "pool_type": pool_type}

    def output_shapes(self):
        n, c = self.inputs[0].shape[:2]
        return [(n, c, self.out_h, self.out_w)]

    def forward(self, params, xs, ctx: OpContext):
        (x,) = xs
        kh, kw = self.kernel
        sh, sw = self.stride
        ph, pw = self.padding
        nhwc = self.model.config.conv_layout == "NHWC"
        if nhwc:
            if not ctx.nhwc_in:
                x = jnp.transpose(x, (0, 2, 3, 1))
            window = (1, kh, kw, 1)
            strides = (1, sh, sw, 1)
            pads = ((0, 0), (ph, ph), (pw, pw), (0, 0))
        else:
            window = (1, 1, kh, kw)
            strides = (1, 1, sh, sw)
            pads = ((0, 0), (0, 0), (ph, ph), (pw, pw))
        if self.pool_type == self.POOL_MAX:
            init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
            y = lax.reduce_window(x, init, lax.max, window, strides, pads)
        else:
            summed = lax.reduce_window(x, 0.0, lax.add, window, strides, pads)
            # cuDNN CUDNN_POOLING_AVERAGE_COUNT_INCLUDE_PADDING semantics
            y = summed / float(kh * kw)
        y = apply_activation(y, self.activation)
        if nhwc and not ctx.nhwc_out:
            y = jnp.transpose(y, (0, 3, 1, 2))
        return [y]

    def output_axes(self):
        return [(SAMPLE, CHANNEL, HEIGHT, WIDTH)]

    def input_axes(self):
        return [(SAMPLE, CHANNEL, HEIGHT, WIDTH)]

    def flops(self) -> float:
        n, c = self.inputs[0].shape[:2]
        kh, kw = self.kernel
        return float(n * c * self.out_h * self.out_w * kh * kw)


@register_op
class BatchNorm(Op):
    """Training-mode batch norm with running stats.

    Reference: src/ops/batch_norm.cu (cuDNN BN, running stats in a Realm
    instance, model.h:883-899). Running stats here are functional state in
    the executor's `state` pytree, updated each training step.
    """

    op_type = "batch_norm"
    MOMENTUM = 0.9
    EPS = 1e-5

    def __init__(self, model, name, inputs, relu: bool = True):
        super().__init__(model, name, inputs)
        self.relu = relu
        self.num_channels = inputs[0].shape[1]
        self.attrs = {"relu": relu}

    def output_shapes(self):
        return [tuple(self.inputs[0].shape)]

    def weight_specs(self):
        c = self.num_channels
        return {
            "scale": WeightSpec((c,), initializer="ones", axes=(CHANNEL,)),
            "bias": WeightSpec((c,), initializer="zeros", axes=(CHANNEL,)),
        }

    def state_specs(self):
        c = self.num_channels
        return {
            "running_mean": StateSpec((c,), init_value=0.0),
            "running_var": StateSpec((c,), init_value=1.0),
        }

    def forward(self, params, xs, ctx: OpContext):
        (x,) = xs
        nhwc = (x.ndim == 4
                and self.model.config.conv_layout == "NHWC")
        if nhwc:
            if not ctx.nhwc_in:
                x = jnp.transpose(x, (0, 2, 3, 1))
            reduce_axes = (0, 1, 2)
            ch_axis = 3
        else:
            reduce_axes = (0, 2, 3) if x.ndim == 4 else tuple(
                i for i in range(x.ndim) if i != 1)
            ch_axis = 1
        if ctx.training:
            xf = x.astype(jnp.float32)
            mean = jnp.mean(xf, axis=reduce_axes)
            var = jnp.var(xf, axis=reduce_axes)
            ctx.state_out["running_mean"] = (
                self.MOMENTUM * ctx.state_in["running_mean"]
                + (1 - self.MOMENTUM) * mean)
            ctx.state_out["running_var"] = (
                self.MOMENTUM * ctx.state_in["running_var"]
                + (1 - self.MOMENTUM) * var)
        else:
            mean = ctx.state_in["running_mean"]
            var = ctx.state_in["running_var"]
            ctx.state_out["running_mean"] = mean
            ctx.state_out["running_var"] = var
        shape = [1] * x.ndim
        shape[ch_axis] = -1
        inv = lax.rsqrt(var + self.EPS).reshape(shape).astype(x.dtype)
        mean = mean.reshape(shape).astype(x.dtype)
        y = (x - mean) * inv * params["scale"].reshape(shape).astype(
            x.dtype) + params["bias"].reshape(shape).astype(x.dtype)
        if self.relu:
            y = jax.nn.relu(y)
        if nhwc and not ctx.nhwc_out:
            y = jnp.transpose(y, (0, 3, 1, 2))
        return [y]

    def output_axes(self):
        n = len(self.outputs[0].shape)
        axes = [None] * n
        axes[0] = SAMPLE
        axes[1] = CHANNEL
        return [tuple(axes)]

    input_axes = output_axes

    def flops(self) -> float:
        return 8.0 * self.inputs[0].num_elements


@register_op
class Flat(Op):
    """4D (N,C,H,W) -> 2D (N, C*H*W). Reference: src/ops/flat.cu."""

    op_type = "flat"

    def output_shapes(self):
        n = self.inputs[0].shape[0]
        rest = 1
        for s in self.inputs[0].shape[1:]:
            rest *= s
        return [(n, rest)]

    def forward(self, params, xs, ctx: OpContext):
        (x,) = xs
        return [x.reshape(x.shape[0], -1)]

    def output_axes(self):
        return [(SAMPLE, CHANNEL)]

    def input_axes(self):
        axes = [None] * len(self.inputs[0].shape)
        axes[0] = SAMPLE
        return [tuple(axes)]
