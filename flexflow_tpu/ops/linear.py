"""Dense / Linear layer.

Reference: src/ops/linear.cu (1120 LoC) — cuBLAS SGEMM forward, two GEMMs +
GEMV backward, and hand-built parameter parallelism: when out_channels is
split the reference replicates the input tensor and adds a `backward2`
replica-reduction task (linear.cu:144-270, 766-820). On TPU all of that
collapses to a single jnp.dot with the kernel's `channel_out` logical axis
mapped to a mesh axis: GSPMD inserts the all-gather/reduce-scatter.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp

from ..op import (
    CHANNEL_IN,
    CHANNEL_OUT,
    SAMPLE,
    SEQ,
    Op,
    OpContext,
    WeightSpec,
    register_op,
)
from .common import AC_MODE_NONE, apply_activation


@register_op
class Linear(Op):
    op_type = "linear"

    def __init__(self, model, name, inputs, out_channels: int,
                 activation=AC_MODE_NONE, use_bias: bool = True,
                 kernel_initializer: str = "glorot",
                 bias_initializer: str = "zeros"):
        super().__init__(model, name, inputs)
        self.out_channels = int(out_channels)
        self.in_channels = int(inputs[0].shape[-1])
        self.activation = activation
        self.use_bias = use_bias
        self.kernel_initializer = kernel_initializer
        self.bias_initializer = bias_initializer
        self.attrs = {
            "out_channels": self.out_channels,
            "activation": activation,
            "use_bias": use_bias,
        }

    def output_shapes(self) -> List[Tuple[int, ...]]:
        return [tuple(self.inputs[0].shape[:-1]) + (self.out_channels,)]

    def weight_specs(self) -> Dict[str, WeightSpec]:
        # Kernel stored (in, out): the natural layout for x @ W on the MXU.
        # (The reference stores (out, in) for cuBLAS^T, linear.cu:488-546.)
        specs = {
            "kernel": WeightSpec(
                shape=(self.in_channels, self.out_channels),
                initializer=self.kernel_initializer,
                axes=(CHANNEL_IN, CHANNEL_OUT),
            )
        }
        if self.use_bias:
            specs["bias"] = WeightSpec(
                shape=(self.out_channels,),
                initializer=self.bias_initializer,
                axes=(CHANNEL_OUT,),
            )
        return specs

    def forward(self, params, xs, ctx: OpContext):
        (x,) = xs
        y = jnp.dot(x, params["kernel"].astype(x.dtype),
                    preferred_element_type=jnp.float32)
        y = y.astype(x.dtype)
        if self.use_bias:
            y = y + params["bias"].astype(x.dtype)
        return [apply_activation(y, self.activation)]

    def output_axes(self):
        n = len(self.outputs[0].shape)
        axes = [None] * n
        axes[0] = SAMPLE
        if n == 3:
            axes[1] = SEQ  # (batch, seq, features) layout
        axes[-1] = CHANNEL_OUT
        return [tuple(axes)]

    def input_axes(self):
        n = len(self.inputs[0].shape)
        axes = [None] * n
        axes[0] = SAMPLE
        if n == 3:
            axes[1] = SEQ
        axes[-1] = CHANNEL_IN
        return [tuple(axes)]

    def flops(self) -> float:
        batch = 1
        for s in self.inputs[0].shape[:-1]:
            batch *= s
        return 2.0 * batch * self.in_channels * self.out_channels
