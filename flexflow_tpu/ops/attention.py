"""Multi-head attention.

Reference: src/ops/attention.cu — a single cuDNN fused-MHA call
(cudnnMultiHeadAttnForward, attention.cu:245) with one packed 3-D weight
tensor holding {Wq,Wk,Wv,Wo} per head (attention.cu:88-104).

TPU-native design: separate (E, H, D) projection weights whose `head`
logical axis maps to a mesh axis for TP (Megatron-style), and a Pallas
flash-attention kernel (flexflow_tpu/kernels/flash_attention.py) for the
core softmax(QK^T)V — the op the north star explicitly calls out for
replacement. Long-sequence SP/CP shards the `seq` axis; see
flexflow_tpu/parallel/ring_attention.py.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..op import (
    CHANNEL_IN,
    CHANNEL_OUT,
    HEAD,
    SAMPLE,
    SEQ,
    Op,
    OpContext,
    WeightSpec,
    register_op,
)


@register_op
class MultiHeadAttention(Op):
    op_type = "multihead_attention"

    def __init__(self, model, name, inputs, embed_dim: int, num_heads: int,
                 kdim: int = 0, vdim: int = 0, dropout: float = 0.0,
                 use_bias: bool = False, add_bias_kv: bool = False,
                 add_zero_attn: bool = False, causal: bool = False,
                 kernel_initializer: str = "glorot",
                 use_flash=None):
        super().__init__(model, name, inputs)
        q, k, v = inputs
        self.embed_dim = int(embed_dim)
        self.num_heads = int(num_heads)
        self.kdim = int(kdim) if kdim > 0 else self.embed_dim
        self.vdim = int(vdim) if vdim > 0 else self.embed_dim
        assert self.embed_dim % self.num_heads == 0
        self.head_dim = self.embed_dim // self.num_heads
        self.dropout = dropout
        self.use_bias = use_bias
        self.add_bias_kv = add_bias_kv
        self.add_zero_attn = add_zero_attn
        self.causal = causal
        self.use_flash = use_flash
        self.q_in = q.shape[-1]
        self.k_in = k.shape[-1]
        self.v_in = v.shape[-1]
        # self-attention detected at GRAPH level (same input tensor
        # wired to q/k/v) — runtime array identity is unreliable:
        # jax.checkpoint re-flattens duplicated leaves into distinct
        # tracers, which would silently disable the fused path under
        # remat
        self._fused_qkv = (q is k and k is v
                           and self.q_in == self.k_in == self.v_in)
        # cross-attention (seq2seq decoders): K and V read the SAME
        # encoder output — fuse their projections into one 2x-wide GEMM
        self._fused_kv = (not self._fused_qkv and k is v
                          and self.k_in == self.v_in)
        self.kernel_initializer = kernel_initializer
        self.attrs = {"embed_dim": embed_dim, "num_heads": num_heads,
                      "dropout": dropout, "use_bias": use_bias,
                      "causal": causal}

    def output_shapes(self):
        q = self.inputs[0]
        return [(q.shape[0], q.shape[1], self.embed_dim)]

    def weight_specs(self):
        h, d = self.num_heads, self.head_dim
        e = self.embed_dim
        specs = {
            "wq": WeightSpec((self.q_in, h, d), initializer=self.kernel_initializer,
                             axes=(CHANNEL_IN, HEAD, None),
                             fan_in=self.q_in, fan_out=e),
            "wk": WeightSpec((self.k_in, h, d), initializer=self.kernel_initializer,
                             axes=(CHANNEL_IN, HEAD, None),
                             fan_in=self.k_in, fan_out=e),
            "wv": WeightSpec((self.v_in, h, d), initializer=self.kernel_initializer,
                             axes=(CHANNEL_IN, HEAD, None),
                             fan_in=self.v_in, fan_out=e),
            "wo": WeightSpec((h, d, e),
                             initializer=self.kernel_initializer,
                             axes=(HEAD, None, CHANNEL_OUT),
                             fan_in=e, fan_out=e),
        }
        if self.use_bias:
            specs["bo"] = WeightSpec((self.embed_dim,), initializer="zeros",
                                     axes=(CHANNEL_OUT,))
        if self.add_bias_kv:
            # one learned extra kv position (torch MultiheadAttention
            # bias_k/bias_v semantics)
            specs["bias_k"] = WeightSpec((1, h, d), initializer="zeros",
                                         axes=(None, HEAD, None))
            specs["bias_v"] = WeightSpec((1, h, d), initializer="zeros",
                                         axes=(None, HEAD, None))
        return specs

    def forward(self, params, xs, ctx: OpContext):
        q_in, k_in, v_in = xs
        if self._fused_qkv:
            # self-attention: ONE fused (E, 3·H·D) projection GEMM
            # instead of three E x H·D GEMMs — same math, wider MXU
            # call (XLA does not horizontally fuse parallel dots; the
            # reference's cuDNN MHA packs a single QKV weight tensor
            # for the same reason, attention.cu:88-104). The stack of
            # the three weight leaves is a few MB of HBM, trivially
            # amortized by the 3x-wider GEMM.
            w = jnp.stack([params["wq"], params["wk"], params["wv"]],
                          axis=1).astype(q_in.dtype)  # (E, 3, H, D)
            qkv = jnp.einsum("bse,exhd->xbshd", q_in, w)
            q, k, v = qkv[0], qkv[1], qkv[2]
        else:
            q = jnp.einsum("bse,ehd->bshd", q_in,
                           params["wq"].astype(q_in.dtype))
            if self._fused_kv:
                # one 2x-wide GEMM over the shared encoder output
                w = jnp.stack([params["wk"], params["wv"]],
                              axis=1).astype(k_in.dtype)  # (E, 2, H, D)
                kv = jnp.einsum("bse,exhd->xbshd", k_in, w)
                k, v = kv[0], kv[1]
            else:
                k = jnp.einsum("bse,ehd->bshd", k_in,
                               params["wk"].astype(k_in.dtype))
                v = jnp.einsum("bse,ehd->bshd", v_in,
                               params["wv"].astype(v_in.dtype))
        if self.add_bias_kv:
            b = k.shape[0]
            bk = jnp.broadcast_to(params["bias_k"].astype(k.dtype),
                                  (b,) + params["bias_k"].shape)
            bv = jnp.broadcast_to(params["bias_v"].astype(v.dtype),
                                  (b,) + params["bias_v"].shape)
            k = jnp.concatenate([k, bk], axis=1)
            v = jnp.concatenate([v, bv], axis=1)

        o = self._attend(q, k, v, ctx)

        y = jnp.einsum("bshd,hde->bse", o, params["wo"].astype(o.dtype))
        if self.use_bias:
            y = y + params["bo"].astype(y.dtype)
        if self.dropout > 0.0 and ctx.training and ctx.rng is not None:
            keep = 1.0 - self.dropout
            mask = jax.random.bernoulli(ctx.rng, keep, y.shape)
            y = jnp.where(mask, y / keep, 0.0).astype(y.dtype)
        return [y]

    def _attend(self, q, k, v, ctx: OpContext):
        """softmax(QK^T/sqrt(d))V, (b, s, h, d) layout."""
        has_seq_trunc = ctx.seq_length is not None and ctx.seq_length >= 0
        # Sequence parallelism: when the strategy maps `seq` to a mesh
        # axis, run ring attention over that axis (K/V rotate over ICI).
        # Guards mirror spec_for_axes' graceful degradation: fall back to
        # the XLA path when shapes don't divide the mesh axes or when kv
        # carries extra rows (bias_kv/zero_attn).
        seq_size = ctx.mesh_axis_size("seq")
        if (seq_size > 1 and not has_seq_trunc
                and not self.add_zero_attn and not self.add_bias_kv
                and q.shape[1] % seq_size == 0
                and k.shape[1] % seq_size == 0):
            from ..parallel.ring_attention import ring_attention
            from ..parallel.ulysses import alltoall_attention, sp_mode_for
            data_ax = ctx.mesh_axis_name("sample") or "data"
            data_size = (ctx.mesh.shape.get(data_ax, 1)
                         if ctx.mesh is not None else 1)
            if q.shape[0] % max(1, data_size) == 0:
                # two SP lowerings: ring (K/V rotate, never materializes
                # scores) vs all-to-all (heads scatter, full-seq blocks
                # on the MXU); sp_mode_for is the single policy both
                # execution and the cost model consult
                mode = sp_mode_for(
                    getattr(self.model.config, "sp_attention", "auto"),
                    num_heads=self.num_heads, seq_size=seq_size,
                    batch_local=q.shape[0] // max(1, data_size),
                    seq_q=q.shape[1], seq_kv=k.shape[1])
                if mode == "alltoall":
                    return alltoall_attention(
                        q, k, v, ctx.mesh,
                        seq_axis=ctx.mesh_axis_name("seq"),
                        batch_axis=data_ax, causal=self.causal,
                        scale=1.0 / math.sqrt(self.head_dim),
                        use_flash=self.use_flash)
                return ring_attention(
                    q, k, v, ctx.mesh, seq_axis=ctx.mesh_axis_name("seq"),
                    batch_axis=data_ax, causal=self.causal,
                    scale=1.0 / math.sqrt(self.head_dim))
        if self.add_zero_attn:
            zero = jnp.zeros(k.shape[:1] + (1,) + k.shape[2:], k.dtype)
            k = jnp.concatenate([k, zero], axis=1)
            v = jnp.concatenate([v, zero], axis=1)
        # flash path handles neither seq_length truncation nor the
        # (now off-block-size) zero-attn row; use XLA for those.
        #
        # use_flash is tri-state: None = auto (the measured
        # flash_profitable gate, kernels/flash_attention.py — shared
        # with the all-to-all SP lowering), True = force the Pallas
        # kernel whenever shapes allow, False = never. pad_lanes=False
        # for d=64 showed no consistent win in the same sweep, so it
        # stays opt-in via flash_attention_bshd.
        b, sq, h, d = q.shape
        sk = k.shape[1]
        from ..kernels.flash_attention import flash_profitable
        if ((self.use_flash is True
             or (self.use_flash is None
                 and flash_profitable(b, h, sq, sk, d)))
                and not has_seq_trunc and not self.add_zero_attn):
            from ..kernels.flash_attention import flash_attention_bshd
            try:
                return flash_attention_bshd(q, k, v, causal=self.causal)
            except Exception:
                pass  # fall back to the XLA path (e.g. tiny shapes on CPU)
        scale = 1.0 / math.sqrt(self.head_dim)
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                            preferred_element_type=jnp.float32) * scale
        if self.causal:
            # top-left alignment (query i attends keys j <= i), matching
            # the Pallas forward kernel's qpos >= kpos mask.
            lq, lk = logits.shape[-2], logits.shape[-1]
            mask = jnp.tril(jnp.ones((lq, lk), dtype=bool))
            logits = jnp.where(mask, logits, -jnp.inf)
        if ctx.seq_length is not None and ctx.seq_length >= 0:
            kidx = jnp.arange(logits.shape[-1])
            logits = jnp.where(kidx[None, None, None, :] < ctx.seq_length,
                               logits, -jnp.inf)
        probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
        return jnp.einsum("bhqk,bkhd->bqhd", probs, v)

    def output_axes(self):
        return [(SAMPLE, SEQ, CHANNEL_OUT)]

    def input_axes(self):
        return [(SAMPLE, SEQ, CHANNEL_IN)] * 3

    def flops(self) -> float:
        b, lq = self.inputs[0].shape[:2]
        lk = self.inputs[1].shape[1]
        e, h, d = self.embed_dim, self.num_heads, self.head_dim
        proj = 2.0 * b * (lq * self.q_in + lk * self.k_in + lk * self.v_in) * e
        attn = 2.0 * b * h * lq * lk * d * 2
        out = 2.0 * b * lq * e * e
        return proj + attn + out
