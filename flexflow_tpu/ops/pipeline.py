"""PipelineBlocks: a stack of identical sub-graphs with first-class
pipeline parallelism.

Schedule note: this meta-op runs GPipe (forward schedule + autodiff
transpose). True 1F1B cannot live inside an op that is differentiated
as part of a larger graph — interleaving a stage's backward with later
forwards requires the downstream cotangent DURING the forward pass,
which only exists when the pipeline owns the whole training step. That
form is provided by the graph-level staged executor
(core/staged.py + parallel/graph_pipeline.pipeline_1f1b_grads):
build the stack from plain per-layer ops and pin/auto-cut stages with
--pipeline-schedule 1f1b.

Builder: ``ff.pipeline_blocks(x, block_builder, num_layers)`` where
``block_builder(sub_model, t) -> t_out`` constructs one shape-preserving
block using the normal layer API on a sub-FFModel. Weights of every block
op are stacked with a leading `layer` dim; when the strategy maps `layer`
to a mesh `pipe` axis, forward runs the GPipe collective-permute schedule
(parallel/pipeline.py); otherwise it is a plain lax.scan over layers
(which XLA compiles to a single fused loop — also the idiomatic TPU way
to build deep repeated models).
"""

from __future__ import annotations

from typing import Callable, Dict, List

import jax
import jax.numpy as jnp
from jax import lax

from ..op import LAYER, SAMPLE, SEQ, Op, OpContext, WeightSpec, register_op


@register_op
class PipelineBlocks(Op):
    op_type = "pipeline_blocks"
    has_aux_loss = True  # may carry sub-op aux losses; excluded from remat

    def __init__(self, model, name, inputs, block_builder: Callable,
                 num_layers: int, num_microbatches: int = 4):
        super().__init__(model, name, inputs)
        self.num_layers = int(num_layers)
        self.num_microbatches = int(num_microbatches)
        # build the symbolic block sub-graph once
        from ..model import FFModel
        from ..config import FFConfig
        sub = FFModel(FFConfig())
        x_sym = sub.create_tensor(inputs[0].shape, dtype=inputs[0].dtype,
                                  name="block_input")
        out_sym = block_builder(sub, x_sym)
        assert tuple(out_sym.shape) == tuple(inputs[0].shape), (
            f"pipeline block must preserve shape: {inputs[0].shape} -> "
            f"{out_sym.shape}")
        for op in sub.ops:
            assert not op.state_specs(), (
                f"stateful op {op.name} not supported inside pipeline "
                f"blocks (functional scan)")
        self.sub = sub
        self.sub_input = x_sym
        self.sub_output = out_sym
        self.attrs = {"num_layers": num_layers,
                      "num_microbatches": num_microbatches}

    def output_shapes(self):
        return [tuple(self.inputs[0].shape)]

    def weight_specs(self) -> Dict[str, WeightSpec]:
        specs = {}
        for op in self.sub.ops:
            for wname, s in op.weight_specs().items():
                specs[f"{op.name}.{wname}"] = WeightSpec(
                    shape=(self.num_layers,) + tuple(s.shape),
                    dtype=s.dtype,
                    initializer=s.initializer,
                    axes=(LAYER,) + tuple(s.axes),
                    custom_init=self._stacked_init(s) if (
                        s.custom_init or s.fan_in or s.fan_out
                        or s.initializer not in ("zeros", "ones")) else None,
                    fan_in=s.fan_in, fan_out=s.fan_out,
                )
        return specs

    @staticmethod
    def _stacked_init(spec: WeightSpec):
        """Initialize each layer slice independently (vmapped keys)."""
        from ..core import initializers as I

        base = spec.custom_init or I.resolve(spec.initializer)

        def init(key, shape, dtype, fan_in=None, fan_out=None):
            L = shape[0]
            keys = jax.random.split(key, L)
            def one(k):
                try:
                    return base(k, shape[1:], dtype, fan_in=spec.fan_in,
                                fan_out=spec.fan_out)
                except TypeError:
                    return base(k, shape[1:], dtype)
            return jax.vmap(one)(keys)

        return init

    def _block_fn(self, ctx: OpContext):
        sub = self.sub

        def block_fn(layer_params: Dict[str, jax.Array], h, layer_idx):
            values = {self.sub_input.uid: h}
            aux = jnp.float32(0.0)
            layer_rng = (jax.random.fold_in(ctx.rng, layer_idx)
                         if ctx.rng is not None else None)
            for i, op in enumerate(sub.ops):
                sub_ctx = OpContext(
                    training=ctx.training,
                    rng=(jax.random.fold_in(layer_rng, i)
                         if layer_rng is not None else None),
                    seq_length=ctx.seq_length,
                    mesh=ctx.mesh, op_strategy=ctx.op_strategy)
                op_params = {w: layer_params[f"{op.name}.{w}"]
                             for w in op.weight_specs()}
                xs = [values[t.uid] for t in op.inputs]
                ys = op.forward(op_params, xs, sub_ctx)
                for t, y in zip(op.outputs, ys):
                    values[t.uid] = y
                if sub_ctx.aux_loss is not None:
                    aux = aux + sub_ctx.aux_loss
            return values[self.sub_output.uid], aux

        return block_fn

    def forward(self, params, xs, ctx: OpContext):
        (x,) = xs
        from ..parallel.pipeline import pipeline_apply
        block_fn = self._block_fn(ctx)
        pipe_size = ctx.mesh_axis_size("layer")
        mesh = ctx.mesh if pipe_size > 1 else None
        if mesh is not None:
            data_ax = ctx.mesh_axis_name("sample") or "data"
            out, aux = pipeline_apply(
                block_fn, params, x, mesh,
                pipe_axis=ctx.mesh_axis_name("layer"),
                num_microbatches=self.num_microbatches,
                num_layers=self.num_layers,
                data_axis=data_ax)
        else:
            def body(carry, inp):
                h, a = carry
                lp, li = inp
                y, la = block_fn(lp, h, li)
                return (y, a + la), None
            (out, aux), _ = lax.scan(
                body, (x, jnp.float32(0.0)),
                (params, jnp.arange(self.num_layers)),
                length=self.num_layers)
        if ctx.training:
            ctx.aux_loss = aux
        return [out]

    def output_axes(self):
        n = len(self.outputs[0].shape)
        axes = [None] * n
        axes[0] = SAMPLE
        if n == 3:
            axes[1] = SEQ
        return [tuple(axes)]

    input_axes = output_axes

    def flops(self) -> float:
        return self.num_layers * sum(op.flops() for op in self.sub.ops)
