"""Operator library — TPU-native equivalents of reference src/ops/*.cu.

Each op is a pure-functional JAX computation (forward only; backward comes
from autodiff of the whole step). The hot ops additionally have Pallas
kernels under flexflow_tpu/kernels/.
"""

from .linear import Linear
from .conv import Conv2D, Pool2D, BatchNorm, Flat
from .elementwise import ElementUnary, ElementBinary, Dropout, LayerNorm, Reduce, Softmax
from .tensor_ops import (
    Concat,
    Split,
    Reshape,
    Transpose,
    Reverse,
    TopK,
    BatchMatmul,
)
from .embedding import DistributedEmbedding, Embedding
from .attention import MultiHeadAttention
from .moe import GroupBy, Aggregate
from .moe_ffn import MoEFFN
from .pipeline import PipelineBlocks
from .rnn import LSTM

__all__ = [
    "Linear",
    "Conv2D",
    "Pool2D",
    "BatchNorm",
    "Flat",
    "ElementUnary",
    "ElementBinary",
    "Reduce",
    "Dropout",
    "Softmax",
    "LayerNorm",
    "Concat",
    "Split",
    "Reshape",
    "Transpose",
    "Reverse",
    "TopK",
    "BatchMatmul",
    "DistributedEmbedding",
    "Embedding",
    "MultiHeadAttention",
    "GroupBy",
    "Aggregate",
    "MoEFFN",
    "PipelineBlocks",
    "LSTM",
]
