"""Transformer encoder — the flagship model.

Reference: examples/cpp/Transformer/transformer.cc:28-56,110-135 — an
encoder of MultiHeadAttention + dense blocks (512 hidden / 8 layers,
synthetic data). We keep the same op mix (MHA + dense + elementwise add);
the attention core runs through the Pallas flash kernel on TPU.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ..config import FFConfig
from ..model import FFModel


def build_transformer_lm(config: Optional[FFConfig] = None,
                         vocab_size: int = 256, max_seq_len: int = 128,
                         batch_size: int = None, hidden: int = 256,
                         num_heads: int = 4, num_layers: int = 2,
                         ff_dim: int = 512, dtype=None,
                         mesh=None, strategy=None,
                         layer_norm: bool = True) -> FFModel:
    """Causal decoder LM — the serving counterpart of the encoder
    classifier below, consumed by flexflow_tpu.serve.ServeEngine.

    Token + learned-position embeddings, pre-LN causal-attention blocks,
    final LN, tied-nothing vocab head. The op NAMES are the contract
    the ServeEngine reads weights through (tok_embed / pos_embed /
    layer{i}_{ln1,attn,ln2,ff1,ff2} / final_ln / lm_head) — the graph
    itself also runs as a normal FFModel (training the LM uses the
    ordinary executor; serving bypasses the graph for the cached decode
    path but the parameters are the same arrays)."""
    cfg = config or FFConfig()
    if dtype is None:
        # the serving activation dtype follows the config's precision
        # policy: a bf16 compute_dtype serves bf16 activations (the
        # ServeEngine mirrors whatever tok_embed emits, so the greedy
        # exactness oracle holds at the engine's own precision)
        dtype = jnp.dtype(cfg.compute_dtype)
    bs = batch_size or cfg.batch_size
    ff = FFModel(cfg, mesh=mesh, strategy=strategy)
    tokens = ff.create_tensor((bs, max_seq_len), dtype=jnp.int32,
                              name="tokens")
    positions = ff.create_tensor((bs, max_seq_len), dtype=jnp.int32,
                                 name="positions")
    te = ff.embedding(tokens, vocab_size, hidden, aggr="none",
                      name="tok_embed", dtype=dtype)
    pe = ff.embedding(positions, max_seq_len, hidden, aggr="none",
                      name="pos_embed", dtype=dtype)
    t = ff.add(te, pe, name="embed_add")
    for i in range(num_layers):
        a_in = ff.layer_norm(t, name=f"layer{i}_ln1") if layer_norm else t
        a = ff.multihead_attention(a_in, a_in, a_in, hidden, num_heads,
                                   causal=True, name=f"layer{i}_attn")
        t = ff.add(a, t, name=f"layer{i}_res1")
        f_in = ff.layer_norm(t, name=f"layer{i}_ln2") if layer_norm else t
        h = ff.dense(f_in, ff_dim, activation="relu", name=f"layer{i}_ff1")
        h = ff.dense(h, hidden, name=f"layer{i}_ff2")
        t = ff.add(h, t, name=f"layer{i}_res2")
    if layer_norm:
        t = ff.layer_norm(t, name="final_ln")
    ff.dense(t, vocab_size, name="lm_head")
    return ff


def build_transformer(config: Optional[FFConfig] = None,
                      batch_size: int = None, seq_len: int = 128,
                      hidden: int = 512, num_heads: int = 8,
                      num_layers: int = 6, ff_dim: int = 2048,
                      num_classes: int = 10, dtype=jnp.float32,
                      mesh=None, strategy=None,
                      use_flash=None, layer_norm: bool = False) -> FFModel:
    """layer_norm=True builds pre-LN blocks (modern practice; the
    reference Transformer example has no normalization at all,
    transformer.cc:28-56, so the default keeps its exact topology)."""
    cfg = config or FFConfig()
    bs = batch_size or cfg.batch_size
    ff = FFModel(cfg, mesh=mesh, strategy=strategy)
    t = ff.create_tensor((bs, seq_len, hidden), dtype=dtype, name="input")
    for i in range(num_layers):
        a_in = ff.layer_norm(t, name=f"layer{i}_ln1") if layer_norm else t
        a = ff.multihead_attention(a_in, a_in, a_in, hidden, num_heads,
                                   use_flash=use_flash,
                                   name=f"layer{i}_attn")
        t = ff.add(a, t, name=f"layer{i}_res1")
        f_in = ff.layer_norm(t, name=f"layer{i}_ln2") if layer_norm else t
        h = ff.dense(f_in, ff_dim, activation="relu",
                     name=f"layer{i}_ff1")
        h = ff.dense(h, hidden, name=f"layer{i}_ff2")
        t = ff.add(h, t, name=f"layer{i}_res2")
    # classification head over the first position (avoids a giant
    # flat->dense): slice via split, then dense+softmax.
    head, _rest = ff.split(t, [1, t.shape[1] - 1], axis=1, name="cls_split")
    head = ff.reshape(head, (bs, hidden), name="cls_reshape")
    logits = ff.dense(head, num_classes, name="cls_head")
    out = ff.softmax(logits, name="cls_softmax")
    return ff
