"""Transformer encoder — the flagship model.

Reference: examples/cpp/Transformer/transformer.cc:28-56,110-135 — an
encoder of MultiHeadAttention + dense blocks (512 hidden / 8 layers,
synthetic data). We keep the same op mix (MHA + dense + elementwise add);
the attention core runs through the Pallas flash kernel on TPU.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ..config import FFConfig
from ..model import FFModel


def build_transformer(config: Optional[FFConfig] = None,
                      batch_size: int = None, seq_len: int = 128,
                      hidden: int = 512, num_heads: int = 8,
                      num_layers: int = 6, ff_dim: int = 2048,
                      num_classes: int = 10, dtype=jnp.float32,
                      mesh=None, strategy=None,
                      use_flash=None) -> FFModel:
    cfg = config or FFConfig()
    bs = batch_size or cfg.batch_size
    ff = FFModel(cfg, mesh=mesh, strategy=strategy)
    t = ff.create_tensor((bs, seq_len, hidden), dtype=dtype, name="input")
    for i in range(num_layers):
        a = ff.multihead_attention(t, t, t, hidden, num_heads,
                                   use_flash=use_flash,
                                   name=f"layer{i}_attn")
        t = ff.add(a, t, name=f"layer{i}_res1")
        h = ff.dense(t, ff_dim, activation="relu", name=f"layer{i}_ff1")
        h = ff.dense(h, hidden, name=f"layer{i}_ff2")
        t = ff.add(h, t, name=f"layer{i}_res2")
    # classification head over the first position (avoids a giant
    # flat->dense): slice via split, then dense+softmax.
    head, _rest = ff.split(t, [1, t.shape[1] - 1], axis=1, name="cls_split")
    head = ff.reshape(head, (bs, hidden), name="cls_reshape")
    logits = ff.dense(head, num_classes, name="cls_head")
    out = ff.softmax(logits, name="cls_softmax")
    return ff
