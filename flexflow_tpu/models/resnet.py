"""ResNet.

Reference: examples/cpp/ResNet (residual adds + BN, 560 LoC). Bottleneck
architecture; depth 18/34 use basic blocks, 50/101/152 bottlenecks —
ResNet-101 is one of the MLSys'19 benchmark models.
"""

from __future__ import annotations

from typing import Optional

from ..config import FFConfig
from ..model import FFModel

_DEPTHS = {
    18: ("basic", [2, 2, 2, 2]),
    34: ("basic", [3, 4, 6, 3]),
    50: ("bottleneck", [3, 4, 6, 3]),
    101: ("bottleneck", [3, 4, 23, 3]),
    152: ("bottleneck", [3, 8, 36, 3]),
}


def _basic_block(ff, t, channels, stride, name):
    shortcut = t
    u = ff.conv2d(t, channels, 3, 3, stride, stride, 1, 1,
                  name=f"{name}_conv1")
    u = ff.batch_norm(u, relu=True, name=f"{name}_bn1")
    u = ff.conv2d(u, channels, 3, 3, 1, 1, 1, 1, name=f"{name}_conv2")
    u = ff.batch_norm(u, relu=False, name=f"{name}_bn2")
    if stride != 1 or shortcut.shape[1] != channels:
        shortcut = ff.conv2d(shortcut, channels, 1, 1, stride, stride, 0, 0,
                             name=f"{name}_proj")
        shortcut = ff.batch_norm(shortcut, relu=False, name=f"{name}_projbn")
    u = ff.add(u, shortcut, name=f"{name}_res")
    return ff.relu(u, name=f"{name}_out")


def _bottleneck_block(ff, t, channels, stride, name):
    shortcut = t
    u = ff.conv2d(t, channels, 1, 1, 1, 1, 0, 0, name=f"{name}_conv1")
    u = ff.batch_norm(u, relu=True, name=f"{name}_bn1")
    u = ff.conv2d(u, channels, 3, 3, stride, stride, 1, 1,
                  name=f"{name}_conv2")
    u = ff.batch_norm(u, relu=True, name=f"{name}_bn2")
    u = ff.conv2d(u, 4 * channels, 1, 1, 1, 1, 0, 0, name=f"{name}_conv3")
    u = ff.batch_norm(u, relu=False, name=f"{name}_bn3")
    if stride != 1 or shortcut.shape[1] != 4 * channels:
        shortcut = ff.conv2d(shortcut, 4 * channels, 1, 1, stride, stride,
                             0, 0, name=f"{name}_proj")
        shortcut = ff.batch_norm(shortcut, relu=False, name=f"{name}_projbn")
    u = ff.add(u, shortcut, name=f"{name}_res")
    return ff.relu(u, name=f"{name}_out")


def build_resnet(config: Optional[FFConfig] = None, depth: int = 18,
                 batch_size: int = None, num_classes: int = 10,
                 image_size: int = 32, mesh=None, strategy=None) -> FFModel:
    cfg = config or FFConfig()
    bs = batch_size or cfg.batch_size
    kind, layers = _DEPTHS[depth]
    block = _basic_block if kind == "basic" else _bottleneck_block

    ff = FFModel(cfg, mesh=mesh, strategy=strategy)
    x = ff.create_tensor((bs, 3, image_size, image_size), name="input")
    if image_size >= 64:
        t = ff.conv2d(x, 64, 7, 7, 2, 2, 3, 3, name="stem")
        t = ff.batch_norm(t, relu=True, name="stem_bn")
        t = ff.pool2d(t, 3, 3, 2, 2, 1, 1, name="stem_pool")
    else:
        t = ff.conv2d(x, 64, 3, 3, 1, 1, 1, 1, name="stem")
        t = ff.batch_norm(t, relu=True, name="stem_bn")
    channels = 64
    for stage, n_blocks in enumerate(layers):
        for b in range(n_blocks):
            stride = 2 if (b == 0 and stage > 0) else 1
            t = block(ff, t, channels, stride, f"s{stage}b{b}")
        channels *= 2
    # global average pool
    h, w = t.shape[2], t.shape[3]
    t = ff.pool2d(t, h, w, 1, 1, 0, 0, pool_type="avg", name="gap")
    t = ff.flat(t, name="flat")
    t = ff.dense(t, num_classes, name="fc")
    t = ff.softmax(t, name="softmax")
    return ff
