"""NMT LSTM.

Reference: nmt/ — a *separate* 3.6k-LoC Legion RNN framework (rnn.cu,
lstm.cu cuDNN recurrence, embed.cu, softmax_data_parallel.cu, its own
RnnMapper). Per SURVEY.md section 7 step 8 we do NOT reproduce that
framework; LSTM is an ordinary op of the main framework (lax.scan cell,
MXU-batched gate GEMMs) and the NMT model is an encoder-decoder-style
stacked-LSTM LM built with the normal builder API.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ..config import FFConfig
from ..model import FFModel


def build_nmt_lstm(config: Optional[FFConfig] = None,
                   batch_size: int = None, seq_len: int = 40,
                   vocab_size: int = 32000, embed_dim: int = 1024,
                   hidden: int = 1024, num_layers: int = 2,
                   mesh=None, strategy=None, dtype=None) -> FFModel:
    """Stacked-LSTM sequence model: embed -> L x LSTM -> dense(vocab)
    -> softmax over the final position (nmt/rnn.h:91-160 topology,
    embed_size/hidden 1024 like nmt.cc).

    dtype=jnp.bfloat16 runs activations (and thus the LSTM recurrence's
    per-step GEMMs) in bf16 on the MXU's native path; weights stay f32,
    gates accumulate f32."""
    cfg = config or FFConfig()
    bs = batch_size or cfg.batch_size
    ff = FFModel(cfg, mesh=mesh, strategy=strategy)
    tokens = ff.create_tensor((bs, seq_len), dtype=jnp.int32, name="input")

    # per-token embedding (aggr none keeps the seq dim)
    t = ff.embedding(tokens, vocab_size, embed_dim, aggr="none",
                     name="embed", dtype=dtype)
    for i in range(num_layers):
        t = ff.lstm(t, hidden, return_sequences=True, name=f"lstm_{i}")
    # predict the next token from the last position
    last = ff.split(t, [seq_len - 1, 1], axis=1, name="last_split")[1]
    last = ff.reshape(last, (bs, hidden), name="last_reshape")
    logits = ff.dense(last, vocab_size, name="proj")
    out = ff.softmax(logits, name="softmax")
    return ff


def build_nmt_seq2seq(config: Optional[FFConfig] = None,
                      batch_size: int = None, src_len: int = 20,
                      tgt_len: int = 20, vocab_size: int = 16000,
                      embed_dim: int = 512, hidden: int = 512,
                      num_layers: int = 2, attn_heads: int = 1,
                      mesh=None, strategy=None, dtype=None) -> FFModel:
    """Encoder-decoder NMT with attention, teacher-forced: the full
    shape of the reference's nmt/ framework (nmt/rnn.h:91-160 —
    encoder/decoder LSTM stacks over SharedVariable weights; its
    per-timestep softmax_data_parallel.cu becomes one per-position
    softmax + sequence sparse-CCE here). Inputs "src" (bs, src_len) and
    "tgt" (bs, tgt_len) int tokens; output (bs, tgt_len, vocab)
    probabilities — train with label = next-token ids (bs, tgt_len).

    Decoder->encoder attention runs through the framework's
    multihead_attention (flash/Pallas path), generalizing the
    reference's fixed alignment-free decoder."""
    cfg = config or FFConfig()
    bs = batch_size or cfg.batch_size
    ff = FFModel(cfg, mesh=mesh, strategy=strategy)
    src = ff.create_tensor((bs, src_len), dtype=jnp.int32, name="src")
    tgt = ff.create_tensor((bs, tgt_len), dtype=jnp.int32, name="tgt")

    enc = ff.embedding(src, vocab_size, embed_dim, aggr="none",
                       name="src_embed", dtype=dtype)
    for i in range(num_layers):
        enc = ff.lstm(enc, hidden, return_sequences=True,
                      name=f"enc_lstm_{i}")

    dec = ff.embedding(tgt, vocab_size, embed_dim, aggr="none",
                       name="tgt_embed", dtype=dtype)
    for i in range(num_layers):
        dec = ff.lstm(dec, hidden, return_sequences=True,
                      name=f"dec_lstm_{i}")

    # Luong-style attention over encoder states + combine
    ctx = ff.multihead_attention(dec, enc, enc, embed_dim=hidden,
                                 num_heads=attn_heads, name="cross_attn")
    t = ff.concat([dec, ctx], axis=2, name="attn_concat")
    t = ff.dense(t, hidden, activation="tanh", name="attn_combine")
    logits = ff.dense(t, vocab_size, name="proj")
    ff.softmax(logits, axis=-1, name="softmax")
    return ff
