"""Mixture-of-experts example model.

Reference: examples/cpp/mixture_of_experts/moe.cc — gating softmax +
TopK + GroupBy + per-expert dense nets + Aggregate on MNIST-sized
inputs. Built here in BOTH styles:

  * build_moe_reference: the reference's composable op pipeline
    (softmax/top_k/group_by/aggregate) — capability parity.
  * build_moe_fused: the TPU-first fused MoEFFN with expert parallelism.
"""

from __future__ import annotations

from typing import Optional

from ..config import FFConfig
from ..model import FFModel


def build_moe_reference(config: Optional[FFConfig] = None,
                        batch_size: int = None, input_dim: int = 784,
                        num_classes: int = 10, num_experts: int = 4,
                        k: int = 2, alpha: float = 2.0,
                        expert_hidden: int = 64,
                        mesh=None, strategy=None) -> FFModel:
    cfg = config or FFConfig()
    bs = batch_size or cfg.batch_size
    ff = FFModel(cfg, mesh=mesh, strategy=strategy)
    x = ff.create_tensor((bs, input_dim), name="input")

    # gating network (moe.cc: dense -> softmax -> top_k)
    gate = ff.dense(x, num_experts, name="gate_dense")
    gate = ff.softmax(gate, name="gate_softmax")
    gate_vals, gate_assign = ff.top_k(gate, k, name="gate_topk")

    # dispatch
    expert_inputs = ff.group_by(x, gate_assign, num_experts, alpha,
                                name="group_by")

    # per-expert classifier nets (moe.cc expert blocks)
    expert_preds = []
    for i, einp in enumerate(expert_inputs):
        h = ff.dense(einp, expert_hidden, activation="relu",
                     name=f"expert{i}_fc1")
        p = ff.dense(h, num_classes, name=f"expert{i}_fc2")
        expert_preds.append(p)

    out = ff.aggregate(gate_vals, gate_assign, expert_preds, num_experts,
                       name="aggregate")
    out = ff.softmax(out, name="softmax")
    return ff


def build_moe_fused(config: Optional[FFConfig] = None,
                    batch_size: int = None, input_dim: int = 784,
                    num_classes: int = 10, num_experts: int = 8,
                    k: int = 2, expert_hidden: int = 128,
                    mesh=None, strategy=None) -> FFModel:
    cfg = config or FFConfig()
    bs = batch_size or cfg.batch_size
    ff = FFModel(cfg, mesh=mesh, strategy=strategy)
    x = ff.create_tensor((bs, input_dim), name="input")
    t = ff.dense(x, 256, activation="relu", name="stem")
    t = ff.moe_ffn(t, num_experts=num_experts, k=k,
                   hidden_dim=expert_hidden, capacity_factor=2.0,
                   name="moe")
    t = ff.dense(t, num_classes, name="head")
    t = ff.softmax(t, name="softmax")
    return ff
