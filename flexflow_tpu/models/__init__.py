"""Model zoo — the reference's examples/cpp + examples/python workloads
(SURVEY.md 2.7), built on the framework's builder API."""

from .alexnet import build_alexnet
from .transformer import build_transformer

__all__ = ["build_alexnet", "build_transformer"]
