"""Model zoo — the reference's examples/cpp + examples/python workloads
(SURVEY.md 2.7), built on the framework's builder API."""

from .alexnet import build_alexnet
from .transformer import build_transformer, build_transformer_lm
from .resnet import build_resnet
from .inception import build_inception_v3
from .dlrm import build_dlrm
from .moe import build_moe_fused, build_moe_reference
from .candle_uno import build_candle_uno
from .nmt_lstm import build_nmt_lstm, build_nmt_seq2seq

__all__ = [
    "build_alexnet",
    "build_transformer",
    "build_transformer_lm",
    "build_resnet",
    "build_inception_v3",
    "build_dlrm",
    "build_moe_reference",
    "build_moe_fused",
    "build_candle_uno",
    "build_nmt_lstm",
    "build_nmt_seq2seq",
]
