"""CANDLE-Uno (cancer drug response MLP).

Reference: examples/cpp/candle_uno/candle_uno.cc — multiple input feature
towers (gene expression, drug descriptors, ...), each through its own
dense tower, concatenated into a deep residual-free MLP regression head.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

from ..config import FFConfig
from ..model import FFModel

DEFAULT_FEATURE_SHAPES = {
    "dose1": 1,
    "cell_rnaseq": 942,
    "drug1_descriptors": 5270,
    "drug1_fingerprints": 2048,
}


def build_candle_uno(config: Optional[FFConfig] = None,
                     batch_size: int = None,
                     feature_shapes: Optional[Dict[str, int]] = None,
                     tower_layers: Sequence[int] = (1000, 1000, 1000),
                     final_layers: Sequence[int] = (1000, 1000, 1000, 1000),
                     mesh=None, strategy=None) -> FFModel:
    cfg = config or FFConfig()
    bs = batch_size or cfg.batch_size
    feats = feature_shapes or DEFAULT_FEATURE_SHAPES
    ff = FFModel(cfg, mesh=mesh, strategy=strategy)

    towers = []
    for name, dim in feats.items():
        t = ff.create_tensor((bs, dim), name=name)
        if dim > 1:  # candle_uno: feature towers only for wide inputs
            for i, width in enumerate(tower_layers):
                t = ff.dense(t, width, activation="relu",
                             name=f"{name}_tower_{i}")
        towers.append(t)

    t = ff.concat(towers, axis=1, name="concat_features")
    for i, width in enumerate(final_layers):
        t = ff.dense(t, width, activation="relu", name=f"final_{i}")
    t = ff.dense(t, 1, name="growth_out")  # regression (MSE loss)
    return ff
