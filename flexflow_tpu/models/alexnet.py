"""AlexNet.

Reference: examples/cpp/AlexNet/alexnet.cc:34-137 (top_level_task graph) and
bootcamp_demo/ff_alexnet_cifar10.py — conv/pool/flat/dense/softmax stack.
CIFAR-10 variant uses 32x32 inputs; ImageNet variant 224x224.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ..config import FFConfig
from ..model import FFModel


def build_alexnet(config: Optional[FFConfig] = None, batch_size: int = None,
                  num_classes: int = 10, image_size: int = 32,
                  mesh=None, strategy=None, dtype=None) -> FFModel:
    """dtype=jnp.bfloat16 runs activations in bf16 (weights stay f32,
    cast per-op) — the idiomatic TPU mixed-precision training mode that
    keeps the convs on the MXU's native bf16 path."""
    cfg = config or FFConfig()
    bs = batch_size or cfg.batch_size
    ff = FFModel(cfg, mesh=mesh, strategy=strategy)
    x = ff.create_tensor((bs, 3, image_size, image_size),
                         dtype=dtype or jnp.float32, name="input")

    if image_size >= 64:
        # ImageNet-scale geometry (alexnet.cc:60-80)
        t = ff.conv2d(x, 64, 11, 11, 4, 4, 2, 2, activation="relu")
        t = ff.pool2d(t, 3, 3, 2, 2, 0, 0)
        t = ff.conv2d(t, 192, 5, 5, 1, 1, 2, 2, activation="relu")
        t = ff.pool2d(t, 3, 3, 2, 2, 0, 0)
    else:
        # CIFAR-10 geometry (bootcamp_demo/ff_alexnet_cifar10.py)
        t = ff.conv2d(x, 64, 5, 5, 1, 1, 2, 2, activation="relu")
        t = ff.pool2d(t, 3, 3, 2, 2, 0, 0)
        t = ff.conv2d(t, 192, 5, 5, 1, 1, 2, 2, activation="relu")
        t = ff.pool2d(t, 3, 3, 2, 2, 0, 0)
    t = ff.conv2d(t, 384, 3, 3, 1, 1, 1, 1, activation="relu")
    t = ff.conv2d(t, 256, 3, 3, 1, 1, 1, 1, activation="relu")
    t = ff.conv2d(t, 256, 3, 3, 1, 1, 1, 1, activation="relu")
    t = ff.pool2d(t, 3, 3, 2, 2, 0, 0)
    t = ff.flat(t)
    t = ff.dense(t, 4096, activation="relu")
    t = ff.dense(t, 4096, activation="relu")
    t = ff.dense(t, num_classes)
    t = ff.softmax(t)
    return ff
