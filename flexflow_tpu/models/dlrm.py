"""DLRM (deep learning recommendation model).

Reference: examples/cpp/DLRM/dlrm.cc:26-124 — bottom MLP over dense
features, one embedding bag per sparse feature, pairwise dot-product
feature interaction, top MLP, sigmoid CTR head. The reference's headline
trick is *parameter-parallel* embedding placement (per-GPU tables via
strategy files, dlrm_strategy.cc); the TPU equivalent shards each table's
vocab over the mesh `model` axis (strategy {vocab: model}).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax.numpy as jnp

from ..config import FFConfig
from ..model import FFModel


def build_dlrm(config: Optional[FFConfig] = None, batch_size: int = None,
               dense_dim: int = 13,
               embedding_vocab_sizes: Sequence[int] = (1000,) * 8,
               embedding_bag_size: int = 1, embedding_dim: int = 64,
               bot_mlp: Sequence[int] = (512, 256, 64),
               top_mlp: Sequence[int] = (512, 256, 1),
               mesh=None, strategy=None,
               stacked_tables: bool = False, dtype=None) -> FFModel:
    """stacked_tables=True uses one DistributedEmbedding over all sparse
    features (requires equal vocab sizes): the executable analog of the
    reference's per-GPU table placement — map its `table` axis to a mesh
    axis and each device hosts vocab-complete tables
    (dlrm_strategy.cc:1-50)."""
    cfg = config or FFConfig()
    bs = batch_size or cfg.batch_size
    ff = FFModel(cfg, mesh=mesh, strategy=strategy)

    dense_in = ff.create_tensor((bs, dense_dim), name="dense_features",
                                dtype=dtype or jnp.float32)
    sparse_ins = [
        ff.create_tensor((bs, embedding_bag_size), dtype=jnp.int32,
                         name=f"sparse_{i}")
        for i in range(len(embedding_vocab_sizes))
    ]

    # bottom MLP (dlrm.cc create_mlp)
    t = dense_in
    for i, width in enumerate(bot_mlp):
        t = ff.dense(t, width, activation="relu", name=f"bot_mlp_{i}")
    dense_emb = t  # (bs, embedding_dim)
    assert dense_emb.shape[-1] == embedding_dim, (
        "last bot_mlp width must equal embedding_dim")

    # embedding bags (dlrm.cc create_emb; vocab-shardable for ICI
    # parameter parallelism, or table-sharded when stacked)
    if stacked_tables:
        vocabs = set(embedding_vocab_sizes)
        assert len(vocabs) == 1, (
            "stacked_tables requires equal vocab sizes, got "
            f"{sorted(vocabs)}")
        embs = ff.distributed_embedding(
            sparse_ins, embedding_vocab_sizes[0], embedding_dim,
            aggr="sum", name="emb_tables", dtype=dtype)
    else:
        embs = [
            ff.embedding(s, vocab, embedding_dim, aggr="sum",
                         name=f"emb_{i}", dtype=dtype)
            for i, (s, vocab) in enumerate(zip(sparse_ins,
                                               embedding_vocab_sizes))
        ]

    # pairwise dot-product interaction (dlrm.cc interact_features):
    # stack features (bs, F, D), compute (bs, F, F) gram via batch_matmul
    feats = [dense_emb] + embs
    F = len(feats)
    stacked = ff.concat(feats, axis=1, name="interact_cat")  # (bs, F*D)
    stacked = ff.reshape(stacked, (bs, F, embedding_dim),
                         name="interact_reshape")
    trans = ff.transpose(stacked, [0, 2, 1], name="interact_T")
    gram = ff.batch_matmul(stacked, trans, name="interact_bmm")  # (bs,F,F)
    gram_flat = ff.reshape(gram, (bs, F * F), name="interact_flat")
    top_in = ff.concat([dense_emb, gram_flat], axis=1, name="top_cat")

    # top MLP + sigmoid CTR
    t = top_in
    for i, width in enumerate(top_mlp[:-1]):
        t = ff.dense(t, width, activation="relu", name=f"top_mlp_{i}")
    t = ff.dense(t, top_mlp[-1], name="top_out")
    t = ff.sigmoid(t, name="ctr")
    return ff
