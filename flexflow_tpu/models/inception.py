"""Inception-v3.

Reference: examples/cpp/InceptionV3/inception.cc — the module builders
(InceptionA/B/C/D/E) exercising Conv2D/Pool2D/Concat with parallel
branches. Geometry follows the standard Inception-v3 (299x299) with a
reduced-resolution variant for small inputs.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ..config import FFConfig
from ..model import FFModel


def _conv_bn(ff, t, ch, kh, kw, sh, sw, ph, pw, name):
    t = ff.conv2d(t, ch, kh, kw, sh, sw, ph, pw, name=f"{name}_conv")
    return ff.batch_norm(t, relu=True, name=f"{name}_bn")


def _inception_a(ff, t, pool_ch, name):
    b1 = _conv_bn(ff, t, 64, 1, 1, 1, 1, 0, 0, f"{name}_b1")
    b2 = _conv_bn(ff, t, 48, 1, 1, 1, 1, 0, 0, f"{name}_b2a")
    b2 = _conv_bn(ff, b2, 64, 5, 5, 1, 1, 2, 2, f"{name}_b2b")
    b3 = _conv_bn(ff, t, 64, 1, 1, 1, 1, 0, 0, f"{name}_b3a")
    b3 = _conv_bn(ff, b3, 96, 3, 3, 1, 1, 1, 1, f"{name}_b3b")
    b3 = _conv_bn(ff, b3, 96, 3, 3, 1, 1, 1, 1, f"{name}_b3c")
    b4 = ff.pool2d(t, 3, 3, 1, 1, 1, 1, pool_type="avg",
                   name=f"{name}_pool")
    b4 = _conv_bn(ff, b4, pool_ch, 1, 1, 1, 1, 0, 0, f"{name}_b4")
    return ff.concat([b1, b2, b3, b4], axis=1, name=f"{name}_cat")


def _inception_b(ff, t, name):
    b1 = _conv_bn(ff, t, 384, 3, 3, 2, 2, 0, 0, f"{name}_b1")
    b2 = _conv_bn(ff, t, 64, 1, 1, 1, 1, 0, 0, f"{name}_b2a")
    b2 = _conv_bn(ff, b2, 96, 3, 3, 1, 1, 1, 1, f"{name}_b2b")
    b2 = _conv_bn(ff, b2, 96, 3, 3, 2, 2, 0, 0, f"{name}_b2c")
    b3 = ff.pool2d(t, 3, 3, 2, 2, 0, 0, name=f"{name}_pool")
    return ff.concat([b1, b2, b3], axis=1, name=f"{name}_cat")


def _inception_c(ff, t, ch7, name):
    b1 = _conv_bn(ff, t, 192, 1, 1, 1, 1, 0, 0, f"{name}_b1")
    b2 = _conv_bn(ff, t, ch7, 1, 1, 1, 1, 0, 0, f"{name}_b2a")
    b2 = _conv_bn(ff, b2, ch7, 1, 7, 1, 1, 0, 3, f"{name}_b2b")
    b2 = _conv_bn(ff, b2, 192, 7, 1, 1, 1, 3, 0, f"{name}_b2c")
    b3 = _conv_bn(ff, t, ch7, 1, 1, 1, 1, 0, 0, f"{name}_b3a")
    b3 = _conv_bn(ff, b3, ch7, 7, 1, 1, 1, 3, 0, f"{name}_b3b")
    b3 = _conv_bn(ff, b3, ch7, 1, 7, 1, 1, 0, 3, f"{name}_b3c")
    b3 = _conv_bn(ff, b3, ch7, 7, 1, 1, 1, 3, 0, f"{name}_b3d")
    b3 = _conv_bn(ff, b3, 192, 1, 7, 1, 1, 0, 3, f"{name}_b3e")
    b4 = ff.pool2d(t, 3, 3, 1, 1, 1, 1, pool_type="avg",
                   name=f"{name}_pool")
    b4 = _conv_bn(ff, b4, 192, 1, 1, 1, 1, 0, 0, f"{name}_b4")
    return ff.concat([b1, b2, b3, b4], axis=1, name=f"{name}_cat")


def _inception_d(ff, t, name):
    b1 = _conv_bn(ff, t, 192, 1, 1, 1, 1, 0, 0, f"{name}_b1a")
    b1 = _conv_bn(ff, b1, 320, 3, 3, 2, 2, 0, 0, f"{name}_b1b")
    b2 = _conv_bn(ff, t, 192, 1, 1, 1, 1, 0, 0, f"{name}_b2a")
    b2 = _conv_bn(ff, b2, 192, 1, 7, 1, 1, 0, 3, f"{name}_b2b")
    b2 = _conv_bn(ff, b2, 192, 7, 1, 1, 1, 3, 0, f"{name}_b2c")
    b2 = _conv_bn(ff, b2, 192, 3, 3, 2, 2, 0, 0, f"{name}_b2d")
    b3 = ff.pool2d(t, 3, 3, 2, 2, 0, 0, name=f"{name}_pool")
    return ff.concat([b1, b2, b3], axis=1, name=f"{name}_cat")


def _inception_e(ff, t, name):
    b1 = _conv_bn(ff, t, 320, 1, 1, 1, 1, 0, 0, f"{name}_b1")
    b2 = _conv_bn(ff, t, 384, 1, 1, 1, 1, 0, 0, f"{name}_b2a")
    b2a = _conv_bn(ff, b2, 384, 1, 3, 1, 1, 0, 1, f"{name}_b2b1")
    b2b = _conv_bn(ff, b2, 384, 3, 1, 1, 1, 1, 0, f"{name}_b2b2")
    b2 = ff.concat([b2a, b2b], axis=1, name=f"{name}_b2cat")
    b3 = _conv_bn(ff, t, 448, 1, 1, 1, 1, 0, 0, f"{name}_b3a")
    b3 = _conv_bn(ff, b3, 384, 3, 3, 1, 1, 1, 1, f"{name}_b3b")
    b3a = _conv_bn(ff, b3, 384, 1, 3, 1, 1, 0, 1, f"{name}_b3c1")
    b3b = _conv_bn(ff, b3, 384, 3, 1, 1, 1, 1, 0, f"{name}_b3c2")
    b3 = ff.concat([b3a, b3b], axis=1, name=f"{name}_b3cat")
    b4 = ff.pool2d(t, 3, 3, 1, 1, 1, 1, pool_type="avg",
                   name=f"{name}_pool")
    b4 = _conv_bn(ff, b4, 192, 1, 1, 1, 1, 0, 0, f"{name}_b4")
    return ff.concat([b1, b2, b3, b4], axis=1, name=f"{name}_cat")


def build_inception_v3(config: Optional[FFConfig] = None,
                       batch_size: int = None, num_classes: int = 10,
                       image_size: int = 299, mesh=None,
                       strategy=None, dtype=None) -> FFModel:
    """dtype=jnp.bfloat16 runs activations in bf16 (weights stay f32,
    cast per-op) — mixed precision on the MXU's native path."""
    cfg = config or FFConfig()
    bs = batch_size or cfg.batch_size
    ff = FFModel(cfg, mesh=mesh, strategy=strategy)
    x = ff.create_tensor((bs, 3, image_size, image_size),
                         dtype=dtype or jnp.float32, name="input")

    if image_size >= 128:
        t = _conv_bn(ff, x, 32, 3, 3, 2, 2, 0, 0, "stem1")
        t = _conv_bn(ff, t, 32, 3, 3, 1, 1, 0, 0, "stem2")
        t = _conv_bn(ff, t, 64, 3, 3, 1, 1, 1, 1, "stem3")
        t = ff.pool2d(t, 3, 3, 2, 2, 0, 0, name="stem_pool1")
        t = _conv_bn(ff, t, 80, 1, 1, 1, 1, 0, 0, "stem4")
        t = _conv_bn(ff, t, 192, 3, 3, 1, 1, 0, 0, "stem5")
        t = ff.pool2d(t, 3, 3, 2, 2, 0, 0, name="stem_pool2")
    else:
        # reduced stem for small images (keeps the module structure)
        t = _conv_bn(ff, x, 64, 3, 3, 1, 1, 1, 1, "stem1")
        t = _conv_bn(ff, t, 192, 3, 3, 1, 1, 1, 1, "stem2")

    t = _inception_a(ff, t, 32, "mixed0")
    t = _inception_a(ff, t, 64, "mixed1")
    t = _inception_a(ff, t, 64, "mixed2")
    t = _inception_b(ff, t, "mixed3")
    t = _inception_c(ff, t, 128, "mixed4")
    t = _inception_c(ff, t, 160, "mixed5")
    t = _inception_c(ff, t, 160, "mixed6")
    t = _inception_c(ff, t, 192, "mixed7")
    t = _inception_d(ff, t, "mixed8")
    t = _inception_e(ff, t, "mixed9")
    t = _inception_e(ff, t, "mixed10")

    h, w = t.shape[2], t.shape[3]
    t = ff.pool2d(t, h, w, 1, 1, 0, 0, pool_type="avg", name="gap")
    t = ff.flat(t, name="flat")
    t = ff.dense(t, num_classes, name="fc")
    t = ff.softmax(t, name="softmax")
    return ff
