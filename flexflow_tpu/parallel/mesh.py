"""Device mesh construction.

The reference discovers GPUs/CPUs and their memories inside the mapper
(mapper.cc:55-145) and encodes machines analytically in `MachineModel`
(machine_model.cc). On TPU the machine is a `jax.sharding.Mesh`: an N-D
array of devices with named axes. Canonical axis names:

  data      — batch (DP; reference "sample parallel")
  model     — tensor parallel (reference "parameter/attribute parallel")
  seq       — sequence/context parallel (new, no reference analog)
  expert    — expert parallel for MoE (new)
  pipe      — pipeline stages (new)

Meshes should be laid out so the fastest-varying axes ride ICI; multi-host
meshes put `data` on DCN (jax device order already enumerates
process-local devices contiguously, which achieves this).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

DATA = "data"
MODEL = "model"
SEQ_AX = "seq"
EXPERT_AX = "expert"
PIPE = "pipe"
# serving-side tensor parallelism (docs/serving.md "Sharded serving"):
# the ONE mixed prefill+decode program shards over a 1-D mesh on this
# axis — head-parallel attention, head-sharded KV pages, vocab-sharded
# embedding/head. Named distinctly from the training axes because a
# serve mesh is built per engine, not per FFModel.
TENSOR = "tensor"

ALL_AXES = (DATA, MODEL, SEQ_AX, EXPERT_AX, PIPE)


@dataclasses.dataclass
class MachineSpec:
    """Analytic description of the target machine for the cost model
    (replaces reference EnhancedMachineModel, simulator.h:99-236).

    Defaults approximate a TPU v5p pod slice.
    """

    num_chips: int = 1
    # per-chip
    peak_flops: float = 459e12  # bf16 FLOP/s per v5p chip
    hbm_bandwidth: float = 2.765e12  # bytes/s
    hbm_capacity: float = 95e9  # bytes
    vmem_capacity: float = 128e6
    # interconnect
    ici_bandwidth: float = 9e10 * 2  # bytes/s per link, 3D torus, bidir
    ici_latency: float = 1e-6
    dcn_bandwidth: float = 25e9
    dcn_latency: float = 10e-6
    # chips sharing one host NIC: DCN collectives funnel every local
    # chip's traffic through it, so effective per-chip DCN bandwidth is
    # dcn_bandwidth/chips_per_host (the reference's EnhancedMachineModel
    # models the same shared-NIC congestion, machine_model.cc:172+)
    chips_per_host: int = 4
    # host link (PCIe-class DMA between a chip's HBM and its host's
    # DRAM): the path a DISAGGREGATED serving deployment ships finished
    # KV pages over (prefill engine -> host -> decode engine,
    # serve/disagg.py). Priced by TPUMachineModel.host_transfer so the
    # placement search can weigh the page-handoff link against the
    # compute it frees (search/serve_place.optimize_serve_disagg).
    host_link_bandwidth: float = 5e10  # bytes/s per chip<->host DMA
    host_link_latency: float = 5e-6
    # physical ICI torus factorization of the slice, e.g. (4, 4, 4) for
    # a 64-chip v5p cube or (16, 16) for a v5e pod; () = flat/unknown
    # (every mesh axis priced as a single ring). A mesh axis laid out
    # over k torus dims runs its collective phases over k link sets
    # concurrently (the analog of reference get_comm_path routing over
    # the physical hierarchy, machine_model.cc:695).
    ici_torus_dims: tuple = ()
    # wraparound links present (torus vs line): halves worst-case hop
    # distance and doubles bisection
    ici_wraparound: bool = True

    @staticmethod
    def v5e(num_chips: int = 1) -> "MachineSpec":
        return MachineSpec(
            num_chips=num_chips, peak_flops=197e12, hbm_bandwidth=8.1e11,
            hbm_capacity=16e9, ici_bandwidth=4.5e10, dcn_bandwidth=25e9)


def make_mesh(shape: Sequence[int], axes: Sequence[str],
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a Mesh from axis sizes/names over the available devices."""
    if devices is None:
        devices = jax.devices()
    n = int(np.prod(shape))
    assert n <= len(devices), (
        f"mesh needs {n} devices, have {len(devices)}")
    arr = np.array(devices[:n]).reshape(tuple(shape))
    return Mesh(arr, tuple(axes))


def default_mesh(num_devices: Optional[int] = None) -> Mesh:
    """Pure data-parallel mesh over all devices (the reference's default
    strategy is pure DP too — mapper.cc:118-145 seeds 1D-5D DP)."""
    devices = jax.devices()
    if num_devices is not None:
        devices = devices[:num_devices]
    return make_mesh((len(devices),), (DATA,), devices)


def single_device_mesh() -> Mesh:
    return make_mesh((1,), (DATA,), jax.devices()[:1])


def serve_tensor_mesh(tensor_parallel: int,
                      devices: Optional[Sequence] = None) -> Mesh:
    """The 1-D serving mesh ServeEngine shards the mixed program over:
    `tensor_parallel` devices on the TENSOR axis (head-parallel
    attention + head-sharded KV pages + vocab-sharded embedding/head,
    docs/serving.md)."""
    return make_mesh((int(tensor_parallel),), (TENSOR,), devices)
