"""Reference-format strategy file I/O.

The reference persists strategies as plain text (strategy.cc:95-189):

    <num_ops>
    <op_name> <device_type> <nDims> <dim_0> ... <dim_n-1> <id_0> ... <id_k-1>

keyed at runtime by hash(op name) -> MappingTagID. We keep the same
format for tooling familiarity: export derives per-dim split counts and
device ids from (strategy, mesh); import reconstructs an axis map by
matching split counts back onto the op's logical axes.

The native format remains JSON (Strategy.save/load) — it round-trips the
axis maps exactly; this module is the compatibility layer.
"""

from __future__ import annotations

import warnings
from typing import Dict, List

import numpy as np

from ..op import Op
from .pconfig import DEVICE_KEY, OpStrategy, ParallelConfig, Strategy


def op_parallel_config(op: Op, strategy: OpStrategy, mesh) -> ParallelConfig:
    """Derive the reference-style view: per-output-dim split counts +
    explicit device ids (row-major over the mesh submesh used).

    A device-explicit OpStrategy (the reference's own device_ids,
    config.h:47-73) exports unsplit dims with its literal device list —
    exactly how the DLRM strategy files pinned tables
    (dlrm_strategy.cc:1-50)."""
    out_axes = op.output_axes()[0] if op.outputs else ()
    out_shape = op.outputs[0].shape if op.outputs else ()
    if strategy.device_ids:
        # device_type "tpu_pin" marks an EXPLICIT placement: the format
        # cannot otherwise distinguish "pinned to device 0" from the
        # default single-part [0] device list
        if any(k != DEVICE_KEY for k in strategy.axis_map):
            warnings.warn(
                f"strategy for {op.name!r} combines mesh-axis splits "
                f"with explicit device ids; the text format carries the "
                f"placement only (mirror of the lossy import case)")
        return ParallelConfig(device_type="tpu_pin",
                              dims=[1] * max(1, len(out_axes)),
                              device_ids=list(strategy.device_ids))
    dims = []
    used_axes = []
    for i, ax in enumerate(out_axes):
        m = strategy.mesh_axis_for(ax)
        if isinstance(m, str) and m in mesh.shape \
                and out_shape[i] % mesh.shape[m] == 0 \
                and m not in used_axes:
            dims.append(mesh.shape[m])
            used_axes.append(m)
        else:
            dims.append(1)
    n_parts = int(np.prod(dims)) if dims else 1
    device_ids = list(range(n_parts))
    return ParallelConfig(device_type="tpu", dims=dims,
                          device_ids=device_ids)


def save_strategies_to_file(model, strategy: Strategy, mesh,
                            path: str) -> None:
    """Reference text format (strategy.cc:126-189)."""
    lines = [str(len(model.ops))]
    for op in model.ops:
        pc = op_parallel_config(op, strategy.for_op(op.name), mesh)
        parts = [op.name, pc.device_type, str(len(pc.dims))]
        parts += [str(d) for d in pc.dims]
        parts += [str(i) for i in pc.device_ids]
        lines.append(" ".join(parts))
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")


def load_strategies_from_file(model, mesh, path: str) -> Strategy:
    """Rebuild an axis map from the text format: a >1 split on dim i maps
    that dim's logical axis to the smallest matching mesh axis."""
    with open(path) as f:
        tokens = f.read().split("\n")
    n = int(tokens[0].strip())
    ops_by_name = {op.name: op for op in model.ops}
    strat = Strategy()
    for line in tokens[1:n + 1]:
        parts = line.split()
        name, dev_type = parts[0], parts[1]
        ndims = int(parts[2])
        dims = [int(x) for x in parts[3:3 + ndims]]
        device_ids = [int(x) for x in parts[3 + ndims:]]
        op = ops_by_name.get(name)
        if op is None:
            continue
        out_axes = op.output_axes()[0]
        axis_map: Dict[str, str] = {}
        used = set()
        for i, split in enumerate(dims):
            if split <= 1 or i >= len(out_axes) or out_axes[i] is None:
                continue
            for mesh_ax, size in mesh.shape.items():
                if size == split and mesh_ax not in used:
                    axis_map[out_axes[i]] = mesh_ax
                    used.add(mesh_ax)
                    break
        # explicit placement: the "tpu_pin" device-type marker, or an
        # unsplit op whose device list differs from the default range
        # (how the reference's DLRM strategy files pin tables)
        n_parts = int(np.prod(dims)) if dims else 1
        if device_ids and (dev_type == "tpu_pin"
                           or (not axis_map
                               and device_ids != list(range(n_parts)))):
            axis_map = {DEVICE_KEY: tuple(device_ids)}
        elif (axis_map and device_ids
                and device_ids != list(range(n_parts))):
            # split AND explicitly placed: the mesh-axis mapping cannot
            # carry the id list — be honest about the approximation
            warnings.warn(
                f"strategy file op {name!r}: explicit device ids "
                f"{device_ids} on a split op are not representable as a "
                f"mesh-axis mapping; loading the split only")
        strat.set(name, OpStrategy(axis_map))
    return strat
