"""Reference-format strategy file I/O.

The reference persists strategies as plain text (strategy.cc:95-189):

    <num_ops>
    <op_name> <device_type> <nDims> <dim_0> ... <dim_n-1> <id_0> ... <id_k-1>

keyed at runtime by hash(op name) -> MappingTagID. We keep the same
format for tooling familiarity: export derives per-dim split counts and
device ids from (strategy, mesh); import reconstructs an axis map by
matching split counts back onto the op's logical axes.

The native format remains JSON (Strategy.save/load) — it round-trips the
axis maps exactly; this module is the compatibility layer.
"""

from __future__ import annotations

import warnings
from typing import Dict, List

import numpy as np

from ..op import Op
from .pconfig import DEVICE_KEY, OpStrategy, ParallelConfig, Strategy


def op_parallel_config(op: Op, strategy: OpStrategy, mesh) -> ParallelConfig:
    """Derive the reference-style view: per-output-dim split counts +
    explicit device ids (row-major over the mesh submesh used).

    A device-explicit OpStrategy (the reference's own device_ids,
    config.h:47-73) exports unsplit dims with its literal device list —
    exactly how the DLRM strategy files pinned tables
    (dlrm_strategy.cc:1-50)."""
    out_axes = op.output_axes()[0] if op.outputs else ()
    out_shape = op.outputs[0].shape if op.outputs else ()
    if strategy.device_ids:
        # device_type "tpu_pin" marks an EXPLICIT placement: the format
        # cannot otherwise distinguish "pinned to device 0" from the
        # default single-part [0] device list
        if any(k != DEVICE_KEY for k in strategy.axis_map):
            warnings.warn(
                f"strategy for {op.name!r} combines mesh-axis splits "
                f"with explicit device ids; the text format carries the "
                f"placement only (mirror of the lossy import case)")
        return ParallelConfig(device_type="tpu_pin",
                              dims=[1] * max(1, len(out_axes)),
                              device_ids=list(strategy.device_ids))
    dims = []
    used_axes = []
    for i, ax in enumerate(out_axes):
        m = strategy.mesh_axis_for(ax)
        if isinstance(m, str) and m in mesh.shape \
                and out_shape[i] % mesh.shape[m] == 0 \
                and m not in used_axes:
            dims.append(mesh.shape[m])
            used_axes.append(m)
        else:
            dims.append(1)
    n_parts = int(np.prod(dims)) if dims else 1
    device_ids = list(range(n_parts))
    return ParallelConfig(device_type="tpu", dims=dims,
                          device_ids=device_ids)


def save_strategies_to_file(model, strategy: Strategy, mesh,
                            path: str) -> None:
    """Reference text format (strategy.cc:126-189)."""
    lines = [str(len(model.ops))]
    for op in model.ops:
        pc = op_parallel_config(op, strategy.for_op(op.name), mesh)
        parts = [op.name, pc.device_type, str(len(pc.dims))]
        parts += [str(d) for d in pc.dims]
        parts += [str(i) for i in pc.device_ids]
        lines.append(" ".join(parts))
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")


# ---------------------------------------------------------------------------
# REFERENCE-native formats (VERDICT r3 #10): the reference persists
# strategies two ways — the FFProtoBuf.Strategy protobuf the DLRM
# examples ship (examples/cpp/DLRM/strategies/*.pb, schema in
# dlrm_strategy.py: Op{name=1, device_type=2, dims=3, device_ids=4})
# and the plain-text token stream of strategy.cc:95-189
# (<count> then per op: name, device_type, nDims, dims..., n, ids...).
# Both import directly onto OpStrategy so reference artifacts replay.
# ---------------------------------------------------------------------------

def parse_reference_pb(path: str) -> List[tuple]:
    """Decode FFProtoBuf.Strategy with the in-tree protobuf wire reader
    (no protobuf dependency). Returns [(name, device_type, dims, ids)]."""
    from ..frontends.onnx_wire import _fields, _varint
    with open(path, "rb") as f:
        buf = f.read()

    def ints(val):  # repeated varint: non-packed int or packed bytes
        if isinstance(val, int):
            return [val]
        out, pos = [], 0
        while pos < len(val):
            v, pos = _varint(val, pos)
            out.append(v)
        return out

    out = []
    for fno, wt, val in _fields(buf):
        if fno != 1:  # Strategy.ops
            continue
        if wt != 2:  # not a length-delimited message: wrong proto
            raise ValueError(
                f"{path}: field 1 has wire type {wt}, expected an "
                f"embedded Op message — not an FFProtoBuf.Strategy "
                f"file")
        name, dtype = "", 0
        dims: List[int] = []
        ids: List[int] = []
        for ofno, owt, oval in _fields(bytes(val)):
            if ofno == 1:
                name = oval.decode()
            elif ofno == 2:
                dtype = int(oval)
            elif ofno == 3:
                dims.extend(ints(oval))
            elif ofno == 4:
                ids.extend(ints(oval))
        if not name:
            raise ValueError(
                f"{path}: Op entry without a name — not an "
                f"FFProtoBuf.Strategy file")
        out.append((name, dtype, dims, ids))
    return out


def parse_reference_text(path: str) -> List[tuple]:
    """Token-stream parser mirroring load_strategies_from_file
    (strategy.cc:95-144): whitespace-insensitive, count-prefixed."""
    with open(path) as f:
        toks = f.read().split()
    it = iter(toks)
    n_ops = int(next(it))
    out = []
    for _ in range(n_ops):
        name = next(it)
        dtype = int(next(it))
        ndims = int(next(it))
        dims = [int(next(it)) for _ in range(ndims)]
        n_ids = int(next(it))
        ids = [int(next(it)) for _ in range(n_ids)]
        out.append((name, dtype, dims, ids))
    return out


def _dims_to_axis_map(op: Op, dims: List[int], mesh,
                      legion_order: bool = False) -> Dict[str, str]:
    """Per-dim split counts -> axis map: each >1 split matches the
    first unused mesh axis of that size (sorted by name for
    determinism). `legion_order` reverses first — reference files store
    the sample dim LAST (Legion order), our own text format stores
    NumPy order."""
    out_axes = op.output_axes()[0] if op.outputs else ()
    seq = list(reversed(dims)) if legion_order else list(dims)
    axis_map: Dict[str, str] = {}
    used = set()
    for i, split in enumerate(seq):
        if split <= 1 or i >= len(out_axes) or out_axes[i] is None:
            continue
        for mesh_ax, size in sorted(mesh.shape.items()):
            if size == split and mesh_ax not in used:
                axis_map[out_axes[i]] = mesh_ax
                used.add(mesh_ax)
                break
    return axis_map


# family names the reference uses for shared entries (one "linear"
# entry governs every Linear op via name-hash lookup)
_FAMILY_TYPES = {"linear": "linear", "concat": "concat",
                 "conv2d": "conv2d", "embedding": "embedding",
                 "attention": "multihead_attention"}


def load_reference_strategy_file(model, mesh, path: str) -> Strategy:
    """Import a REFERENCE strategy artifact (protobuf .pb or
    strategy.cc text) onto this model:

    * exact-name entries bind to the same-named op;
    * `embedding<N>` entries with whole-op pins collapse onto a
      `distributed_embedding` op's per-table `__devices__` tuple (the
      executable form of the reference's per-GPU DLRM tables);
    * family entries ("linear", "concat", ...) bind to every op of
      that type, reproducing the reference's shared-name lookup;
    * identity device lists with >1 splits become mesh-axis mappings;
      non-identity lists become explicit placements.
    """
    entries = (parse_reference_pb(path) if path.endswith(".pb")
               else parse_reference_text(path))
    strat = Strategy()
    ops_by_name = {op.name: op for op in model.ops}

    # collapse embedding<N> whole-op pins onto stacked-table ops
    emb_entries = sorted(
        ((int(name[len("embedding"):]), ids) for name, _d, dims, ids
         in entries
         if name.startswith("embedding")
         and name[len("embedding"):].isdigit()
         and len(ids) == 1 and all(d == 1 for d in dims)),
        key=lambda t: t[0])
    if emb_entries:
        table_ids = tuple(ids[0] for _n, ids in emb_entries)
        for op in model.ops:
            if op.op_type == "distributed_embedding" \
                    and getattr(op, "num_tables", 0) == len(table_ids):
                strat.set(op.name, OpStrategy({DEVICE_KEY: table_ids}))
                break

    import re

    def apply(op, name, dims, ids):
        n_parts = int(np.prod(dims)) if dims else 1
        axis_map = _dims_to_axis_map(op, dims, mesh, legion_order=True)
        if ids and ids != list(range(n_parts)) and not axis_map:
            axis_map = {DEVICE_KEY: tuple(ids)}
        elif ids and ids != list(range(n_parts)) and axis_map:
            warnings.warn(
                f"reference strategy {name!r}: explicit device ids "
                f"{ids} on a split op load as the split only")
        strat.set(op.name, OpStrategy(axis_map))

    # pass 1: exact-name entries (the reference's hash lookup gives an
    # op its same-named entry — these always win)
    for name, _dtype, dims, ids in entries:
        op = ops_by_name.get(name)
        if op is None:
            continue
        if op.name in strat.op_strategies:  # collapsed table pins win
            continue
        apply(op, name, dims, ids)

    # pass 2: family / indexed bindings, never overwriting pass 1
    for name, _dtype, dims, ids in entries:
        if name in ops_by_name:
            continue
        if name in _FAMILY_TYPES:
            targets = [op for op in model.ops
                       if op.op_type == _FAMILY_TYPES[name]]
        elif name.startswith("embedding") \
                and name[len("embedding"):].isdigit():
            # bind to the standalone embedding op with the SAME
            # trailing index (suffix matching would alias 1 and 11)
            idx = int(name[len("embedding"):])
            targets = []
            for op in model.ops:
                if op.op_type != "embedding":
                    continue
                m = re.search(r"(\d+)$", op.name)
                if m and int(m.group(1)) == idx:
                    targets.append(op)
        else:
            continue
        for op in targets:
            if op.name in strat.op_strategies:
                continue  # exact entries / table collapse win
            apply(op, name, dims, ids)
    return strat


def load_strategies_from_file(model, mesh, path: str) -> Strategy:
    """Rebuild an axis map from the text format: a >1 split on dim i maps
    that dim's logical axis to the smallest matching mesh axis."""
    with open(path) as f:
        tokens = f.read().split("\n")
    n = int(tokens[0].strip())
    ops_by_name = {op.name: op for op in model.ops}
    strat = Strategy()
    for line in tokens[1:n + 1]:
        parts = line.split()
        name, dev_type = parts[0], parts[1]
        ndims = int(parts[2])
        dims = [int(x) for x in parts[3:3 + ndims]]
        device_ids = [int(x) for x in parts[3 + ndims:]]
        op = ops_by_name.get(name)
        if op is None:
            continue
        axis_map: Dict[str, str] = _dims_to_axis_map(op, dims, mesh)
        # explicit placement: the "tpu_pin" device-type marker, or an
        # unsplit op whose device list differs from the default range
        # (how the reference's DLRM strategy files pin tables)
        n_parts = int(np.prod(dims)) if dims else 1
        if device_ids and (dev_type == "tpu_pin"
                           or (not axis_map
                               and device_ids != list(range(n_parts)))):
            axis_map = {DEVICE_KEY: tuple(device_ids)}
        elif (axis_map and device_ids
                and device_ids != list(range(n_parts))):
            # split AND explicitly placed: the mesh-axis mapping cannot
            # carry the id list — be honest about the approximation
            warnings.warn(
                f"strategy file op {name!r}: explicit device ids "
                f"{device_ids} on a split op are not representable as a "
                f"mesh-axis mapping; loading the split only")
        strat.set(name, OpStrategy(axis_map))
    return strat
