"""Pipeline parallelism: GPipe-style microbatch schedule over a mesh
`pipe` axis via shard_map + collective permute.

The reference has NO pipeline schedule — its "model parallelism" is
per-op device placement with concurrency only from Legion dataflow
asynchrony (SURVEY.md 2.4). Here PP is a first-class axis: a stack of
identical blocks (leading dim L) is split into S = |pipe| stages of L/S
layers; M microbatches stream through the ring. Device s computes
microbatch m at tick t = m + s; activations hop stages via ppermute.
Bubble fraction = (S-1)/(M+S-1), the standard GPipe bound.

All devices run the same SPMD program (XLA requirement); stage-dependent
behavior comes from `lax.axis_index`.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from ._compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def pipeline_apply(block_fn: Callable, stacked_params, x, mesh: Mesh,
                   *, pipe_axis: str = "pipe", num_microbatches: int,
                   num_layers: int, data_axis: str = "data"):
    """Run x through L stacked blocks, pipelined over `pipe_axis`.

    block_fn(layer_params, h, layer_idx) -> (y, aux) with
    y.shape == h.shape and aux a float32 scalar (0.0 if unused).
    stacked_params: pytree, every leaf has leading dim L (L % S == 0);
    may be empty for weightless blocks.
    x: (B, ...) global batch; B % num_microbatches == 0.
    Returns (out (B, ...), aux_total scalar).

    Note: under PP the aux term is the mean over microbatches of the
    per-microbatch aux — for nonlinear aux losses (e.g. MoE balancing)
    this is an approximation of the full-batch value.
    """
    L = num_layers

    if pipe_axis not in mesh.shape or mesh.shape[pipe_axis] == 1:
        def body(carry, inp):
            h, aux = carry
            layer_params, li = inp
            y, a = block_fn(layer_params, h, li)
            return (y, aux + a), None
        (out, aux), _ = lax.scan(
            body, (x, jnp.float32(0.0)),
            (stacked_params, jnp.arange(L)), length=L)
        return out, aux

    S = mesh.shape[pipe_axis]
    M = num_microbatches
    B = x.shape[0]
    assert B % M == 0, f"batch {B} not divisible by {M} microbatches"
    mb = B // M
    xm = x.reshape((M, mb) + x.shape[1:])
    assert L % S == 0, f"{L} layers not divisible by {S} stages"
    l_loc = L // S

    data_ax = data_axis if data_axis in mesh.shape else None
    # params: layer dim sharded over pipe; x: microbatches replicated over
    # pipe (each sharded over data on the batch dim inside the microbatch)
    param_spec = jax.tree_util.tree_map(
        lambda l: P(pipe_axis, *([None] * (l.ndim - 1))), stacked_params)
    x_spec = P(None, data_ax, *([None] * (x.ndim - 1)))

    def local_fn(params_local, xm_local):
        # params_local leaves: (L/S, ...); xm_local: (M, mb_local, ...)
        idx = lax.axis_index(pipe_axis)
        zero = jnp.zeros_like(xm_local[0])

        def stage_compute(carry_in, t):
            # first stage consumes microbatch t; later stages consume the
            # activation handed over from the previous stage
            mb_idx = jnp.clip(t, 0, M - 1)
            my_in = jnp.where(idx == 0,
                              lax.dynamic_index_in_dim(
                                  xm_local, mb_idx, keepdims=False),
                              carry_in)

            def layer(carry, inp):
                h, aux = carry
                lp, lj = inp
                y, a = block_fn(lp, h, idx * l_loc + lj)
                return (y, aux + a), None
            (out, aux), _ = lax.scan(
                layer, (my_in, jnp.float32(0.0)),
                (params_local, jnp.arange(l_loc)), length=l_loc)
            return out, aux

        def tick(carry, t):
            carry_act, outputs, aux_acc = carry
            out, aux = stage_compute(carry_act, t)
            # this stage's compute is meaningful only for 0 <= t-idx < M
            # (warmup/drain ticks process garbage; mask their aux)
            valid = jnp.logical_and(t - idx >= 0, t - idx < M)
            aux_acc = aux_acc + jnp.where(valid, aux, 0.0)
            # hand activation to the next stage (ring; last->first wraps
            # but the wrapped value is ignored by stage 0)
            perm = [(i, (i + 1) % S) for i in range(S)]
            nxt = lax.ppermute(out, pipe_axis, perm)
            # last stage finished microbatch t-(S-1) this tick
            done_idx = t - (S - 1)
            write = jnp.logical_and(idx == S - 1, done_idx >= 0)
            safe_idx = jnp.clip(done_idx, 0, M - 1)
            cur = lax.dynamic_index_in_dim(outputs, safe_idx,
                                           keepdims=False)
            upd = jnp.where(write, out, cur)
            outputs = lax.dynamic_update_index_in_dim(
                outputs, upd, safe_idx, 0)
            return (nxt, outputs, aux_acc), None

        outputs0 = jnp.zeros_like(xm_local)
        (_, outputs, aux_acc), _ = lax.scan(
            tick, (zero, outputs0, jnp.float32(0.0)),
            jnp.arange(M + S - 1))
        # results live on the last stage; broadcast to all stages so the
        # output spec can stay replicated over pipe
        outputs = lax.psum(
            jnp.where(idx == S - 1, outputs, jnp.zeros_like(outputs)),
            pipe_axis)
        # aux: sum over stages' valid ticks, averaged over microbatches
        aux_total = lax.psum(aux_acc, pipe_axis) / M
        return outputs, aux_total

    out, aux = shard_map(local_fn, mesh=mesh,
                         in_specs=(param_spec, x_spec),
                         out_specs=(x_spec, P()),
                         check_vma=False)(stacked_params, xm)
    return out.reshape((B,) + x.shape[1:]), aux
