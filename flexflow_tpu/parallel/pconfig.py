"""Per-op parallelization strategies.

Reference `ParallelConfig` (include/config.h:47-73): device_type, nDims,
per-dim split counts, explicit device_ids. The TPU-native strategy is a
mapping {logical axis -> mesh axis}; split counts follow from the mesh
axis sizes and explicit device ids follow from the mesh layout, so both
reference fields are derived, not stored.

`ParallelConfig` is retained as a compatibility view (strategy file I/O,
tests that check reference semantics like num_parts).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional

from ..op import Op


@dataclasses.dataclass
class ParallelConfig:
    """Compatibility view of one op's placement (reference config.h:47-73)."""

    device_type: str = "tpu"
    dims: List[int] = dataclasses.field(default_factory=lambda: [1])
    device_ids: List[int] = dataclasses.field(default_factory=lambda: [0])

    @property
    def num_parts(self) -> int:
        n = 1
        for d in self.dims:
            n *= d
        return n

    def is_data_parallel(self) -> bool:
        # reference simulator.cc:28-40: DP = only the sample (outermost
        # logical, innermost stored) dim is split. We store NumPy order, so
        # DP = only dims[0] split.
        return self.num_parts == self.dims[0]


DEVICE_KEY = "__devices__"


@dataclasses.dataclass
class OpStrategy:
    """Maps an op's logical axes to mesh axes. axis_map values may be a
    mesh axis name, a tuple of axis names (multi-axis sharding), or None.

    Device-explicit placement (the reference's `ParallelConfig.device_ids`,
    include/config.h:47-73 — what lets DLRM pin each embedding table to
    one device): the reserved `__devices__` axis_map entry binds the op to
    an explicit device-index tuple instead of the mesh-uniform SPMD
    program. The simulator gives such ops their own compute resources
    (concurrency across disjoint devices) and the cost model prices the
    gather of their outputs; see search/cost_model.py."""

    axis_map: Dict[str, object] = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        if DEVICE_KEY in self.axis_map:  # normalize for keying/dedup
            self.axis_map[DEVICE_KEY] = tuple(self.axis_map[DEVICE_KEY])

    @property
    def device_ids(self) -> Optional[tuple]:
        """Explicit device placement, or None for mesh-uniform SPMD."""
        return self.axis_map.get(DEVICE_KEY)

    def mesh_axis_for(self, logical_axis: Optional[str]):
        if logical_axis is None:
            return None
        return self.axis_map.get(logical_axis)

    def copy(self) -> "OpStrategy":
        return OpStrategy(dict(self.axis_map))


class Strategy:
    """Global strategy: op name -> OpStrategy, plus a default.

    The default maps `sample` to the mesh's `data` axis — exactly the
    reference's seeded data-parallel default (mapper.cc:118-145).
    """

    def __init__(self, op_strategies: Optional[Dict[str, OpStrategy]] = None,
                 default: Optional[OpStrategy] = None):
        self.op_strategies: Dict[str, OpStrategy] = op_strategies or {}
        self.default = default or OpStrategy({"sample": "data"})
        # search-discovered pipeline lowering that cannot ride per-op
        # pins (interleaved auto-cut: v stages per device) — carried so
        # --export/--import round-trips the whole winning plan:
        # {"stages": D, "virtual_stages": v, "schedule": "1f1b",
        #  "microbatches": M}. compile() applies it to the config knobs
        # its auto-cut lowering reads.
        self.pipeline: Optional[Dict] = None

    def for_op(self, op_name: str) -> OpStrategy:
        return self.op_strategies.get(op_name, self.default)

    def set(self, op_name: str, strategy: OpStrategy) -> None:
        self.op_strategies[op_name] = strategy

    def copy(self) -> "Strategy":
        out = Strategy(
            {k: v.copy() for k, v in self.op_strategies.items()},
            self.default.copy(),
        )
        out.pipeline = dict(self.pipeline) if self.pipeline else None
        return out

    # ---- file I/O ----
    # Native format is JSON ({"default": {...}, "ops": {name: axis_map}}).
    # The reference's plain-text format (strategy.cc:95-189) is also
    # readable/writable for tooling familiarity via to_text/from_text.

    def save(self, path: str) -> None:
        data = {
            "format": "flexflow_tpu_strategy_v1",
            "default": self.default.axis_map,
            "ops": {k: v.axis_map for k, v in self.op_strategies.items()},
        }
        if self.pipeline:
            data["pipeline"] = self.pipeline
        with open(path, "w") as f:
            json.dump(data, f, indent=2)

    @staticmethod
    def load(path: str) -> "Strategy":
        with open(path) as f:
            data = json.load(f)
        out = Strategy(
            {k: OpStrategy(v) for k, v in data.get("ops", {}).items()},
            OpStrategy(data.get("default", {"sample": "data"})),
        )
        pl = data.get("pipeline")
        if pl is not None:
            # fail at load with the file in hand, not deep in compile
            if not isinstance(pl, dict) \
                    or not isinstance(pl.get("stages"), int) \
                    or pl["stages"] < 1:
                raise ValueError(
                    f"{path}: \"pipeline\" must be an object with an "
                    f"int \"stages\" >= 1 (got {pl!r})")
            out.pipeline = pl
        return out

    def __repr__(self):
        return (f"Strategy(default={self.default.axis_map}, "
                f"{len(self.op_strategies)} op overrides)")


def placement_assignment(tables: int, devices: int, scheme: str) -> tuple:
    """Per-table device assignment schemes — the single source the MCMC
    candidates (search/mcmc.py) and the strategy generator
    (tools/gen_dlrm_strategy.py) both draw from, so the generator's
    output always lies inside the search space (reference
    dlrm_strategy.py emits what its search consumed, likewise)."""
    if tables < 1 or devices < 1:
        raise ValueError(
            f"tables and devices must be >= 1, got {tables}/{devices}")
    if scheme == "round_robin":
        return tuple(t % devices for t in range(tables))
    if scheme == "blocked":
        return tuple(min(t * devices // tables, devices - 1)
                     for t in range(tables))
    if scheme == "one_device":
        return (0,) * tables
    raise ValueError(f"unknown placement scheme {scheme!r}")


DATA_PARALLEL = Strategy()


def sequence_parallel_strategy(seq_axis: str = "seq") -> Strategy:
    """SP/CP: activations sharded over the sequence dim; attention runs
    as ring attention over `seq_axis` (new capability vs the reference,
    SURVEY.md 2.4)."""
    return Strategy(default=OpStrategy({"sample": "data",
                                        "seq": seq_axis}))


def megatron_strategy(model_axis: str = "model") -> Strategy:
    """TP default: split channel_out/head/vocab over the model axis (the
    reference reached the same placement through MCMC discovering
    out-channel splits for Linear, linear.cu:1074-1107)."""
    return Strategy(default=OpStrategy({
        "sample": "data",
        "channel_out": model_axis,
        "head": model_axis,
        "vocab": model_axis,
    }))
