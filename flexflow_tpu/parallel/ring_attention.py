"""Ring attention — sequence/context parallelism over ICI.

The reference has NO sequence-parallel axis (SURVEY.md 2.4: "SP/CP ...
absent"); this is a designed-in new capability. Q, K, V are sharded over
the mesh `seq` axis; each device keeps its Q shard resident and the K/V
shards rotate around the ring via `lax.ppermute`, with online-softmax
(flash-style m/l rescaling) accumulation so the full score matrix never
materializes. Per-step compute is (s_local x s_local) — XLA overlaps the
ppermute with the block matmuls.

Causal masking uses *global* positions derived from `lax.axis_index`, so
results are exactly those of unsharded top-left-causal attention.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from ._compat import shard_map


def _block_scores(q, k, scale):
    # q: (b, sq, h, d), k: (b, sk, h, d) -> (b, h, sq, sk) fp32
    return jnp.einsum("bqhd,bkhd->bhqk", q, k,
                      preferred_element_type=jnp.float32) * scale


def _ring_attn_local(q, k, v, *, axis_name, causal, scale):
    """Runs inside shard_map: q,k,v are local seq-shards."""
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    b, sq, h, d = q.shape
    sk = k.shape[1]

    qf = q.astype(jnp.float32)
    m = jnp.full((b, h, sq), -jnp.inf, jnp.float32)
    l = jnp.zeros((b, h, sq), jnp.float32)
    acc = jnp.zeros((b, sq, h, d), jnp.float32)

    def step(carry, step_idx):
        m, l, acc, k_cur, v_cur = carry
        # shard currently held = (my_idx - step_idx) mod axis_size
        src = (my_idx - step_idx) % axis_size
        s = _block_scores(qf, k_cur.astype(jnp.float32), scale)
        if causal:
            qpos = (my_idx * sq
                    + lax.broadcasted_iota(jnp.int32, (sq, sk), 0))
            kpos = (src * sk
                    + lax.broadcasted_iota(jnp.int32, (sq, sk), 1))
            s = jnp.where((qpos >= kpos)[None, None], s, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        # guard fully-masked rows: keep m finite so exp() stays 0, not nan
        m_safe = jnp.where(jnp.isinf(m_new), 0.0, m_new)
        p = jnp.exp(s - m_safe[..., None])
        p = jnp.where(jnp.isinf(m_new)[..., None], 0.0, p)
        alpha = jnp.where(jnp.isinf(m), 0.0, jnp.exp(m - m_safe))
        l_new = l * alpha + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p, v_cur.astype(jnp.float32))
        acc_new = acc * alpha.transpose(0, 2, 1)[..., None] + pv
        # rotate k/v one hop around the ring
        perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (m_new, l_new, acc_new, k_nxt, v_nxt), None

    (m, l, acc, _, _), _ = lax.scan(
        step, (m, l, acc, k, v), jnp.arange(axis_size))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = acc / l_safe.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention(q, k, v, mesh: Mesh, *, seq_axis: str = "seq",
                   batch_axis: str = "data", causal: bool = False,
                   scale: float = None):
    """(b, s, h, d) attention with s sharded over `seq_axis`.

    Call under jit with global arrays; shard_map partitions internally.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    batch_ax = batch_axis if batch_axis in mesh.shape else None
    spec = P(batch_ax, seq_axis, None, None)
    fn = partial(_ring_attn_local, axis_name=seq_axis, causal=causal,
                 scale=scale)
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)(q, k, v)
