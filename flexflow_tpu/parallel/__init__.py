"""Parallelism: mesh construction, per-op strategies, sharding resolution,
ring attention (SP), pipeline parallelism.

This layer replaces the reference's FFMapper + ParallelConfig machinery
(src/mapper/mapper.cc, include/config.h:47-73): instead of routing Legion
index-task points to explicit device ids, a strategy maps each op's
*logical axes* to mesh axes and GSPMD materializes the placement.
"""

from .mesh import MachineSpec, make_mesh, default_mesh
from .pconfig import OpStrategy, Strategy, ParallelConfig
from .sharding import spec_for_axes, op_output_sharding, weight_sharding
