"""All-to-all (DeepSpeed-Ulysses-style) sequence parallelism.

The second SP lowering next to ring attention (ring_attention.py; the
reference has NO sequence axis at all — SURVEY.md 2.4). Instead of
keeping Q resident and rotating K/V shards around the ring, two
`lax.all_to_all`s re-partition the problem: heads scatter over the
`seq` mesh axis while the sequence gathers, so each device runs
STANDARD full-sequence attention for h/n heads, then the output
all-to-alls back to sequence shards.

TPU tradeoff vs ring:
  * all-to-all rides the ICI torus at bisection bandwidth (priced by
    machine_model.all_to_all) and the attention itself is one big
    (s x s) block per head group — full MXU tiles and full
    flash-kernel compatibility, where ring computes n smaller
    (s/n x s/n) blocks with a ppermute between each.
  * memory: scores materialize (b, h/n, s, s) per device unless the
    flash path takes over, so very long sequences still want the ring
    (the `auto` policy in `sp_mode_for` draws that line).
Head-count divisibility (h % n == 0) is required; ring has no such
constraint.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from ._compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

# score-matrix bytes per device above which `auto` falls back to ring
# attention (which never materializes scores). Mirrors the flash
# heuristic's working-set bound (ops/attention.py).
ALLTOALL_SCORE_BYTES_LIMIT = 2 << 30


def sp_mode_for(cfg_mode: str, *, num_heads: int, seq_size: int,
                batch_local: int, seq_q: int, seq_kv: int) -> str:
    """Resolve the SP attention lowering: explicit "ring"/"alltoall"
    pass through (alltoall still requires head divisibility); "auto"
    picks alltoall when heads divide AND the per-device (sq x sk)
    score matrix fits, else ring. Shared by the executing op
    (ops/attention.py) and the cost model so the search prices what
    actually runs."""
    if num_heads % seq_size != 0:
        return "ring"
    if cfg_mode in ("ring", "alltoall"):
        return cfg_mode
    score_bytes = (4.0 * batch_local * (num_heads // seq_size)
                   * seq_q * seq_kv)
    return "alltoall" if score_bytes <= ALLTOALL_SCORE_BYTES_LIMIT \
        else "ring"


def _a2a(x, axis_name, *, split_axis, concat_axis):
    return lax.all_to_all(x, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def _alltoall_attn_local(q, k, v, *, axis_name, causal, scale,
                         use_flash):
    """Runs inside shard_map: q,k,v are (b, s_local, h, d) seq-shards."""
    # heads scatter, sequence gathers -> (b, s_global, h_local, d)
    q = _a2a(q, axis_name, split_axis=2, concat_axis=1)
    k = _a2a(k, axis_name, split_axis=2, concat_axis=1)
    v = _a2a(v, axis_name, split_axis=2, concat_axis=1)
    # full-sequence blocks mean the flash kernel applies unchanged —
    # the point of this lowering at long s (ring's per-hop blocks are
    # s/n x s/n). Same tri-state + measured gate as the unsharded
    # dispatch (ops/attention.py); the kernel bakes in 1/sqrt(d), so a
    # caller-custom scale falls back to the XLA path.
    from ..kernels.flash_attention import flash_profitable
    b, sq, h, d = q.shape
    sk = k.shape[1]
    want_flash = (use_flash is True
                  or (use_flash is None
                      and flash_profitable(b, h, sq, sk, d)))
    if want_flash and abs(scale * math.sqrt(d) - 1.0) < 1e-6:
        try:
            from ..kernels.flash_attention import flash_attention_bshd
            out = flash_attention_bshd(q, k, v, causal=causal)
            return _a2a(out, axis_name, split_axis=1, concat_axis=2)
        except Exception:
            pass  # tiny shapes / non-TPU: XLA path below
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32),
                   preferred_element_type=jnp.float32) * scale
    if causal:
        # top-left alignment over the GLOBAL (sq x sk) score block,
        # matching ring attention's cross-attention handling
        qpos = lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        kpos = lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where((qpos >= kpos)[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p,
                     v.astype(jnp.float32)).astype(q.dtype)
    # sequence scatters back, heads gather -> (b, s_local, h, d)
    return _a2a(out, axis_name, split_axis=1, concat_axis=2)


def alltoall_attention(q, k, v, mesh: Mesh, *, seq_axis: str = "seq",
                       batch_axis: str = "data", causal: bool = False,
                       scale: float = None, use_flash=None):
    """(b, s, h, d) attention with s sharded over `seq_axis`, lowered
    via head-scatter/seq-gather all-to-alls. Exact (softmax over the
    full sequence); numerics match unsharded attention. Requires
    h % axis_size == 0. `use_flash` is the op's tri-state (None=auto /
    True=force / False=never) for the per-device kernel."""
    n = int(mesh.shape[seq_axis])
    if q.shape[2] % n != 0:
        raise ValueError(
            f"alltoall SP needs heads ({q.shape[2]}) divisible by the "
            f"{seq_axis!r} axis size ({n}); use ring attention")
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    batch_ax = batch_axis if batch_axis in mesh.shape else None
    spec = P(batch_ax, seq_axis, None, None)
    fn = partial(_alltoall_attn_local, axis_name=seq_axis,
                 causal=causal, scale=scale, use_flash=use_flash)
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)(q, k, v)
