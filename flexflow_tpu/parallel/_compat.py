"""Version-compat shims for the parallel subsystem.

`shard_map` has moved across jax releases: new jax exports it at the
top level (`jax.shard_map`), older releases only under
`jax.experimental.shard_map` — and the replication-check kwarg was
renamed (`check_rep` -> `check_vma`). Every shard_map consumer in this
package (ring_attention, ulysses, pipeline, graph_pipeline — and
core/staged.py through graph_pipeline) imports it from here so the
version probe lives in exactly one place. Call sites use the NEW
spelling (`check_vma`); the wrapper translates for old jax.
"""

from __future__ import annotations

import functools
import inspect

try:  # jax >= 0.6: top-level export
    from jax import shard_map as _shard_map
except ImportError:  # older jax: experimental namespace
    from jax.experimental.shard_map import shard_map as _shard_map

if "check_vma" in inspect.signature(_shard_map).parameters:
    shard_map = _shard_map
else:
    @functools.wraps(_shard_map)
    def shard_map(*args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map(*args, **kwargs)

__all__ = ["shard_map"]
