"""Resolve (op, strategy, mesh) -> jax shardings.

This is the whole of the reference's mapper layer (mapper.cc slice_task /
map_task, 1531 LoC) reduced to PartitionSpec construction: GSPMD does the
actual placement and collective insertion.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..op import TABLE, Op, WeightSpec
from .pconfig import OpStrategy


def spec_for_axes(axes: Sequence[Optional[str]], strategy: OpStrategy,
                  mesh: Mesh, shape: Optional[Sequence[int]] = None) -> P:
    """Build a PartitionSpec mapping each logical axis through the
    strategy; axes that resolve to mesh axes not present in `mesh` (or
    that don't divide the dim size) are left unsharded."""
    entries = []
    used = set()
    for i, ax in enumerate(axes):
        m = strategy.mesh_axis_for(ax)
        if m is None:
            entries.append(None)
            continue
        names = (m,) if isinstance(m, str) else tuple(m)
        names = tuple(n for n in names
                      if n in mesh.shape and n not in used)
        if not names:
            entries.append(None)
            continue
        if shape is not None:
            size = 1
            for n in names:
                size *= mesh.shape[n]
            if shape[i] % size != 0:
                entries.append(None)
                continue
        used.update(names)
        entries.append(names[0] if len(names) == 1 else names)
    while entries and entries[-1] is None:
        entries.pop()
    return P(*entries)


def op_output_sharding(op: Op, strategy: OpStrategy, mesh: Mesh):
    """NamedSharding per output of `op`."""
    out = []
    for i, axes in enumerate(op.output_axes()):
        spec = spec_for_axes(axes, strategy, mesh, op.outputs[i].shape)
        out.append(NamedSharding(mesh, spec))
    return out


def weight_sharding(spec: WeightSpec, strategy: OpStrategy, mesh: Mesh):
    pspec = spec_for_axes(spec.axes, strategy, mesh, spec.shape)
    return NamedSharding(mesh, pspec)


def effective_op_strategy(op: Op, strategy: OpStrategy,
                          mesh: Mesh) -> OpStrategy:
    """Strategy view actually lowered for `op`. Device-placed stacked
    embeddings (reference device_ids, executed via slice_task
    mapper.cc:346-440): the slot-stacked `table` axis shards over the
    FULL mesh in device order, so slot block d lives exactly on
    mesh.devices.flat[d] — the strategy's explicit ids execute
    literally rather than as replication."""
    if mesh is not None and getattr(op, "placement", None):
        am = {k: v for k, v in strategy.axis_map.items()}
        am[TABLE] = tuple(mesh.axis_names)
        return OpStrategy(am)
    return strategy


def batch_sharding(mesh: Mesh, ndim: int, data_axis: str = "data"):
    """Input batch: shard dim 0 over the data axis."""
    if data_axis not in mesh.shape:
        return NamedSharding(mesh, P())
    return NamedSharding(mesh, P(data_axis, *([None] * (ndim - 1))))


def place_global(arr, sharding):
    """Place a host-computed GLOBAL array under `sharding`. Single
    process: plain device_put. Multi-controller SPMD: device_put cannot
    address remote devices, so each process contributes its addressable
    shards from the (identically computed on every host — deterministic
    init/imports) global array via make_array_from_callback."""
    import numpy as np
    if jax.process_count() <= 1:
        return jax.device_put(arr, sharding)
    host = np.asarray(arr)
    return jax.make_array_from_callback(
        host.shape, sharding, lambda idx: host[idx])


def place_process_local(host, sharding):
    """Place per-process batch data: each process holds ITS shard of
    the global batch (global = concat over processes in process order).
    Single process this is just device_put."""
    if jax.process_count() <= 1:
        return jax.device_put(host, sharding)
    if sharding.is_fully_replicated:
        # replicated placement would install each process's DIFFERENT
        # local batch as "the same" global array — XLA assumes
        # replicated operands are identical across processes, so this
        # is silent data corruption, not a supported layout
        raise NotImplementedError(
            "multi-process batch placement needs a 'data' mesh axis to "
            "split the global batch; a replicated batch would combine "
            "different per-process data silently")
    return jax.make_array_from_process_local_data(sharding, host)
