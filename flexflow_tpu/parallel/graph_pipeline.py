"""Generalized pipeline parallelism over ARBITRARY op graphs.

Reference FlexFlow executes per-op device placement by routing each op's
index-task points to its `ParallelConfig.device_ids`
(/root/reference/src/mapper/mapper.cc:346-440); concurrency between ops
placed on different devices comes from Legion's dataflow asynchrony.
XLA's SPMD model has no per-op device routing — every device runs one
program — so the TPU-native execution of "layer L on device d" is a
PIPELINE: stages are contiguous groups of ops, the mesh `pipe` axis
assigns one stage per device coordinate, and microbatches stream
through the ring (shard_map + lax.switch on the stage index +
lax.ppermute hops). This file is that lowering:

  * ``StagePlan``     — partition of the op graph into S stages, with
    the boundary (cut) tensors each inter-stage hop must carry.
    Built either from a strategy's explicit whole-op device pins
    (`assignment_from_pins`, the executable form of the reference's
    propagate-placed strategies model.cc:1807-1903) or by flops-balanced
    auto-cut (`balanced_stages`, the analog of SURVEY §7 hard part (c):
    searching stage boundaries).
  * ``PackSpec``      — per-stage parameter flat-packing: every stage's
    weights flatten into one (S, L) row per dtype, sharded over the
    pipe axis, so each device PHYSICALLY holds only its stage's
    parameters (and its optimizer state rows) — true weight residency,
    not replication. Elementwise optimizers (SGD/Adam) apply to packed
    rows unchanged.
  * ``pipeline_logits`` — the schedule. GPipe semantics: M microbatches,
    M + S - 1 ticks, bubble fraction (S-1)/(M+S-1); backward runs as the
    autodiff transpose of the same schedule (reverse pipeline).
    `schedule="1f1b"` interleaves each stage's backward with remaining
    forwards via a two-wire (activation + cotangent) steady state,
    cutting peak per-stage activation storage from M to S microbatches.

Heterogeneous stages are expressed as `lax.switch` branches on
`lax.axis_index(pipe)`: XLA compiles every stage body once, each device
executes its own branch — the one-program answer to Legion's per-device
task variants.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from ._compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..op import Op, OpContext


# --------------------------------------------------------------------------
# stage planning
# --------------------------------------------------------------------------

@dataclasses.dataclass
class StagePlan:
    """Partition of a model's op graph into pipeline stages.

    stages[s]    ops of stage s, in topological order
    stage_of     op name -> stage index
    cuts[i]      tensors crossing the boundary between stages <= i and
                 stages > i (each must ride hop i of the wire)
    """

    stages: List[List[Op]]
    stage_of: Dict[str, int]
    cuts: List[List]  # List[List[Tensor]]

    @property
    def num_stages(self) -> int:
        return len(self.stages)


def _check_supported(model, stage_of: Dict[str, int]) -> None:
    # stateful ops (BatchNorm) are legal under BOTH schedules: packed
    # state rows advance per microbatch in order at fwd ticks
    # (grad-accumulation semantics); 1F1B's backward recompute reads
    # state as a constant, guarded by Op.training_output_reads_state
    # (StagedExecutor rejects ops that set it)
    for op in model.ops:
        if op.op_type == "pipeline_blocks":
            raise NotImplementedError(
                f"graph pipeline: {op.name!r} is itself a pipeline "
                f"meta-op; nesting pipelines is not supported")
        if op.name not in stage_of:
            raise ValueError(f"op {op.name!r} has no stage assignment")


def build_stage_plan(model, stage_of: Dict[str, int]) -> StagePlan:
    """Materialize a StagePlan from an op->stage map. Validates that
    data flows forward (producer stage <= consumer stage) and computes
    the cut tensors every hop must carry."""
    _check_supported(model, stage_of)
    S = max(stage_of.values()) + 1
    producer = {}
    for op in model.ops:
        for t in op.outputs:
            producer[t.uid] = op.name
    input_uids = {t.uid for t in model.input_tensors}
    for op in model.ops:
        for t in op.inputs:
            if t.uid in input_uids:
                continue  # graph inputs are microbatch-fed to every stage
            ps = stage_of[producer[t.uid]]
            if ps > stage_of[op.name]:
                raise ValueError(
                    f"stage assignment sends tensor {t.uid} backward: "
                    f"producer {producer[t.uid]!r} is stage {ps}, "
                    f"consumer {op.name!r} is stage "
                    f"{stage_of[op.name]} — pipeline hops only go "
                    f"forward")
    stages: List[List[Op]] = [[] for _ in range(S)]
    for op in model.ops:  # model.ops is topological order
        stages[stage_of[op.name]].append(op)

    # last consumer stage per tensor; the model output is virtually
    # consumed at the last stage (it must arrive there to be emitted)
    last_use: Dict[int, int] = {}
    for op in model.ops:
        for t in op.inputs:
            if t.uid in input_uids:
                continue
            last_use[t.uid] = max(last_use.get(t.uid, 0),
                                  stage_of[op.name])
    final_uid = model.final_tensor.uid
    last_use[final_uid] = S - 1

    cuts: List[List] = []
    by_uid = {}
    for op in model.ops:
        for t in op.outputs:
            by_uid[t.uid] = t
    batch = model.input_tensors[0].shape[0] if model.input_tensors \
        else None
    for i in range(S - 1):
        cut = [by_uid[uid] for uid, last in sorted(last_use.items())
               if stage_of[producer[uid]] <= i < last]
        for t in cut:
            # the wire microbatches dim 0: a tensor whose dim 0 is NOT
            # the batch (e.g. GroupBy's (capacity, D) expert buffers)
            # would be silently reinterpreted sample-wise
            if batch is not None and (not t.shape
                                      or t.shape[0] != batch):
                raise NotImplementedError(
                    f"graph pipeline: tensor {t.uid} "
                    f"(shape {t.shape}, producer "
                    f"{producer[t.uid]!r}) crosses the stage-"
                    f"{i}/{i + 1} boundary but its dim 0 is not the "
                    f"batch dim ({batch}); cut elsewhere")
        cuts.append(cut)
    return StagePlan(stages=stages, stage_of=dict(stage_of), cuts=cuts)


def balanced_stages(model, num_stages: int) -> Dict[str, int]:
    """Flops-balanced contiguous auto-cut: partition the topological op
    order into `num_stages` segments minimizing the max per-stage flops
    (linear-partition DP). The searchable analog of the reference's
    hand-chosen per-layer placements."""
    ops = model.ops
    n = len(ops)
    S = min(num_stages, n)
    costs = [max(float(op.flops()), 1.0) for op in ops]
    prefix = np.concatenate([[0.0], np.cumsum(costs)])

    def seg(i, j):  # cost of ops[i:j]
        return prefix[j] - prefix[i]

    INF = float("inf")
    # dp[k][j] = best max-stage-cost splitting ops[:j] into k stages
    dp = [[INF] * (n + 1) for _ in range(S + 1)]
    cut = [[0] * (n + 1) for _ in range(S + 1)]
    dp[0][0] = 0.0
    for k in range(1, S + 1):
        for j in range(k, n + 1):
            for i in range(k - 1, j):
                c = max(dp[k - 1][i], seg(i, j))
                if c < dp[k][j]:
                    dp[k][j] = c
                    cut[k][j] = i
    bounds = [n]
    j = n
    for k in range(S, 0, -1):
        j = cut[k][j]
        bounds.append(j)
    bounds.reverse()  # [0, c1, ..., n]
    stage_of = {}
    for s in range(S):
        for op in ops[bounds[s]:bounds[s + 1]]:
            stage_of[op.name] = s
    return stage_of


def assignment_from_pins(model, strategy) -> Optional[Dict[str, int]]:
    """Derive a stage assignment from a strategy's whole-op device pins
    (length-1 `__devices__` tuples on non-embedding ops) — the
    executable lowering of reference propagate-placed strategies
    (model.cc:1807-1903). Stage order = device-id order. Unpinned ops
    inherit the latest stage among their producers. Returns None when no
    such pins exist; raises if the pins cannot form a forward pipeline
    (caller falls back to replication with the compile warning)."""
    pins = {}
    for op in model.ops:
        s = strategy.for_op(op.name)
        ids = s.device_ids
        if ids is None or op.op_type == "distributed_embedding":
            continue
        if len(set(ids)) != 1:
            raise ValueError(
                f"op {op.name!r}: multi-device pin {ids} has no "
                f"executable lowering (whole-op pins = one device id; "
                f"use axis_map sharding for intra-op splits)")
        pins[op.name] = int(ids[0])
    if not pins:
        return None
    order = sorted(set(pins.values()))
    rank = {d: i for i, d in enumerate(order)}
    producer = {}
    for op in model.ops:
        for t in op.outputs:
            producer[t.uid] = op.name
    input_uids = {t.uid for t in model.input_tensors}
    stage_of: Dict[str, int] = {}
    for op in model.ops:
        inherited = 0
        for t in op.inputs:
            if t.uid not in input_uids:
                inherited = max(inherited, stage_of[producer[t.uid]])
        stage_of[op.name] = (rank[pins[op.name]] if op.name in pins
                             else inherited)
    # pipelining is only meaningful for SEQUENTIAL placements: each
    # consecutive stage pair must be bridged by a real data edge
    # (producer in stage i feeding a consumer in stage i+1). Pins on
    # parallel SIBLING branches (e.g. DLRM's independent per-table
    # embeddings round-robined over devices) express concurrency, not
    # a pipeline — serializing them into stages would slow them down;
    # they fall back to the simulator's per-device concurrency pricing
    # (and, for embeddings, the distributed_embedding slot layout is
    # the executable form).
    S = max(stage_of.values()) + 1
    if S > 1:
        bridged = [False] * (S - 1)
        for op in model.ops:
            dst = stage_of[op.name]
            for t in op.inputs:
                if t.uid in input_uids:
                    continue
                src = stage_of[producer[t.uid]]
                if src == dst - 1:
                    bridged[src] = True
        if not all(bridged):
            gap = bridged.index(False)
            raise ValueError(
                f"pins do not form a sequential pipeline: no tensor "
                f"flows from stage {gap} to stage {gap + 1} (the "
                f"pinned ops are parallel siblings — placement there "
                f"means concurrency, not pipelining)")
    return stage_of


def pick_pipe_axis(mesh: Mesh, num_stages: int) -> Optional[str]:
    """Mesh axis to pipeline over: prefer an axis literally named
    'pipe'/'layer' of the right size, else any non-'data' axis whose
    size equals the stage count."""
    if mesh is None:
        return None
    for name in ("pipe", "layer"):
        if mesh.shape.get(name) == num_stages:
            return name
    for name, size in mesh.shape.items():
        if name != "data" and size == num_stages:
            return name
    return None


# --------------------------------------------------------------------------
# parameter flat-packing
# --------------------------------------------------------------------------

@dataclasses.dataclass
class _Segment:
    stage: int
    dtype: str
    offset: int
    size: int
    shape: Tuple[int, ...]
    row: int = -1  # physical row in the packed array (= stage unless
    #                an interleaved layout permutes ownership)

    def __post_init__(self):
        if self.row < 0:
            self.row = self.stage


@dataclasses.dataclass
class PackSpec:
    """Layout of per-stage flat-packed parameters.

    Packed form: {dtype_str: (S, L_dtype)} — one row per stage
    (weights flattened, concatenated, zero-padded to the longest
    stage). Sharded P(pipe, None): each device holds its rows, so
    weights (and elementwise-optimizer state, which mirrors the packed
    tree) physically reside on their pinned device.

    Interleaved layouts (virtual_stages v > 1 over D devices): stage s
    lives on device s % D (round-robin — every pipeline hop is a ring
    neighbor), but NamedSharding blocks rows contiguously per device,
    so stages pack in DEVICE-MAJOR row order: row(s) = (s % D) * v +
    s // D. Device d then owns rows [d*v, (d+1)*v) = its stages
    {d, d+D, ...}.
    """

    segments: Dict[Tuple[str, str], _Segment]  # (op, weight) -> segment
    lengths: Dict[str, int]                    # dtype -> L
    num_stages: int
    virtual_stages: int = 1

    def row_layout(self, stage: int) -> List[Tuple[str, str, _Segment]]:
        return [(op, w, seg) for (op, w), seg in self.segments.items()
                if seg.stage == stage]


def make_pack_spec(plan: StagePlan, n_dev: Optional[int] = None,
                   specs_of=None, pad_to: int = 1) -> PackSpec:
    """Flat-pack layout for per-stage tensors. `specs_of` selects what
    packs (default: weight_specs; pass `lambda op: op.state_specs()`
    for the functional-state rows BatchNorm et al. carry). `pad_to`
    rounds each dtype's row length up to a multiple — set to the data
    axis size so ZeRO can shard the optimizer rows' L dimension."""
    if specs_of is None:
        specs_of = lambda op: op.weight_specs()  # noqa: E731
    S = plan.num_stages
    v = 1
    if n_dev is not None and n_dev > 0 and S != n_dev:
        if S % n_dev != 0:
            # a truncated v would map two stages onto one packed row
            # and silently overwrite weights
            raise ValueError(
                f"{S} stages do not divide over {n_dev} devices")
        v = S // n_dev

    def row_of(s: int) -> int:
        return (s % n_dev) * v + s // n_dev if v > 1 else s

    segments: Dict[Tuple[str, str], _Segment] = {}
    lengths: Dict[str, int] = {}
    for s, ops in enumerate(plan.stages):
        offsets: Dict[str, int] = {}
        for op in ops:
            for wname, spec in specs_of(op).items():
                dt = np.dtype(spec.dtype).name
                size = int(np.prod(spec.shape)) if spec.shape else 1
                off = offsets.get(dt, 0)
                segments[(op.name, wname)] = _Segment(
                    stage=s, dtype=dt, offset=off, size=size,
                    shape=tuple(spec.shape), row=row_of(s))
                offsets[dt] = off + size
        for dt, end in offsets.items():
            lengths[dt] = max(lengths.get(dt, 0), end)
    if not lengths:  # weightless graph: keep one dummy lane so the
        lengths["float32"] = 1  # packed tree / optimizer state is non-empty
    if pad_to > 1:
        lengths = {dt: -(-L // pad_to) * pad_to
                   for dt, L in lengths.items()}
    return PackSpec(segments=segments, lengths=lengths,
                    num_stages=S, virtual_stages=v)


def pack_params(spec: PackSpec, params_by_op: Dict[str, Dict[str, np.ndarray]]):
    """Host-side: {op: {w: array}} -> {dtype: (S, L) ndarray}."""
    packed = {dt: np.zeros((spec.num_stages, L), dtype=dt)
              for dt, L in spec.lengths.items()}
    for (opn, wn), seg in spec.segments.items():
        arr = np.asarray(params_by_op[opn][wn]).reshape(-1)
        packed[seg.dtype][seg.row, seg.offset:seg.offset + seg.size] = arr
    return packed


def unpack_stage(spec: PackSpec, packed_row: Dict[str, jax.Array],
                 stage: int) -> Dict[str, Dict[str, jax.Array]]:
    """Trace-time: slice one stage's weights out of its packed row
    ({dtype: (L,)}). `stage` is static (each switch branch closes over
    its own)."""
    out: Dict[str, Dict[str, jax.Array]] = {}
    for opn, wn, seg in spec.row_layout(stage):
        flat = lax.dynamic_slice_in_dim(packed_row[seg.dtype],
                                        seg.offset, seg.size)
        out.setdefault(opn, {})[wn] = flat.reshape(seg.shape)
    return out


def update_stage_row(spec: PackSpec, row: Dict[str, jax.Array],
                     stage: int, by_op: Dict[str, Dict[str, jax.Array]]
                     ) -> Dict[str, jax.Array]:
    """Trace-time: write per-op entries (e.g. ctx.state_out) back into
    one stage's packed row ({dtype: (L,)}). `stage` is static."""
    out = dict(row)
    for opn, wn, seg in spec.row_layout(stage):
        val = by_op.get(opn, {}).get(wn)
        if val is None:
            continue
        out[seg.dtype] = lax.dynamic_update_slice_in_dim(
            out[seg.dtype],
            val.reshape(-1).astype(out[seg.dtype].dtype),
            seg.offset, axis=0)
    return out


def read_op_weights(spec: PackSpec, packed, op_name: str):
    """Host-side view of one op's weights out of the packed arrays."""
    out = {}
    for (opn, wn), seg in spec.segments.items():
        if opn != op_name:
            continue
        row = np.asarray(packed[seg.dtype][seg.row])
        out[wn] = row[seg.offset:seg.offset + seg.size].reshape(seg.shape)
    return out


def write_op_weights(spec: PackSpec, packed, op_name: str,
                     weights: Dict[str, np.ndarray]):
    """Return a new packed dict with `op_name`'s weights replaced."""
    host = {dt: np.asarray(a).copy() for dt, a in packed.items()}
    for wn, arr in weights.items():
        seg = spec.segments.get((op_name, wn))
        if seg is None:
            raise KeyError(
                f"{op_name!r} has no weight {wn!r} in the stage packing")
        a = np.asarray(arr)
        if tuple(a.shape) != seg.shape:
            raise ValueError(
                f"{op_name}.{wn}: shape {a.shape} != declared {seg.shape}")
        host[seg.dtype][seg.row,
                        seg.offset:seg.offset + seg.size] = \
            a.astype(host[seg.dtype].dtype, copy=False).reshape(-1)
    return host


# --------------------------------------------------------------------------
# wire (inter-stage hop buffer)
# --------------------------------------------------------------------------

def _wire_layouts(plan: StagePlan, model=None):
    """Per-cut flat layout and per-dtype max hop width. The wire is one
    {dtype: (W,)} buffer: every device sends/receives the same shapes
    (SPMD), each interprets its own cut's layout.

    Under an active compute_dtype policy (core/precision.py) FLOAT cut
    tensors ride the wire at the compute dtype: stage activations are
    already compute-dtype inside the stage, and an f32 wire would both
    double the hop bytes and silently upcast the downstream stage's
    whole compute (ops follow their input dtype)."""
    from ..core import precision as MP
    wire_dt = None
    if model is not None and MP.policy_active(model.config):
        wire_dt = np.dtype(model.config.compute_dtype).name
    layouts = []
    widths: Dict[str, int] = {}
    for cut in plan.cuts:
        lay = []
        offsets: Dict[str, int] = {}
        for t in cut:
            dt = np.dtype(t.dtype).name
            if wire_dt is not None and jnp.issubdtype(jnp.dtype(dt),
                                                      jnp.floating):
                dt = wire_dt
            size = int(np.prod(t.shape[1:]))  # per-sample; dim0 = batch
            off = offsets.get(dt, 0)
            lay.append((t.uid, dt, off, size, tuple(t.shape[1:])))
            offsets[dt] = off + size
        for dt, end in offsets.items():
            widths[dt] = max(widths.get(dt, 0), end)
        layouts.append(lay)
    if not widths:
        widths["float32"] = 1
    return layouts, widths


# --------------------------------------------------------------------------
# the pipelined forward
# --------------------------------------------------------------------------

def _make_stage_runner(plan: StagePlan, pack: PackSpec, model, layouts,
                       widths, mb_local: int, *, training: bool,
                       seq_length: int, remat: bool = False,
                       state_pack: Optional[PackSpec] = None):
    """Shared stage body for both schedules: unpack weights + incoming
    wire, run the stage's ops, emit (wire_out, final, aux,
    state_row_out). Pure compute — collectives stay at the tick level
    (SPMD-uniform across switch branches). `state_pack` carries
    functional state (BatchNorm running stats) as packed per-stage
    rows, updated in place each tick; without it state_row passes
    through untouched. `remat=True` wraps each stage tick in
    jax.checkpoint so the GPipe backward recomputes stage activations
    from the saved tick inputs instead of storing every intermediate —
    most of 1F1B's activation savings without the interleaved schedule
    (the 1F1B path recomputes inherently and must NOT also remat)."""
    S = plan.num_stages
    final_t = model.final_tensor
    name_of_input = {t.name: t.uid for t in model.input_tensors}
    # mixed-precision policy: stage weights unpack from their (f32)
    # master rows and are cast to compute_dtype per tick, INSIDE the
    # (possibly vjp'd) stage body — cotangents upcast at the cast, so
    # 1F1B's explicit per-stage gradients and GPipe's autodiff
    # transpose both accumulate into f32 packed rows. Float microbatch
    # inputs cast the same way; the wire already carries compute-dtype
    # activations (_wire_layouts).
    from ..core import precision as MP
    mp_dtype = (jnp.dtype(model.config.compute_dtype)
                if MP.policy_active(model.config) else None)

    def run_stage(s: int, row: Dict[str, jax.Array],
                  wire_in: Dict[str, jax.Array],
                  mb_in: Dict[str, jax.Array], mb_rng,
                  state_row: Optional[Dict[str, jax.Array]] = None):
        if state_row is None:
            state_row = {}
        if remat and training and mb_rng is not None:
            # prevent_cse=False: the CSE-prevention barriers exist for
            # remat OUTSIDE scans; inside the tick lax.scan they only
            # block fusion (per the jax.checkpoint docs)
            return jax.checkpoint(functools.partial(_stage_core, s),
                                  prevent_cse=False)(
                row, wire_in, mb_in, mb_rng, state_row)
        return _stage_core(s, row, wire_in, mb_in, mb_rng, state_row)

    def _stage_core(s: int, row: Dict[str, jax.Array],
                    wire_in: Dict[str, jax.Array],
                    mb_in: Dict[str, jax.Array], mb_rng,
                    state_row: Dict[str, jax.Array]):
        values: Dict[int, jax.Array] = {}
        for name, v in mb_in.items():
            if mp_dtype is not None and MP.is_float_array(v) \
                    and v.dtype != mp_dtype:
                v = v.astype(mp_dtype)
            values[name_of_input[name]] = v
        if s > 0:
            for uid, dt, off, size, shape in layouts[s - 1]:
                flat = lax.dynamic_slice_in_dim(
                    wire_in[dt], off * mb_local, size * mb_local)
                values[uid] = flat.reshape((mb_local,) + shape)
        params_s = unpack_stage(pack, row, s)
        if mp_dtype is not None:
            params_s = MP.cast_floats(params_s, mp_dtype)
        states_s = (unpack_stage(state_pack, state_row, s)
                    if state_pack is not None else {})
        state_updates: Dict[str, Dict[str, jax.Array]] = {}
        aux = jnp.float32(0.0)
        for i, op in enumerate(plan.stages[s]):
            ctx = OpContext(
                training=training,
                rng=(jax.random.fold_in(mb_rng, i)
                     if mb_rng is not None else None),
                seq_length=seq_length,
                state_in=states_s.get(op.name, {}),
                mesh=None, op_strategy=None)
            xs = [values[t.uid] for t in op.inputs]
            ys = op.forward(params_s.get(op.name, {}), xs, ctx)
            if mp_dtype is not None:
                # value stream stays compute-dtype (dtype-pinning ops
                # like Embedding would upcast the rest of the stage —
                # mirror of the base executor's walk)
                ys = [y.astype(mp_dtype)
                      if MP.is_float_array(y) and y.dtype != mp_dtype
                      else y for y in ys]
            for t, y in zip(op.outputs, ys):
                values[t.uid] = y
            if ctx.aux_loss is not None:
                aux = aux + ctx.aux_loss
            if ctx.state_out:
                state_updates[op.name] = ctx.state_out
        state_row_out = (update_stage_row(state_pack, state_row, s,
                                          state_updates)
                         if state_pack is not None and state_updates
                         else state_row)
        wire_out = {dt: jnp.zeros((w * mb_local,), dtype=dt)
                    for dt, w in widths.items()}
        if s < S - 1:
            for uid, dt, off, size, shape in layouts[s]:
                wire_out[dt] = lax.dynamic_update_slice_in_dim(
                    wire_out[dt],
                    values[uid].reshape(-1).astype(wire_out[dt].dtype),
                    off * mb_local, axis=0)
        if s == S - 1:
            # declared dtype, not the compute dtype: every lax.switch
            # branch must return identical types, and the non-final
            # stages emit final_t.dtype zeros
            final = values[final_t.uid].astype(final_t.dtype)
        else:
            final = jnp.zeros((mb_local,) + tuple(final_t.shape[1:]),
                              dtype=final_t.dtype)
        return wire_out, final, aux, state_row_out

    return run_stage


def _data_split(mesh: Mesh, data_axis: Optional[str], mb: int):
    """(data_ax or None, n_data, mb_local): microbatches shard over the
    data axis inside each stage when divisible, else replicate."""
    data_ax = data_axis if (data_axis and data_axis in mesh.shape) else None
    ndata = mesh.shape[data_ax] if data_ax else 1
    if mb % ndata != 0:
        data_ax, ndata = None, 1
    return data_ax, ndata, mb // ndata


def pipeline_logits(plan: StagePlan, pack: PackSpec, packed,
                    inputs: Dict[str, jax.Array], rng, mesh: Mesh,
                    pipe_axis: str, data_axis: Optional[str],
                    num_microbatches: int, model, *, training: bool,
                    seq_length: int = -1, schedule: str = "gpipe",
                    state_pack: Optional[PackSpec] = None,
                    state_packed=None):
    """Run the staged graph pipelined over `pipe_axis`; returns
    (logits (B, ...), aux_loss scalar, new_state_packed).

    `state_pack`/`state_packed` carry functional state (BatchNorm
    running stats) as {dtype: (S, L)} rows sharded like the weights;
    each stage's forward tick updates its row in microbatch order —
    gradient-accumulation semantics. On a data axis every shard
    computes LOCAL batch statistics (standard DDP BatchNorm behavior)
    and the returned rows are the mean over data shards.

    GPipe schedule, M microbatches over S stages: tick t has stage s
    computing microbatch t - s; activations hop via ppermute. Backward
    is the autodiff transpose (a reverse pipeline). Bubble fraction
    (S-1)/(M+S-1) forward, same again backward — `simulate_step_scaling`
    predicts step-time scaling, tests hold measurements against it.
    The 1F1B schedule lives in `pipeline_1f1b_grads` (it computes
    gradients directly instead of relying on the autodiff transpose).
    """
    S = plan.num_stages
    M = int(num_microbatches)
    if schedule != "gpipe":
        raise ValueError(
            f"pipeline_logits runs the gpipe schedule; use "
            f"pipeline_1f1b_grads for 1F1B (got {schedule!r})")
    final_t = model.final_tensor
    B = next(iter(inputs.values())).shape[0]
    if B % M != 0:
        raise ValueError(f"batch {B} not divisible by {M} microbatches")
    mb = B // M
    layouts, widths = _wire_layouts(plan, model)

    # (B, ...) -> (M, mb, ...)
    inputs_mb = {k: v.reshape((M, mb) + v.shape[1:])
                 for k, v in inputs.items()}

    data_ax, ndata, mb_local = _data_split(mesh, data_axis, mb)
    run_stage = _make_stage_runner(
        plan, pack, model, layouts, widths, mb_local,
        training=training, seq_length=seq_length,
        remat=bool(getattr(model.config, "remat", False)),
        state_pack=state_pack)
    has_state = state_pack is not None and state_packed is not None
    if state_packed is None:
        state_packed = {}

    def local_fn(packed_local, inputs_local, state_local, rng_op):
        # packed_local: {dt: (1, L)}; inputs_local: {name: (M, mb_l, ...)}
        idx = lax.axis_index(pipe_axis)
        row = {dt: a[0] for dt, a in packed_local.items()}
        st_row0 = {dt: a[0] for dt, a in state_local.items()}
        branches = [functools.partial(run_stage, s) for s in range(S)]

        def tick(carry, t):
            wire, outputs, aux_acc, st_row = carry
            mb_idx = jnp.clip(t - idx, 0, M - 1)
            mb_in = {k: lax.dynamic_index_in_dim(v, mb_idx,
                                                 keepdims=False)
                     for k, v in inputs_local.items()}
            mb_rng = (jax.random.fold_in(rng_op, mb_idx)
                      if rng_op is not None else None)
            wire_out, final, aux, st_new = lax.switch(
                idx, branches, row, wire, mb_in, mb_rng, st_row)
            valid = jnp.logical_and(t - idx >= 0, t - idx < M)
            aux_acc = aux_acc + jnp.where(valid, aux, 0.0)
            # state updates only on valid ticks (warmup/drain garbage
            # microbatches must not touch running stats)
            st_row = {dt: jnp.where(valid, st_new[dt], st_row[dt])
                      for dt in st_row}
            perm = [(i, (i + 1) % S) for i in range(S)]
            wire_nxt = {dt: lax.ppermute(a, pipe_axis, perm)
                        for dt, a in wire_out.items()}
            done_idx = t - (S - 1)
            write = jnp.logical_and(idx == S - 1, done_idx >= 0)
            safe = jnp.clip(done_idx, 0, M - 1)
            cur = lax.dynamic_index_in_dim(outputs, safe, keepdims=False)
            outputs = lax.dynamic_update_index_in_dim(
                outputs, jnp.where(write, final, cur), safe, 0)
            return (wire_nxt, outputs, aux_acc, st_row), None

        wire0 = {dt: jnp.zeros((w * mb_local,), dtype=dt)
                 for dt, w in widths.items()}
        outputs0 = jnp.zeros(
            (M, mb_local) + tuple(final_t.shape[1:]),
            dtype=final_t.dtype)
        (_, outputs, aux_acc, st_row), _ = lax.scan(
            tick, (wire0, outputs0, jnp.float32(0.0), st_row0),
            jnp.arange(M + S - 1))
        outputs = lax.psum(
            jnp.where(idx == S - 1, outputs, jnp.zeros_like(outputs)),
            pipe_axis)
        # aux: mean over (microbatches x data shards). Averaging over
        # the data axis too keeps the P() aux output genuinely uniform —
        # each data shard sees different samples, and a per-shard value
        # declared replicated is undefined under check_vma=False
        aux_total = lax.psum(
            aux_acc, (pipe_axis,) if data_ax is None
            else (pipe_axis, data_ax)) / (M * ndata)
        # state rows: per-data-shard local statistics (DDP BatchNorm
        # behavior) mean-reduced over the data axis so the returned
        # rows are deterministic and replica-uniform
        if data_ax is not None:
            st_row = {dt: lax.pmean(a, data_ax)
                      for dt, a in st_row.items()}
        st_out = {dt: a[None] for dt, a in st_row.items()}
        return outputs, aux_total, st_out

    packed_spec = {dt: P(pipe_axis, None) for dt in packed}
    state_spec = {dt: P(pipe_axis, None) for dt in state_packed}
    in_spec = {k: P(None, data_ax, *([None] * (v.ndim - 2)))
               for k, v in inputs_mb.items()}
    out_spec = P(None, data_ax,
                 *([None] * (len(final_t.shape) - 1)))

    out, aux, st = shard_map(
        local_fn, mesh=mesh,
        in_specs=(packed_spec, in_spec, state_spec, P()),
        out_specs=(out_spec, P(), state_spec),
        check_vma=False)(packed, inputs_mb, state_packed, rng)
    logits = out.reshape((B,) + tuple(final_t.shape[1:]))
    return logits, aux, (st if has_state else None)


# --------------------------------------------------------------------------
# 1F1B schedule
# --------------------------------------------------------------------------

IDLE, FWD, BWD = 0, 1, 2


def _ring_depth(fwd_done, consume_done, S: int, M: int, start: int,
                what: str) -> int:
    """Smallest safe activation ring-buffer depth for a generated
    schedule. The hazard is the ARRIVAL tick: act(m2) lands in stage
    s's buffer one tick after fwd(s-1, m2) runs (not when fwd(s, m2)
    runs), so slot m2 % depth must not be overwritten before the
    consumer has used act(m) — consumption is bwd(s, m) for training
    schedules, fwd(s, m) for forward-only ones."""
    def conflict_free(dep: int) -> bool:
        for s in range(1, S):  # stage 0 takes no wire arrivals
            for m in range(M):
                for m2 in range(m + 1, M):
                    if m2 % dep != m % dep:
                        continue
                    if fwd_done[s - 1][m2] + 1 <= consume_done[s][m]:
                        return False
        return True

    depth = max(1, start)
    while depth < M and not conflict_free(depth):
        depth += 1
    if not conflict_free(depth):
        raise AssertionError(
            f"{what} has no conflict-free ring depth <= {M}")
    return depth


def _arrival_tables(kind, mbi, sidx, n_dev: int, S: int):
    """Per-(tick, device) wire-arrival tables (-1 mb = nothing
    arrived): stage s running fwd(m) at t-1 puts act(m) on stage s+1's
    device ((s+1) % n_dev — a +1 ring neighbor by the round-robin
    layout) at tick t, landing in that stage's chunk ((s+1) // n_dev)
    buffer; bwd cotangents mirror on the -1 ring. Forward-only
    schedules simply leave the bwd tables empty."""
    T = kind.shape[0]
    arr_f = np.full((T, n_dev), -1, np.int32)
    arrc_f = np.zeros((T, n_dev), np.int32)
    arr_b = np.full((T, n_dev), -1, np.int32)
    arrc_b = np.zeros((T, n_dev), np.int32)
    for t in range(1, T):
        for d in range(n_dev):
            s = int(sidx[t - 1, d])
            if kind[t - 1, d] == FWD and s < S - 1:
                rd = (s + 1) % n_dev
                arr_f[t, rd] = mbi[t - 1, d]
                arrc_f[t, rd] = (s + 1) // n_dev
            elif kind[t - 1, d] == BWD and s > 0:
                rd = (s - 1) % n_dev
                arr_b[t, rd] = mbi[t - 1, d]
                arrc_b[t, rd] = (s - 1) // n_dev
    return arr_f, arrc_f, arr_b, arrc_b


def _ring_io(widths, mb_local: int, depth: int, v: int, M: int):
    """(zero_wire, slot, deposit) helpers shared by the interleaved
    training and forward-only tick loops: the uniform wire buffer, the
    flat (chunk, microbatch) ring-buffer slot, and the arrival deposit
    keyed by the static tables."""
    def zero_wire():
        return {dt: jnp.zeros((w * mb_local,), dtype=dt)
                for dt, w in widths.items()}

    def slot(chunk, m):
        return chunk * depth + m % depth

    def deposit(buf, wire, m_arrived, chunk_arrived):
        ok = m_arrived >= 0
        sl = jnp.clip(chunk_arrived, 0, v - 1) * depth \
            + jnp.clip(m_arrived, 0, M - 1) % depth
        out = {}
        for dt, a in buf.items():
            cur = lax.dynamic_index_in_dim(a, sl, keepdims=False)
            upd = jnp.where(ok, wire[dt], cur)
            out[dt] = lax.dynamic_update_index_in_dim(a, upd, sl, 0)
        return out

    return zero_wire, slot, deposit


def one_f_one_b_schedule(S: int, M: int):
    """Plain (non-interleaved) 1F1B: the v=1 case of
    `interleaved_schedule`, kept as the historical entry point —
    one stage per device, kind/mbi tables only."""
    kind, mbi, _sidx, _depth = interleaved_schedule(S, 1, M)
    return kind, mbi


def interleaved_schedule(n_dev: int, v: int, M: int):
    """Interleaved (virtual-stage) 1F1B: S = v * n_dev stages, stage s
    lives on device s % n_dev (round-robin, so every s -> s+1 hop is a
    +1 ring neighbor), each DEVICE runs one unit per tick. With v > 1 a
    device starts chunk c+1's forwards while chunk c waits on
    downstream, dividing the warmup/drain bubble by ~v (the Megatron
    interleaved schedule). v=1 reduces to plain 1F1B.

    Greedy event-driven generation with backward priority (memory
    bound); among ready forwards, the smallest (microbatch, stage)
    first — pushing each microbatch deep as early as possible.

    Returns (kind (T, D), mbi (T, D), sidx (T, D), depth) where sidx is
    the GLOBAL stage id worked each tick (-1 idle) and `depth` is the
    per-stage ring-buffer depth the executor must allocate (validated
    conflict-free against the schedule).
    """
    D, S = n_dev, v * n_dev
    fwd_done = [[-1] * M for _ in range(S)]
    bwd_done = [[-1] * M for _ in range(S)]
    next_f = [0] * S
    next_b = [0] * S
    kind_rows, mbi_rows, sidx_rows = [], [], []
    t = 0
    while any(nb < M for nb in next_b):
        krow = [IDLE] * D
        mrow = [-1] * D
        srow = [-1] * D
        for d in range(D):
            stages = [d + c * D for c in range(v)]
            # backward first: smallest microbatch, then DEEPEST stage
            # (its cotangent unblocks the longest chain)
            best = None
            for s in sorted(stages, reverse=True):
                m = next_b[s]
                if m >= M:
                    continue
                ready = (s == S - 1 and 0 <= fwd_done[s][m] < t) or \
                    (s < S - 1 and 0 <= bwd_done[s + 1][m] < t)
                if ready:
                    if best is None or m < best[1]:
                        best = (s, m, BWD)
            if best is None:
                # fwd in WAVES: microbatch groups of D run chunk-major
                # (chunk c's wave completes before chunk c+1's), the
                # Megatron interleaved pattern — measurably the best of
                # the policies tried (30-60% bubble reduction at v=4
                # across D/M sweeps; see test_interleaved_schedule)
                cand = []
                for s in stages:
                    m = next_f[s]
                    if m >= M or next_f[s] - next_b[s] >= max(1, S - s):
                        continue
                    if s == 0 or 0 <= fwd_done[s - 1][m] < t:
                        cand.append((m // D, s // D, m, s))
                if cand:
                    _, _, m, s = min(cand)
                    best = (s, m, FWD)
            if best is not None:
                s, m, k = best
                krow[d], mrow[d], srow[d] = k, m, s
                if k == FWD:
                    fwd_done[s][m] = t
                    next_f[s] += 1
                else:
                    bwd_done[s][m] = t
                    next_b[s] += 1
        kind_rows.append(krow)
        mbi_rows.append(mrow)
        sidx_rows.append(srow)
        t += 1
        if t > 4 * v * (M + S) + 8:
            raise AssertionError("interleaved schedule did not converge")
    # ring-buffer depth: start at the max in-flight forwards any stage
    # holds, then grow until slot-reuse is provably safe (_ring_depth;
    # consumption = the bwd tick)
    inflight = [0] * S
    peak = [0] * S
    for krow, srow in zip(kind_rows, sidx_rows):
        for k, s in zip(krow, srow):
            if k == FWD:
                inflight[s] += 1
                peak[s] = max(peak[s], inflight[s])
            elif k == BWD:
                inflight[s] -= 1
    depth = _ring_depth(
        fwd_done, bwd_done, S, M, start=max(peak),
        what=f"interleaved schedule (D={n_dev}, v={v}, M={M})")
    return (np.asarray(kind_rows, np.int32),
            np.asarray(mbi_rows, np.int32),
            np.asarray(sidx_rows, np.int32), depth)


def schedule_bubble(kind) -> float:
    """Idle fraction of the device timeline a generated schedule
    leaves (warmup + drain + dependency stalls)."""
    total = kind.size
    busy = int((kind != IDLE).sum())
    return 1.0 - busy / total


def pipeline_1f1b_grads(plan: StagePlan, pack: PackSpec, packed,
                        inputs: Dict[str, jax.Array],
                        label, loss_fn, rng, mesh: Mesh,
                        pipe_axis: str, data_axis: Optional[str],
                        num_microbatches: int, model, *,
                        seq_length: int = -1,
                        state_pack: Optional[PackSpec] = None,
                        state_packed=None):
    """One-forward-one-backward pipelined TRAINING step: returns
    (logits (B, ...), aux scalar, grads {dtype: (S, L)},
    new_state_packed).

    Functional state (BatchNorm running stats): fwd ticks run OUTSIDE
    the vjp, so state rows advance there per microbatch in order —
    identical semantics to the GPipe path — while the bwd recompute
    reads the state row as a constant and its state writes are
    discarded (in training mode gradients do not depend on state_in,
    which only feeds the running-stat momentum update).

    Unlike the GPipe path (autodiff transpose of the forward schedule),
    this computes gradients EXPLICITLY inside the tick loop: each
    stage's backward recomputes its forward from the saved input
    activation via `jax.vjp` (remat-1F1B) as soon as the downstream
    cotangent arrives, so peak live activations per stage drop from M
    microbatches to min(S - s, M). Two wires ride the ring each tick:
    activations forward (ppermute i->i+1), cotangents backward
    (ppermute i->i-1). Ring buffers of depth min(S, M) hold arrived
    activations/cotangents between their arrival tick and use tick.
    """
    S = plan.num_stages
    M = int(num_microbatches)
    final_t = model.final_tensor
    B = next(iter(inputs.values())).shape[0]
    if B % M != 0:
        raise ValueError(f"batch {B} not divisible by {M} microbatches")
    mb = B // M
    layouts, widths = _wire_layouts(plan, model)
    for dt in widths:
        # jnp.issubdtype, not np: ml_dtypes' bfloat16 is floating but
        # plain numpy's issubdtype does not know its hierarchy
        if not jnp.issubdtype(jnp.dtype(dt), jnp.floating):
            raise NotImplementedError(
                f"1F1B: non-float tensor (dtype {dt}) crosses a stage "
                f"boundary; cotangent wires need float dtypes — use "
                f"the gpipe schedule")

    inputs_mb = {k: v.reshape((M, mb) + v.shape[1:])
                 for k, v in inputs.items()}
    label_mb = (label.reshape((M, mb) + label.shape[1:])
                if label is not None else None)

    data_ax, ndata, mb_local = _data_split(mesh, data_axis, mb)
    run_stage = _make_stage_runner(
        plan, pack, model, layouts, widths, mb_local,
        training=True, seq_length=seq_length, state_pack=state_pack)
    has_state = state_pack is not None and state_packed is not None
    if state_packed is None:
        state_packed = {}

    n_dev = int(mesh.shape[pipe_axis])
    v = S // n_dev
    if S != v * n_dev:
        raise ValueError(
            f"{S} stages do not divide over the {n_dev}-device "
            f"{pipe_axis!r} axis")
    kind, mbi, sidx, depth = interleaved_schedule(n_dev, v, M)
    T = kind.shape[0]
    arr_f, arrc_f, arr_b, arrc_b = _arrival_tables(
        kind, mbi, sidx, n_dev, S)
    # branch index per (tick, device): 0 idle, 1+s fwd(s), 1+S+s bwd(s)
    bidx = np.where(kind == IDLE, 0,
                    np.where(kind == FWD, 1 + sidx, 1 + S + sidx))

    kind_a = jnp.asarray(kind)
    mbi_a = jnp.asarray(mbi)
    sidx_a = jnp.asarray(sidx)
    arr_f_a = jnp.asarray(arr_f)
    arrc_f_a = jnp.asarray(arrc_f)
    arr_b_a = jnp.asarray(arr_b)
    arrc_b_a = jnp.asarray(arrc_b)
    bidx_a = jnp.asarray(bidx.astype(np.int32))

    # objective scaling (matches the GPipe/autodiff path): the reported
    # loss is mean over the GLOBAL batch; each (stage, data-shard)
    # device's per-microbatch loss contributes 1/(M * ndata); aux
    # contributes 1/M per device (psum'd over pipe only)
    loss_scale = 1.0 / (M * ndata)
    # aux averages over data shards too (the GPipe path psums aux over
    # (pipe, data) and divides by M*ndata — grads must match)
    aux_scale = 1.0 / (M * ndata)

    _zero_wire, slot, _deposit = _ring_io(widths, mb_local, depth, v, M)

    def local_fn(packed_local, inputs_local, state_local, rng_op,
                 label_local):
        idx = lax.axis_index(pipe_axis)
        # packed_local: {dt: (v, L)} — this device's chunk rows in
        # device-major order; stage s (s % n_dev == this device) reads
        # local row s // n_dev
        rows = packed_local

        def mb_inputs_at(m):
            return {k: lax.dynamic_index_in_dim(v_, m, keepdims=False)
                    for k, v_ in inputs_local.items()}

        def st_stage(st, c):
            return {dt: a[c] for dt, a in st.items()}

        def fwd_branch(s, rows, act_buf, ct_buf, wire_f, wire_b, m,
                       mb_rng, gacc, st):
            c = s // n_dev
            row = {dt: a[c] for dt, a in rows.items()}
            mb_in = mb_inputs_at(m)
            wire_in = {dt: lax.dynamic_index_in_dim(
                act_buf[dt], slot(c, m), keepdims=False)
                for dt in act_buf}
            wire_out, final, aux, st_new = run_stage(
                s, row, wire_in, mb_in, mb_rng,
                state_row=st_stage(st, c))
            st = {dt: st[dt].at[c].set(st_new[dt]) for dt in st}
            return wire_out, _zero_wire(), final, gacc, aux, st

        def bwd_branch(s, rows, act_buf, ct_buf, wire_f, wire_b, m,
                       mb_rng, gacc, st):
            c = s // n_dev
            row = {dt: a[c] for dt, a in rows.items()}
            mb_in = mb_inputs_at(m)
            wire_in = {dt: lax.dynamic_index_in_dim(
                act_buf[dt], slot(c, m), keepdims=False)
                for dt in act_buf}
            # the recompute reads state as a CONSTANT (no grad flows
            # through running stats in training mode); its state
            # writes are discarded — fwd ticks own the state advance
            st_c = st_stage(st, c)
            if s == S - 1:
                def objective(r, w):
                    _wire_o, final, aux, _st = run_stage(
                        s, r, w, mb_in, mb_rng, state_row=st_c)
                    obj = aux_scale * aux
                    if loss_fn is not None and label_local is not None:
                        lbl = lax.dynamic_index_in_dim(
                            label_local, m, keepdims=False)
                        obj = obj + loss_scale * loss_fn(final, lbl)
                    return obj
                _obj, pull = jax.vjp(objective, row, wire_in)
                d_row, d_wire = pull(jnp.float32(1.0))
            else:
                def emit(r, w):
                    wire_o, _final, aux, _st = run_stage(
                        s, r, w, mb_in, mb_rng, state_row=st_c)
                    return wire_o, aux
                _out, pull = jax.vjp(emit, row, wire_in)
                ct_wire = {dt: lax.dynamic_index_in_dim(
                    ct_buf[dt], slot(c, m), keepdims=False)
                    for dt in ct_buf}
                d_row, d_wire = pull((ct_wire,
                                      jnp.float32(aux_scale)))
            gacc = {dt: gacc[dt].at[c].add(
                d_row[dt].astype(gacc[dt].dtype)) for dt in gacc}
            final0 = jnp.zeros((mb_local,) + tuple(final_t.shape[1:]),
                               dtype=final_t.dtype)
            return (_zero_wire(), d_wire, final0, gacc,
                    jnp.float32(0.0), st)

        def idle_branch(rows, act_buf, ct_buf, wire_f, wire_b, m,
                        mb_rng, gacc, st):
            final0 = jnp.zeros((mb_local,) + tuple(final_t.shape[1:]),
                               dtype=final_t.dtype)
            return (_zero_wire(), _zero_wire(), final0, gacc,
                    jnp.float32(0.0), st)

        branches = ([idle_branch]
                    + [functools.partial(fwd_branch, s)
                       for s in range(S)]
                    + [functools.partial(bwd_branch, s)
                       for s in range(S)])

        def tick(carry, t):
            (act_buf, ct_buf, wire_f, wire_b, gacc, outputs, aux_acc,
             st) = carry
            # deposit arrivals into the (chunk, mb) ring buffers
            act_buf = _deposit(act_buf, wire_f, arr_f_a[t, idx],
                               arrc_f_a[t, idx])
            ct_buf = _deposit(ct_buf, wire_b, arr_b_a[t, idx],
                              arrc_b_a[t, idx])

            m = mbi_a[t, idx]
            safe_m = jnp.clip(m, 0, M - 1)
            mb_rng = (jax.random.fold_in(rng_op, safe_m)
                      if rng_op is not None else None)
            b = bidx_a[t, idx]
            wire_f_out, wire_b_out, final, gacc, aux, st = lax.switch(
                b, branches, rows, act_buf, ct_buf, wire_f, wire_b,
                safe_m, mb_rng, gacc, st)

            # every 1F1B fwd tick is real work (idle replaces the
            # GPipe warmup garbage), so fwd-tick aux sums are exact
            aux_acc = aux_acc + aux
            k = kind_a[t, idx]
            is_last_fwd = jnp.logical_and(k == FWD,
                                          sidx_a[t, idx] == S - 1)
            outputs = _write_mb(outputs, final, safe_m, is_last_fwd)

            fperm = [(i, (i + 1) % n_dev) for i in range(n_dev)]
            bperm = [(i, (i - 1) % n_dev) for i in range(n_dev)]
            wire_f = {dt: lax.ppermute(a, pipe_axis, fperm)
                      for dt, a in wire_f_out.items()}
            wire_b = {dt: lax.ppermute(a, pipe_axis, bperm)
                      for dt, a in wire_b_out.items()}
            return (act_buf, ct_buf, wire_f, wire_b, gacc, outputs,
                    aux_acc, st), None

        def _write_mb(outputs, final, m, flag):
            cur = lax.dynamic_index_in_dim(outputs, m, keepdims=False)
            upd = jnp.where(flag, final, cur)
            return lax.dynamic_update_index_in_dim(outputs, upd, m, 0)

        zw = _zero_wire()
        act_buf0 = {dt: jnp.zeros((v * depth,) + a.shape, a.dtype)
                    for dt, a in zw.items()}
        ct_buf0 = {dt: jnp.zeros_like(a) for dt, a in act_buf0.items()}
        gacc0 = {dt: jnp.zeros((v, L), dtype=packed_local[dt].dtype)
                 for dt, L in pack.lengths.items()}
        outputs0 = jnp.zeros((M, mb_local) + tuple(final_t.shape[1:]),
                             dtype=final_t.dtype)
        (_, _, _, _, gacc, outputs, aux_acc, st_rows), _ = lax.scan(
            tick, (act_buf0, ct_buf0, zw, dict(zw), gacc0, outputs0,
                   jnp.float32(0.0), state_local),
            jnp.arange(T))
        # the last stage lives on the last device (S-1 = v*n_dev-1)
        outputs = lax.psum(
            jnp.where(idx == n_dev - 1, outputs,
                      jnp.zeros_like(outputs)),
            pipe_axis)
        aux_total = lax.psum(
            aux_acc, (pipe_axis,) if data_ax is None
            else (pipe_axis, data_ax)) / (M * ndata)
        # weight grads: each device owns its chunk rows; replicas
        # across the data axis hold partial sums -> reduce there
        if data_ax is not None:
            gacc = {dt: lax.psum(a, data_ax) for dt, a in gacc.items()}
            # state rows: per-shard local stats (DDP BatchNorm) ->
            # deterministic replica-uniform mean, same as GPipe
            st_rows = {dt: lax.pmean(a, data_ax)
                       for dt, a in st_rows.items()}
        return outputs, aux_total, gacc, st_rows

    packed_spec = {dt: P(pipe_axis, None) for dt in packed}
    state_spec = {dt: P(pipe_axis, None) for dt in state_packed}
    in_spec = {k: P(None, data_ax, *([None] * (v.ndim - 2)))
               for k, v in inputs_mb.items()}
    lbl_spec = (P(None, data_ax,
                  *([None] * (label_mb.ndim - 2)))
                if label_mb is not None else P())
    out_spec = P(None, data_ax, *([None] * (len(final_t.shape) - 1)))
    grad_spec = {dt: P(pipe_axis, None) for dt in packed}

    outputs, aux, grads, st = shard_map(
        local_fn, mesh=mesh,
        in_specs=(packed_spec, in_spec, state_spec, P(), lbl_spec),
        out_specs=(out_spec, P(), grad_spec, state_spec),
        check_vma=False)(packed, inputs_mb, state_packed, rng,
                         label_mb)
    logits = outputs.reshape((B,) + tuple(final_t.shape[1:]))
    return logits, aux, grads, (st if has_state else None)


def interleaved_forward_schedule(n_dev: int, v: int, M: int):
    """Forward-only interleaved schedule (eval/predict under virtual
    stages): same wave policy as `interleaved_schedule` minus the
    backward units and the in-flight memory cap — eval stores no
    activations for a backward, so microbatches stream as fast as the
    ring delivers them. Returns (kind (T, D), mbi, sidx, depth) with
    the same conventions (kind is FWD or IDLE only).
    """
    D, S = n_dev, v * n_dev
    fwd_done = [[-1] * M for _ in range(S)]
    next_f = [0] * S
    kind_rows, mbi_rows, sidx_rows = [], [], []
    t = 0
    while any(nf < M for nf in next_f):
        krow = [IDLE] * D
        mrow = [-1] * D
        srow = [-1] * D
        for d in range(D):
            stages = [d + c * D for c in range(v)]
            cand = []
            for s in stages:
                m = next_f[s]
                if m >= M:
                    continue
                if s == 0 or 0 <= fwd_done[s - 1][m] < t:
                    cand.append((m // D, s // D, m, s))
            if cand:
                _, _, m, s = min(cand)
                krow[d], mrow[d], srow[d] = FWD, m, s
                fwd_done[s][m] = t
                next_f[s] += 1
        kind_rows.append(krow)
        mbi_rows.append(mrow)
        sidx_rows.append(srow)
        t += 1
        if t > 4 * v * (M + S) + 8:
            raise AssertionError(
                "interleaved forward schedule did not converge")
    # forward-only consumption is the fwd tick itself
    depth = _ring_depth(
        fwd_done, fwd_done, S, M, start=1,
        what=f"forward schedule (D={n_dev}, v={v}, M={M})")
    return (np.asarray(kind_rows, np.int32),
            np.asarray(mbi_rows, np.int32),
            np.asarray(sidx_rows, np.int32), depth)


def pipeline_logits_interleaved(plan: StagePlan, pack: PackSpec, packed,
                                inputs: Dict[str, jax.Array], rng,
                                mesh: Mesh, pipe_axis: str,
                                data_axis: Optional[str],
                                num_microbatches: int, model, *,
                                training: bool, seq_length: int = -1,
                                state_pack: Optional[PackSpec] = None,
                                state_packed=None):
    """Forward-only pipelined run under an interleaved (virtual-stage)
    layout: S = v * n_dev stages, stage s on device s % n_dev, packed
    rows in device-major order (PackSpec.row_of). The eval/predict
    counterpart of `pipeline_1f1b_grads` — same tick machinery (static
    schedule tables, lax.switch branch per stage, activation ring
    buffers, +1-ring ppermute) without the backward wire. Returns
    (logits (B, ...), aux scalar)."""
    S = plan.num_stages
    M = int(num_microbatches)
    final_t = model.final_tensor
    B = next(iter(inputs.values())).shape[0]
    if B % M != 0:
        raise ValueError(f"batch {B} not divisible by {M} microbatches")
    mb = B // M
    layouts, widths = _wire_layouts(plan, model)

    inputs_mb = {k: v_.reshape((M, mb) + v_.shape[1:])
                 for k, v_ in inputs.items()}
    data_ax, ndata, mb_local = _data_split(mesh, data_axis, mb)
    run_stage = _make_stage_runner(
        plan, pack, model, layouts, widths, mb_local,
        training=training, seq_length=seq_length,
        state_pack=state_pack)
    if state_packed is None:
        state_packed = {}

    n_dev = int(mesh.shape[pipe_axis])
    v = S // n_dev
    if S != v * n_dev:
        raise ValueError(
            f"{S} stages do not divide over the {n_dev}-device "
            f"{pipe_axis!r} axis")
    kind, mbi, sidx, depth = interleaved_forward_schedule(n_dev, v, M)
    T = kind.shape[0]
    arr_f, arrc_f, _arr_b, _arrc_b = _arrival_tables(
        kind, mbi, sidx, n_dev, S)
    bidx = np.where(kind == IDLE, 0, 1 + sidx)

    kind_a = jnp.asarray(kind)
    mbi_a = jnp.asarray(mbi)
    sidx_a = jnp.asarray(sidx)
    arr_f_a = jnp.asarray(arr_f)
    arrc_f_a = jnp.asarray(arrc_f)
    bidx_a = jnp.asarray(bidx.astype(np.int32))

    _zero_wire, slot, _deposit = _ring_io(widths, mb_local, depth, v, M)

    def local_fn(packed_local, inputs_local, state_local, rng_op):
        idx = lax.axis_index(pipe_axis)
        rows = packed_local  # {dt: (v, L)} device-major chunk rows

        def fwd_branch(s, rows, act_buf, m, mb_rng):
            c = s // n_dev
            row = {dt: a[c] for dt, a in rows.items()}
            mb_in = {k: lax.dynamic_index_in_dim(v_, m, keepdims=False)
                     for k, v_ in inputs_local.items()}
            wire_in = {dt: lax.dynamic_index_in_dim(
                act_buf[dt], slot(c, m), keepdims=False)
                for dt in act_buf}
            # state is read-only here (eval/predict: BN consumes its
            # running stats; updates are dropped — no step stores them)
            wire_out, final, aux, _st = run_stage(
                s, row, wire_in, mb_in, mb_rng,
                state_row={dt: a[c] for dt, a in state_local.items()})
            return wire_out, final, aux

        def idle_branch(rows, act_buf, m, mb_rng):
            final0 = jnp.zeros((mb_local,) + tuple(final_t.shape[1:]),
                               dtype=final_t.dtype)
            return _zero_wire(), final0, jnp.float32(0.0)

        branches = [idle_branch] + [functools.partial(fwd_branch, s)
                                    for s in range(S)]

        def tick(carry, t):
            act_buf, wire_f, outputs, aux_acc = carry
            act_buf = _deposit(act_buf, wire_f, arr_f_a[t, idx],
                               arrc_f_a[t, idx])
            m = mbi_a[t, idx]
            safe_m = jnp.clip(m, 0, M - 1)
            mb_rng = (jax.random.fold_in(rng_op, safe_m)
                      if rng_op is not None else None)
            wire_out, final, aux = lax.switch(
                bidx_a[t, idx], branches, rows, act_buf, safe_m, mb_rng)
            aux_acc = aux_acc + aux  # every fwd tick is real work
            is_last = jnp.logical_and(kind_a[t, idx] == FWD,
                                      sidx_a[t, idx] == S - 1)
            cur = lax.dynamic_index_in_dim(outputs, safe_m,
                                           keepdims=False)
            outputs = lax.dynamic_update_index_in_dim(
                outputs, jnp.where(is_last, final, cur), safe_m, 0)
            fperm = [(i, (i + 1) % n_dev) for i in range(n_dev)]
            wire_f = {dt: lax.ppermute(a, pipe_axis, fperm)
                      for dt, a in wire_out.items()}
            return (act_buf, wire_f, outputs, aux_acc), None

        zw = _zero_wire()
        act_buf0 = {dt: jnp.zeros((v * depth,) + a.shape, a.dtype)
                    for dt, a in zw.items()}
        outputs0 = jnp.zeros((M, mb_local) + tuple(final_t.shape[1:]),
                             dtype=final_t.dtype)
        (_, _, outputs, aux_acc), _ = lax.scan(
            tick, (act_buf0, zw, outputs0, jnp.float32(0.0)),
            jnp.arange(T))
        # stage S-1 = v*n_dev - 1 lives on device n_dev - 1
        outputs = lax.psum(
            jnp.where(idx == n_dev - 1, outputs,
                      jnp.zeros_like(outputs)),
            pipe_axis)
        aux_total = lax.psum(
            aux_acc, (pipe_axis,) if data_ax is None
            else (pipe_axis, data_ax)) / (M * ndata)
        return outputs, aux_total

    packed_spec = {dt: P(pipe_axis, None) for dt in packed}
    state_spec = {dt: P(pipe_axis, None) for dt in state_packed}
    in_spec = {k: P(None, data_ax, *([None] * (v_.ndim - 2)))
               for k, v_ in inputs_mb.items()}
    out_spec = P(None, data_ax, *([None] * (len(final_t.shape) - 1)))

    out, aux = shard_map(
        local_fn, mesh=mesh,
        in_specs=(packed_spec, in_spec, state_spec, P()),
        out_specs=(out_spec, P()),
        check_vma=False)(packed, inputs_mb, state_packed, rng)
    return out.reshape((B,) + tuple(final_t.shape[1:])), aux


# --------------------------------------------------------------------------
# analytics
# --------------------------------------------------------------------------

def bubble_fraction(num_stages: int, num_microbatches: int) -> float:
    """GPipe bubble: idle fraction of each device's timeline."""
    S, M = num_stages, num_microbatches
    return (S - 1) / (M + S - 1)


def simulate_step_scaling(num_stages: int, m_a: int, m_b: int) -> float:
    """Predicted step-time ratio time(M=m_a)/time(M=m_b) at fixed global
    batch: per-microbatch work scales 1/M, ticks = M + S - 1, so step
    time ∝ (M + S - 1)/M. The measurable form of the bubble model (the
    sim-vs-measured agreement tests hold CPU-mesh timings against it)."""
    S = num_stages
    return ((m_a + S - 1) / m_a) / ((m_b + S - 1) / m_b)


def peak_microbatches(num_stages: int, num_microbatches: int,
                      schedule: str) -> int:
    """Peak in-flight microbatches whose activations a stage must hold:
    GPipe stores all M before backward drains; 1F1B caps at S."""
    if schedule == "1f1b":
        return min(num_stages, num_microbatches)
    return num_microbatches
