"""Multi-tenant LoRA adapter pool for the ONE mixed serving program.

One base model serving many fine-tuned tenants is the canonical
millions-of-users serving shape (the Gemma-on-TPU serving paper,
PAPERS.md): each tenant's fine-tune is a LOW-RANK delta — per adapted
projection W, a pair (A, B) with rank r << min(W.shape) applied as

    y = x @ W + (x @ A) @ B * scale

so a tenant costs ~2*r*(d_in + d_out) extra FLOPs per token instead of
a whole model copy. The serving problem is BATCHING them: a
weight-swap server (merge W' = W + scale * A @ B, serve one tenant,
swap) serializes tenants and pays a cache flush per swap, while this
module keeps every resident tenant's (A, B) pairs in fixed HBM SLABS
and lets each lane of the mixed step gather ITS tenant's pair by slot
index — tenant-heterogeneous batches decode in one fixed-shape step,
token-identical (to float epsilon, hence greedy-argmax-identical) to
the merged-weight server.

The pool is managed exactly like the paged KV pool (kv_cache.py):

  * Fixed GEOMETRY: slabs are padded to a fixed ``adapter_rank`` (and
    the engine's padded ff width), so loading/evicting tenants never
    changes a program shape — the zero-recompile contract extends to
    adapter traffic. Rank padding is EXACT: a padded row/column of
    zeros contributes exactly 0.0 to the delta (tests gate this).
  * Slot 0 is the reserved ZERO slab — the base model. Lanes of
    tenant 0 (and inactive lanes) gather slot 0 and their delta is
    exactly zero, so base and adapted lanes mix freely in one step.
  * REFCOUNTS + LRU: a slot is free, cached (loaded, refcount 0,
    parked in an LRU — still resident, a returning tenant re-attaches
    for free), or mapped (refcount > 0: that many admitted requests).
    Loading a new tenant takes a free slot first, then evicts the
    least-recently-parked cached tenant. An absent adapter whose load
    cannot take a slot BLOCKS admission (a planning-visible stall the
    scheduler reports, never a recompile).
  * BYTE BUDGET: ``--adapter-pool-mb`` sizes the slot count from the
    per-slot device bytes (itemsize-derived, tensor-degree-aware),
    mirroring ``kv_pool_mb`` — and the placement search prices the
    same term (search/cost_model.serve_device_bytes), so
    ``--serve-mesh auto`` trades tensor degree against adapter
    residency.

Host/device split, also like the KV pool: this module owns only HOST
bookkeeping (slot states, refcounts, the tenant registry, pending
loads, the rank-padded host weights); the device slabs are allocated
once by the engine and flow READ-ONLY through the jitted mixed step
(gathered per lane, never scattered, never donated), with on-demand
tenant loads running through one jitted donating scatter program
("adapter" in the engine's compile accounting).

Tenant identity also salts the PREFIX-CACHE chain keys
(:func:`tenant_prefix_salt`): an adapted lane's K/V depends on its
adapter, so two tenants with byte-identical prompts must never share
pages — seeding the chain makes their keys disjoint while tenant 0
keeps the unsalted (cross-engine-compatible) chain.
"""

from __future__ import annotations

import dataclasses
import hashlib
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

# the adapted projections, in slab order: per layer, qkv (stacked),
# the attention output, and the two FFN matmuls
ADAPTER_SLABS = ("a_qkv", "b_qkv", "a_wo", "b_wo",
                 "a_ff1", "b_ff1", "a_ff2", "b_ff2")


def tenant_prefix_salt(tenant_id: int) -> bytes:
    """Seed of a tenant's prefix-cache chain (kv_cache.
    prefix_page_keys ``prev``): tenant 0 (the base model) keeps the
    empty seed — its pages stay shareable with every unarmed engine —
    while an adapted tenant's chain starts from a digest of its
    identity, so equal token content under different adapters hashes
    to DISJOINT keys (adapted K/V is a function of the adapter, and a
    cross-tenant page hit would hand one tenant another's cache)."""
    t = int(tenant_id)
    if t == 0:
        return b""
    return hashlib.sha256(b"adapter-tenant:%d" % t).digest()


@dataclasses.dataclass(frozen=True)
class AdapterConfig:
    """Geometry of the adapter slab pool. Built from FFConfig + model
    shape via :meth:`from_ff` (config.py adapter_rank /
    adapter_pool_mb) so the engine, the scheduler's admission gate,
    the memory ledger, and the placement search all size from the
    same knobs.

    ``ff_dim`` here is the ENGINE's (tensor-degree-padded) ff width —
    slabs must match the sharded program's padded geometry, and the
    pad columns/rows are zero so they contribute exactly nothing.
    ``num_slots`` includes the reserved zero slot 0 (the base model),
    mirroring the KV pool's sink page 0."""

    num_layers: int
    hidden: int
    num_heads: int
    head_dim: int
    ff_dim: int
    rank: int = 8
    num_slots: int = 9  # including the reserved base slot 0
    act_itemsize: int = 4
    tensor_parallel: int = 1

    @classmethod
    def from_ff(cls, config, *, num_layers: int, hidden: int,
                num_heads: int, head_dim: int, ff_dim: int,
                act_itemsize: int = 4,
                tensor_parallel: int = 1) -> "AdapterConfig":
        rank = int(getattr(config, "adapter_rank", 0))
        pool_mb = float(getattr(config, "adapter_pool_mb", 0.0) or 0.0)
        tp = max(1, int(tensor_parallel))
        max_seqs = int(getattr(config, "serve_max_seqs", 8))
        num_slots = 1 + max_seqs
        if pool_mb > 0:
            # byte-budget sizing, the kv_pool_mb idiom: the slot count
            # follows the per-DEVICE slab bytes, so a sharded pool
            # holds more tenants at the same per-chip budget
            probe = cls(num_layers=num_layers, hidden=hidden,
                        num_heads=num_heads, head_dim=head_dim,
                        ff_dim=ff_dim, rank=rank, num_slots=2,
                        act_itemsize=act_itemsize, tensor_parallel=tp)
            num_slots = 1 + max(1, int(pool_mb * (1 << 20))
                                // probe.slot_device_bytes)
        return cls(num_layers=num_layers, hidden=hidden,
                   num_heads=num_heads, head_dim=head_dim,
                   ff_dim=ff_dim, rank=rank, num_slots=num_slots,
                   act_itemsize=act_itemsize, tensor_parallel=tp)

    # ---------------- byte accounting ----------------------------------
    @property
    def usable_slots(self) -> int:
        return self.num_slots - 1  # minus the reserved base slot

    def _params_replicated(self) -> int:
        """Per-slot elements of the slabs that stay REPLICATED under
        tensor sharding: the A factors contracted from replicated
        activations (a_qkv, a_ff1) and the B factors producing
        replicated outputs (b_wo, b_ff2)."""
        L, E, r = self.num_layers, self.hidden, self.rank
        return L * (3 * E * r + r * E + E * r + r * E)

    def _params_sharded(self) -> int:
        """Per-slot elements that shard with the program: B factors on
        the head axis (b_qkv) / padded ff axis (b_ff1), A factors
        contracting the sharded head (a_wo) / ff (a_ff2) dims."""
        L, r = self.num_layers, self.rank
        H, D, F = self.num_heads, self.head_dim, self.ff_dim
        return L * (3 * r * H * D + H * D * r + r * F + F * r)

    @property
    def slot_bytes(self) -> int:
        """Device bytes ONE slot costs unsharded: every A/B element at
        the activation itemsize plus the f32 per-slot scale."""
        return (self._params_replicated() + self._params_sharded()) \
            * self.act_itemsize + 4

    @property
    def slot_device_bytes(self) -> int:
        """Per-device bytes of one slot under the serve mesh: the
        head/ff-sharded components divide by the tensor degree, the
        rank-side components replicate."""
        t = max(1, self.tensor_parallel)
        return (self._params_replicated()
                + self._params_sharded() // t) * self.act_itemsize + 4

    @property
    def pool_bytes(self) -> int:
        return self.num_slots * self.slot_bytes

    @property
    def pool_device_bytes(self) -> int:
        return self.num_slots * self.slot_device_bytes

    def validate(self) -> None:
        if self.rank < 1:
            raise ValueError(
                f"adapter_rank must be >= 1 to arm the pool, got "
                f"{self.rank}")
        if self.num_slots < 2:
            raise ValueError(
                f"adapter pool needs >= 2 slots (slot 0 is the "
                f"reserved base-model zero slab), got {self.num_slots}"
                f" — raise --adapter-pool-mb")
        t = max(1, self.tensor_parallel)
        if self.num_heads % t != 0:
            raise ValueError(
                f"sharded adapter slabs need num_heads "
                f"({self.num_heads}) divisible by the tensor degree "
                f"({t})")
        if self.ff_dim % t != 0:
            raise ValueError(
                f"adapter slabs carry the PADDED ff width; {self.ff_dim}"
                f" is not divisible by the tensor degree ({t})")


def _weight_shapes(cfg: AdapterConfig, rank: int, ff: int
                   ) -> Dict[str, tuple]:
    """Expected host-weight shapes at a given (rank, ff width)."""
    L, E = cfg.num_layers, cfg.hidden
    H, D = cfg.num_heads, cfg.head_dim
    return {
        "a_qkv": (L, 3, E, rank), "b_qkv": (L, 3, rank, H, D),
        "a_wo": (L, H, D, rank), "b_wo": (L, rank, E),
        "a_ff1": (L, E, rank), "b_ff1": (L, rank, ff),
        "a_ff2": (L, ff, rank), "b_ff2": (L, rank, E),
    }


class AdapterPool:
    """Host-side slot allocator + tenant registry for the adapter
    slabs (module docstring). Every usable slot (1..num_slots-1) is in
    exactly one of three states:

      free    — unassigned, in ``_free`` (LIFO: warmest reuse first)
      cached  — assigned to a tenant, refcount 0, in the LRU
                (resident; a returning tenant re-attaches for free;
                evictable when a new tenant needs the slot)
      mapped  — refcount > 0 (that many ADMITTED requests of the
                tenant are running; the scheduler acquires at
                admission and releases at finish/abort/preempt)

    ``acquire`` returning a slot may enqueue a PENDING device load
    (the miss path); the session drains :meth:`take_pending` through
    the engine's jitted load program before the next dispatch — the
    stall is planning-visible (``stats["loads"]``), never a
    recompile. The class never touches device memory."""

    def __init__(self, cfg: AdapterConfig):
        cfg.validate()
        self.cfg = cfg
        self._free: List[int] = list(range(cfg.num_slots - 1, 0, -1))
        self._lru: "OrderedDict[int, None]" = OrderedDict()
        self._ref = np.zeros((cfg.num_slots,), dtype=np.int64)
        self._slot_of_tenant: Dict[int, int] = {}
        self._tenant_of_slot: Dict[int, int] = {}
        # tenant -> (rank+ff padded host weights, scale): the source
        # of truth a (re)load copies to the device slab
        self._host: Dict[int, Tuple[Dict[str, np.ndarray], float]] = {}
        # slot -> tenant awaiting a device load (dict, not list: a
        # slot evicted and reassigned before its drain must load the
        # LAST tenant only)
        self._pending: "OrderedDict[int, int]" = OrderedDict()
        self.stats = {"hits": 0, "misses": 0, "loads": 0,
                      "evictions": 0, "releases": 0,
                      "blocked_admissions": 0, "max_slot_refs": 0}

    # ---------------- tenant registry ----------------------------------
    def register(self, tenant_id: int, weights: Dict[str, np.ndarray],
                 *, scale: float = 1.0, ff_dim: Optional[int] = None
                 ) -> None:
        """Register a tenant's adapter weights (host copy, padded to
        the pool rank and the engine's padded ff width — zero padding
        is exact). `weights` carries the true-rank arrays at the
        MODEL's ff width (`ff_dim`, defaulting to the pool's); shapes
        are validated against :func:`_weight_shapes`. Re-registering
        a RESIDENT tenant is refused — its slab would go stale."""
        t = int(tenant_id)
        if t < 1:
            raise ValueError(
                f"tenant ids are >= 1 (0 is the base model), got {t}")
        if t in self._slot_of_tenant:
            raise ValueError(
                f"tenant {t} is resident; evict it before replacing "
                f"its adapter")
        missing = [k for k in ADAPTER_SLABS if k not in weights]
        if missing:
            raise ValueError(f"adapter weights missing {missing}")
        rank = int(weights["a_qkv"].shape[-1])
        if not 1 <= rank <= self.cfg.rank:
            raise ValueError(
                f"adapter rank {rank} exceeds the pool rank "
                f"{self.cfg.rank} (fixed slab geometry)")
        ff = int(ff_dim if ff_dim is not None else self.cfg.ff_dim)
        expect = _weight_shapes(self.cfg, rank, ff)
        padded: Dict[str, np.ndarray] = {}
        full = _weight_shapes(self.cfg, self.cfg.rank, self.cfg.ff_dim)
        for key in ADAPTER_SLABS:
            arr = np.asarray(weights[key], dtype=np.float32)
            if arr.shape != expect[key]:
                raise ValueError(
                    f"adapter {key} shape {arr.shape} != "
                    f"{expect[key]}")
            out = np.zeros(full[key], dtype=np.float32)
            out[tuple(slice(0, s) for s in arr.shape)] = arr
            padded[key] = out
        self._host[t] = (padded, float(scale))

    def registered(self) -> Tuple[int, ...]:
        return tuple(sorted(self._host))

    def host_weights(self, tenant_id: int
                     ) -> Tuple[Dict[str, np.ndarray], float]:
        """(rank/ff-padded weights, scale) of a registered tenant —
        what the engine's load program copies into the slab."""
        return self._host[int(tenant_id)]

    # ---------------- capacity / residency queries ---------------------
    @property
    def free_slots(self) -> int:
        """ACQUIRABLE slots: truly free plus cached-but-unreferenced
        (the LRU is evicted on demand by acquire)."""
        return len(self._free) + len(self._lru)

    def resident(self, tenant_id: int) -> bool:
        """Whether the tenant holds a slot (mapped or LRU-parked) —
        the router's adapter-affinity signal: routing here skips the
        load stall."""
        return int(tenant_id) == 0 \
            or int(tenant_id) in self._slot_of_tenant

    def slot_of(self, tenant_id: int) -> int:
        """The lane gather index of a tenant (0 = the base slab)."""
        t = int(tenant_id)
        return 0 if t == 0 else self._slot_of_tenant[t]

    def ref(self, slot: int) -> int:
        return int(self._ref[slot])

    # ---------------- admission lifecycle ------------------------------
    def acquire(self, tenant_id: int) -> Optional[int]:
        """Admission-side attach: bump the tenant's refcount and
        return its slot, loading into a free/evicted slot on a miss
        (the pending device load). Returns None when every usable
        slot is mapped by OTHER running tenants — the caller must
        block admission (head-of-line stall), exactly like KV page
        exhaustion. Tenant 0 is the base model: always slot 0, never
        counted."""
        t = int(tenant_id)
        if t == 0:
            return 0
        if t not in self._host:
            raise KeyError(
                f"tenant {t} has no registered adapter (register() "
                f"before submitting its requests)")
        slot = self._slot_of_tenant.get(t)
        if slot is not None:
            if self._ref[slot] == 0:
                self._lru.pop(slot, None)
            self.stats["hits"] += 1
        else:
            if self._free:
                slot = self._free.pop()
            elif self._lru:
                slot, _ = self._lru.popitem(last=False)
                self._evict_slot(slot)
                self.stats["evictions"] += 1
            else:
                self.stats["blocked_admissions"] += 1
                return None
            self._slot_of_tenant[t] = slot
            self._tenant_of_slot[slot] = t
            self._pending[slot] = t
            self.stats["misses"] += 1
            self.stats["loads"] += 1
        self._ref[slot] += 1
        self.stats["max_slot_refs"] = max(self.stats["max_slot_refs"],
                                          int(self._ref[slot]))
        return slot

    def release(self, tenant_id: int) -> None:
        """Finish/abort/preempt-side detach: the refcount drops; a
        slot reaching 0 parks in the LRU — still loaded, so the
        tenant's next request re-attaches without a load."""
        t = int(tenant_id)
        if t == 0:
            return
        slot = self._slot_of_tenant[t]
        if self._ref[slot] <= 0:
            raise RuntimeError(
                f"release of tenant {t} (slot {slot}) below zero refs")
        self._ref[slot] -= 1
        self.stats["releases"] += 1
        if self._ref[slot] == 0:
            self._lru[slot] = None  # most-recently parked

    def _evict_slot(self, slot: int) -> None:
        old = self._tenant_of_slot.pop(slot)
        del self._slot_of_tenant[old]
        self._pending.pop(slot, None)  # a never-drained load is moot

    def take_pending(self) -> List[Tuple[int, int]]:
        """Drain the pending device loads as [(slot, tenant)] — the
        session runs these through the engine's jitted load program
        BEFORE the next mixed dispatch (a lane must never gather a
        slab its tenant hasn't landed in)."""
        out = list(self._pending.items())
        self._pending.clear()
        return out

    # ---------------- reports ------------------------------------------
    def pool_report(self) -> Dict[str, object]:
        """The adapter-pool block of serve_report / last_stats."""
        c = self.cfg
        return {
            "rank": c.rank,
            "usable_slots": c.usable_slots,
            "resident_tenants": len(self._slot_of_tenant),
            "registered_tenants": len(self._host),
            "bytes_per_slot": c.slot_bytes,
            "pool_bytes": c.pool_bytes,
            "tensor_parallel": c.tensor_parallel,
            "bytes_per_slot_device": c.slot_device_bytes,
            "pool_device_bytes": c.pool_device_bytes,
            "occupancy": 1.0 - self.free_slots / c.usable_slots,
        }

    def debug_state(self) -> dict:
        """Bounded JSON-ready snapshot for the failure flight recorder
        (the PagedKVCache.debug_state idiom)."""
        mapped = int(np.count_nonzero(self._ref[1:]))
        return {
            "usable_slots": self.cfg.usable_slots,
            "free_slots": len(self._free),
            "parked_slots": len(self._lru),
            "mapped_slots": mapped,
            "acquirable_slots": self.free_slots,
            "rank": self.cfg.rank,
            "resident": {str(t): int(s) for t, s in
                         sorted(self._slot_of_tenant.items())},
            "pending_loads": len(self._pending),
            "max_slot_ref": int(self._ref.max()) if mapped else 0,
            "stats": dict(self.stats),
        }

    # ---------------- invariant checks (tests) -------------------------
    def check_invariants(self) -> None:
        """Property-style asserts: the free/cached/mapped states
        partition the usable slots, refcounts are consistent, the
        tenant registry is a bijection over assigned slots, pending
        loads target assigned slots, and the base slot is untouched."""
        c = self.cfg
        assert int(self._ref[0]) == 0, "base slot 0 acquired refs"
        assert 0 not in self._tenant_of_slot, "base slot 0 assigned"
        free, lru = set(self._free), set(self._lru)
        assert len(free) == len(self._free), "free list has duplicates"
        assert not (free & lru), "slot both free and cached"
        for s in range(1, c.num_slots):
            r = int(self._ref[s])
            assert r >= 0, f"slot {s} refcount {r} negative"
            states = (s in free) + (s in lru) + (r > 0)
            assert states == 1, (
                f"slot {s} in {states} states (free={s in free}, "
                f"cached={s in lru}, refs={r})")
            assert (s in self._tenant_of_slot) == (s not in free), (
                f"slot {s} assignment inconsistent with free state")
        assert len(free) + len(lru) + int(
            np.count_nonzero(self._ref[1:])) == c.usable_slots, (
            "slot leak: states do not partition the pool")
        assert len(self._slot_of_tenant) == len(self._tenant_of_slot), (
            "tenant registry is not a bijection")
        for t, s in self._slot_of_tenant.items():
            assert self._tenant_of_slot.get(s) == t, (
                f"tenant {t} <-> slot {s} maps inconsistently")
            assert t in self._host, (
                f"resident tenant {t} has no registered weights")
        for s, t in self._pending.items():
            assert self._tenant_of_slot.get(s) == t, (
                f"pending load of slot {s} targets tenant {t} but the "
                f"slot is assigned to {self._tenant_of_slot.get(s)}")


# ---------------- synthetic tenants + the merged-weight oracle ---------
def make_tenant_adapters(*, num_layers: int, hidden: int,
                         num_heads: int, head_dim: int, ff_dim: int,
                         rank: int, tenants: int, seed: int = 0,
                         scale: float = 0.5
                         ) -> Dict[int, Tuple[Dict[str, np.ndarray],
                                              float]]:
    """Seeded synthetic per-tenant adapters {tenant_id: (weights,
    scale)} for tenants 1..`tenants` at the MODEL's (unpadded) ff
    width. Both factors are nonzero (unlike the train-time B=0 init)
    so every tenant visibly steers the logits — which is what the
    parity and goodput gates need — at magnitudes (~1/sqrt(fan-in))
    that keep the adapted forward numerically tame."""
    out: Dict[int, Tuple[Dict[str, np.ndarray], float]] = {}
    L, E, H, D, F = num_layers, hidden, num_heads, head_dim, ff_dim
    shapes = {
        "a_qkv": ((L, 3, E, rank), E), "b_qkv": ((L, 3, rank, H, D), rank),
        "a_wo": ((L, H, D, rank), H * D), "b_wo": ((L, rank, E), rank),
        "a_ff1": ((L, E, rank), E), "b_ff1": ((L, rank, F), rank),
        "a_ff2": ((L, F, rank), F), "b_ff2": ((L, rank, E), rank),
    }
    for t in range(1, int(tenants) + 1):
        rng = np.random.default_rng(int(seed) * 100003 + t)
        w = {k: rng.normal(0.0, fan ** -0.5, shape).astype(np.float32)
             for k, (shape, fan) in shapes.items()}
        out[t] = (w, float(scale))
    return out


def merge_adapter_params(params, weights: Dict[str, np.ndarray],
                         scale: float):
    """The per-tenant merged-weight REFERENCE: a new params pytree
    with every adapted projection folded, W' = W + scale * A @ B —
    what a weight-swap server would serve for this tenant, and the
    oracle the batched path must match token-for-token (greedy /
    top_k=1). Merging runs in f32 and casts back to each kernel's
    dtype. `weights` is the registered (true-rank or padded) dict at
    the kernels' ff width."""
    import jax.numpy as jnp

    def fold(kern, delta):
        k32 = np.asarray(kern, dtype=np.float32)
        return jnp.asarray(k32 + float(scale) * delta
                           ).astype(np.asarray(kern).dtype)

    out = {name: dict(p) for name, p in params.items()}
    L = weights["a_qkv"].shape[0]
    for i in range(L):
        attn = dict(out[f"layer{i}_attn"])
        for j, wname in enumerate(("wq", "wk", "wv")):
            delta = np.einsum("er,rhd->ehd", weights["a_qkv"][i, j],
                              weights["b_qkv"][i, j])
            attn[wname] = fold(attn[wname], delta)
        attn["wo"] = fold(attn["wo"],
                          np.einsum("hdr,re->hde", weights["a_wo"][i],
                                    weights["b_wo"][i]))
        out[f"layer{i}_attn"] = attn
        ff1 = dict(out[f"layer{i}_ff1"])
        ff1["kernel"] = fold(ff1["kernel"],
                             weights["a_ff1"][i] @ weights["b_ff1"][i])
        out[f"layer{i}_ff1"] = ff1
        ff2 = dict(out[f"layer{i}_ff2"])
        ff2["kernel"] = fold(ff2["kernel"],
                             weights["a_ff2"][i] @ weights["b_ff2"][i])
        out[f"layer{i}_ff2"] = ff2
    return out
