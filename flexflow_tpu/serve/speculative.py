"""Host-side drafting for speculative decoding.

Speculative decoding spends SPARE LANES of the fixed-shape mixed step
(serve/engine.py) to advance a decoding sequence by more than one token
per program dispatch: a cheap DRAFTER proposes k continuation tokens,
the engine scores positions [n-1, n-1+k] in one step (each lane's
logits are exactly the logits the reference would compute at that
position GIVEN the drafts before it), and the host accepts the longest
prefix of drafts that match what the model would have emitted anyway.
Greedy verification is therefore token-IDENTICAL to one-at-a-time
decode — a mis-draft costs lanes, never correctness — which is what
lets the serving exactness gate (outputs == generate_reference) keep
running unchanged over the speculative path.

Two pieces live here, both pure host Python (no jax):

  * :class:`PromptLookupDrafter` — prompt-lookup / n-gram drafting: the
    proposal for "what comes after the current suffix" is "whatever
    followed the most recent earlier occurrence of that suffix" in the
    sequence's OWN token history (prompt + generated). No second model,
    no device work, so it drafts (and benches) on CPU CI; repetitive
    text — code, few-shot scaffolding, retrieval quotes — accepts
    nearly everything, adversarial text simply finds no match. The
    :class:`Drafter` interface is one method, so a small draft LM can
    slot in later without touching the scheduler.
  * :class:`DraftControl` — per-request adaptive draft length: a
    windowed acceptance rate scales k between 0 and the configured
    maximum (serve_spec_tokens). Text that keeps rejecting drafts
    drives k to 0 (speculation auto-disables: the request degrades to
    exactly the non-speculative engine, paying nothing), with a rare
    1-token probe so a request whose text turns repetitive later can
    re-enable itself.
"""

from __future__ import annotations

import math
from collections import deque
from typing import Deque, List, Sequence, Tuple


class Drafter:
    """Interface: propose up to k likely continuation tokens for a
    sequence whose resident context is `tokens`. Fewer (or zero)
    proposals are always legal — the scheduler drafts what it gets —
    and wrong proposals are always safe (verification rejects them)."""

    def draft(self, tokens: Sequence[int], k: int) -> List[int]:
        raise NotImplementedError


class PromptLookupDrafter(Drafter):
    """Prompt-lookup decoding: match the context's trailing n-gram
    against its own earlier history and propose the tokens that
    followed the MOST RECENT earlier occurrence.

    Longer n-grams are tried first (a 3-gram match is far more
    predictive than a 1-gram match). Among a length's matches, the most
    recent occurrence that can supply all k continuation tokens wins —
    recency matters because generated text drifts, but an occurrence
    too close to the tail clips its continuation at the end of known
    history (on a constant run the nearest match yields ONE token while
    an earlier one yields k), so fullness outranks pure recency; with
    no full continuation anywhere, the longest available one is taken.
    The scan is O(len * max_ngram) per draft over plain Python ints,
    i.e. microseconds at serving context lengths — the whole point is
    that drafting must cost less than the lanes it risks."""

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1):
        if not 1 <= min_ngram <= max_ngram:
            raise ValueError(
                f"need 1 <= min_ngram <= max_ngram, got "
                f"[{min_ngram}, {max_ngram}]")
        self.max_ngram = int(max_ngram)
        self.min_ngram = int(min_ngram)

    def draft(self, tokens: Sequence[int], k: int) -> List[int]:
        if k <= 0:
            return []
        tokens = list(tokens)
        n_tok = len(tokens)
        for n in range(self.max_ngram, self.min_ngram - 1, -1):
            if n_tok <= n:
                continue
            pattern = tokens[-n:]
            first = pattern[0]
            rest = pattern[1:]
            best: List[int] = []
            # right-to-left: most recent first; stop at the first match
            # whose continuation is full (earlier matches only ever
            # offer MORE continuation, never more recency). The
            # first-element filter keeps the hot loop allocation-free —
            # this scan runs per decoding sequence per step on the host.
            for i in range(n_tok - n - 1, -1, -1):
                if tokens[i] != first:
                    continue
                if rest and tokens[i + 1:i + n] != rest:
                    continue
                avail = min(k, n_tok - i - n)
                if avail > len(best):
                    best = tokens[i + n:i + n + avail]
                    if avail == k:
                        break
            if best:
                return best
        return []


class DraftControl:
    """Per-request draft-length controller over a windowed acceptance
    rate.

    Each verified step records (drafted, accepted); `next_k` maps the
    rate over the last `window` drafting steps to a length in
    [0, k_max]:

      * no history yet  -> k_max (optimism is free: the first window
        measures the text, and wrong drafts only waste budget lanes)
      * rate >= disable_below, or window not yet full -> ceil(k_max *
        3/2 * rate), clamped to [1, k_max]: floored at 1 so the
        estimate keeps refreshing, and overshooting on mid rates
        because a draft's cost (a budget lane) is far below its payoff
        (a whole saved step) — k should only shrink when drafts are
        mostly dead weight
      * a FULL window below `disable_below` -> 0: the text is
        adversarial for this drafter, and a 0-draft request is
        bit-for-bit the plain decode path. Every `probe_every`-th
        decode step the stale window is DROPPED and a single token is
        drafted — a fresh measurement, so a sequence whose text turns
        repetitive later (e.g. enters a generation loop) climbs back
        out of 0 in a handful of steps instead of dragging a window
        full of old failures behind it. A failed probe refills the
        window with cheap 1-token drafts and re-disables.

    All decisions are deterministic functions of the request's own
    history — no RNG, so serving stays reproducible."""

    def __init__(self, k_max: int, window: int = 8,
                 disable_below: float = 0.125, probe_every: int = 32):
        self.k_max = int(k_max)
        self.window = int(window)
        self.disable_below = float(disable_below)
        self.probe_every = int(probe_every)
        self._hist: Deque[Tuple[int, int]] = deque(maxlen=self.window)
        self._decode_steps = 0
        # a probe cleared the window and its measurement has not come
        # back yet: stay at 1-token drafts, NOT the fresh-request
        # optimism (the text already measured adversarial once)
        self._probing = False
        # lifetime counters (serve_report / tests)
        self.drafted = 0
        self.accepted = 0

    @property
    def rate(self) -> float:
        d = sum(d for d, _ in self._hist)
        return sum(a for _, a in self._hist) / d if d else 1.0

    @property
    def disabled(self) -> bool:
        """True when the windowed rate has auto-disabled drafting."""
        return (len(self._hist) == self.window
                and self.rate < self.disable_below)

    def next_k(self) -> int:
        """Draft length for this decode step (before budget/page/
        length clamps — the scheduler shrinks, never grows)."""
        self._decode_steps += 1
        if self.k_max <= 0:
            return 0
        if not self._hist:
            # empty history is optimism only BEFORE the first
            # measurement; after a probe cleared the window (and the
            # drafter may have had nothing to propose, recording
            # nothing) it must stay a 1-token re-measure, or
            # adversarial text would re-trigger full-width drafting
            # every probe period
            return 1 if self._probing else self.k_max
        if self.disabled:
            if self.probe_every and \
                    self._decode_steps % self.probe_every == 0:
                self._hist.clear()   # fresh measurement, not an average
                self._probing = True
                return 1
            return 0
        return max(1, min(self.k_max,
                          int(math.ceil(self.k_max * 1.5 * self.rate))))

    def record(self, drafted: int, accepted: int) -> None:
        """Outcome of one verified step. Steps that drafted nothing
        (no n-gram match, no budget) carry no signal about the text
        and are not recorded."""
        if drafted <= 0:
            return
        assert 0 <= accepted <= drafted, (drafted, accepted)
        self._hist.append((drafted, accepted))
        self._probing = False
        self.drafted += drafted
        self.accepted += accepted
