"""flexflow_tpu.serve — continuous-batching inference.

The training half of the framework compiles an op graph into one jitted
SPMD step; this package opens the inference half: a block-paged KV-cache
with refcounted prefix caching (:mod:`kv_cache`), a continuous-batching
scheduler with chunked prefill, watermark admission and preemption
(:mod:`scheduler`), host-side drafting for verified speculative decode
(:mod:`speculative`), and a :class:`ServeEngine` (:mod:`engine`) that
wraps a built LM into ONE fixed-shape mixed prefill+decode step so XLA
compiles a single serving program, ever. :mod:`disagg` splits serving
into dedicated prefill and decode engine roles with a host-side KV
page handoff between them (:class:`DisaggCluster`) — decode steps stop
paying for prefill lanes, the tail-latency win the placement search
prices via ``optimize_serve(..., disaggregated=True)``. :mod:`router`
builds the tier ABOVE one replica: a :class:`ReplicaPool` of N engines
behind a prefix-affinity router with load-aware spill and a
telemetry-driven :class:`Autoscaler`, serving the seeded timed traffic
:mod:`traffic` synthesizes — goodput-under-SLO as a reproducible
number (docs/serving.md "Multi-replica routing").
"""

from .kv_cache import KVCacheConfig, PagedKVCache, prefix_page_keys
from .scheduler import (ChunkPlan, ContinuousBatchingScheduler,
                        RejectedRequest, Request, RequestOutcome,
                        RequestState, SampleParams, StepPlan)
from .speculative import DraftControl, Drafter, PromptLookupDrafter
from .engine import ServeEngine, ServeSession, StepEvents
from .disagg import (DisaggCluster, PageShipment, engine_for,
                     normalize_on_step)
from .router import Autoscaler, Replica, ReplicaPool
from .traffic import (TrafficRequest, TrafficSpec, make_traffic,
                      rescale_arrivals)
from .transport import (ShipmentReceiver, ShipmentSender,
                        ShipmentWireError, dumps_shipment,
                        loads_shipment)

__all__ = [
    "Autoscaler",
    "Replica",
    "ReplicaPool",
    "ServeSession",
    "StepEvents",
    "TrafficRequest",
    "TrafficSpec",
    "make_traffic",
    "rescale_arrivals",
    "DisaggCluster",
    "PageShipment",
    "engine_for",
    "normalize_on_step",
    "ShipmentReceiver",
    "ShipmentSender",
    "ShipmentWireError",
    "dumps_shipment",
    "loads_shipment",
    "KVCacheConfig",
    "PagedKVCache",
    "prefix_page_keys",
    "ChunkPlan",
    "ContinuousBatchingScheduler",
    "RejectedRequest",
    "Request",
    "RequestOutcome",
    "RequestState",
    "SampleParams",
    "StepPlan",
    "DraftControl",
    "Drafter",
    "PromptLookupDrafter",
    "ServeEngine",
]
