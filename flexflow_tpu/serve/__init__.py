"""flexflow_tpu.serve — continuous-batching inference.

The training half of the framework compiles an op graph into one jitted
SPMD step; this package opens the inference half: a block-paged KV-cache
(:mod:`kv_cache`), a continuous-batching scheduler (:mod:`scheduler`),
and a :class:`ServeEngine` (:mod:`engine`) that wraps a built LM into
jitted prefill/decode steps with static padded shapes so XLA compiles
each bucket exactly once.
"""

from .kv_cache import KVCacheConfig, PagedKVCache
from .scheduler import ContinuousBatchingScheduler, Request, RequestState
from .engine import ServeEngine

__all__ = [
    "KVCacheConfig",
    "PagedKVCache",
    "ContinuousBatchingScheduler",
    "Request",
    "RequestState",
    "ServeEngine",
]
