"""Block-paged KV-cache manager with prefix caching.

The device cache is a fixed pool of PAGES — (page_size, heads, head_dim)
K and V blocks per layer — and each sequence owns a PAGE TABLE mapping
its logical token positions to physical pages, exactly the layout of
"Ragged Paged Attention" serving kernels (PAPERS.md): token t of a
sequence lives at page `table[t // page_size]`, offset `t % page_size`.

Why pages instead of one (max_seqs, max_len) rectangle: a rectangle
reserves max_len tokens of HBM per slot whether or not the sequence uses
them; pages let short and long sequences share one pool, so capacity is
bounded by TOTAL resident tokens, not max_seqs * max_len. Freeing a
finished sequence returns whole pages to the pool — reuse is
defrag-free because pages are fixed-size and position-independent.

Three properties layered on top of the PR 1 allocator:

  * Per-page REFCOUNTS: a page can be mapped by several slots at once.
    The K/V of a token block depends only on the token content and its
    position, so two sequences with the same prompt prefix can read the
    same physical pages. A page returns to circulation only when its
    refcount hits 0.
  * PREFIX HASHING: every COMPLETED page (all page_size positions
    written with real K/V) can be registered under a chain hash of its
    token content — key_i = H(key_{i-1} || tokens[i*ps:(i+1)*ps]) — so
    `match_prefix` finds the longest resident run of pages for a new
    prompt in O(pages). Partial (tail) pages are never shared: they are
    still being written by their owner. A hashed page whose refcount
    drops to 0 is NOT freed — it parks in an LRU of reclaimable cached
    pages, still matchable, and is evicted (hash dropped) only when the
    allocator runs dry. `free_pages` therefore counts reclaimable
    capacity: truly-free pages plus the evictable LRU.
  * ON-DEMAND ALLOCATION: slots claim pages as their sequence actually
    grows (`ensure_capacity` / `append_token` allocate when a page
    boundary is crossed) instead of reserving prompt+max_new up front.
    Effective batch size is bounded by actual residency; the scheduler
    pairs this with a preemption path for the rare pool-exhausted step.

Page 0 is reserved as the write SINK: padding lanes of the static-shape
steps scatter their K/V there through page-table entries of 0, so the
jitted steps never need a masked scatter. Reads are masked by sequence
length, so sink contents are never observed.

Host/device split: this class owns only HOST bookkeeping (free list,
refcounts, hash registry, page tables, lengths) as plain numpy/dicts the
scheduler mutates freely; the device arrays are created once by
`alloc_device_cache()` and flow functionally through the engine's jitted
steps (donated in, returned out) — the manager never touches device
memory.
"""

from __future__ import annotations

import dataclasses
import hashlib
from collections import OrderedDict
from typing import Dict, List, Sequence, Tuple

import numpy as np

from ..config import KV_DTYPES  # the ONE --kv-dtype allowlist

# KV_DTYPES names that store quantized values against per-row scale
# arrays (the PR 8 scale machinery; fp8 reuses it with no new
# bookkeeping — only the page dtype and the qmax change).
QUANTIZED_KV_DTYPES = ("int8", "float8_e4m3")

# --kv-dtype name -> the dtype actually stored in the page arrays.
# "float8_e4m3" stores ml_dtypes' float8_e4m3fn (the finite-only OCP
# variant every jax build ships; the no-suffix e4m3 is newer and not
# universally available).
_KV_STORAGE_ALIASES = {"float8_e4m3": "float8_e4m3fn"}


def kv_storage_dtype(name: str):
    """numpy/jnp dtype of the page arrays for a --kv-dtype name."""
    import jax.numpy as jnp
    return jnp.dtype(_KV_STORAGE_ALIASES.get(str(name), str(name)))


def prefix_page_keys(tokens: Sequence[int], page_size: int,
                     num_pages: int, *, start: int = 0,
                     prev: bytes = b"") -> List[bytes]:
    """Chain hashes for FULL pages [start, num_pages) of `tokens`:
    key_i = sha256(key_{i-1} || block_i_bytes). Position-dependence is
    implicit in the chain (block i's key commits to every token before
    it), so equal keys mean equal (content, position) — the sharing
    precondition. Callers extending an existing chain pass `start` and
    the last known key as `prev`, so per-sequence hashing stays O(pages)
    instead of O(pages^2) across incremental extensions."""
    keys: List[bytes] = []
    for i in range(start, num_pages):
        block = np.asarray(tokens[i * page_size:(i + 1) * page_size],
                           dtype=np.int32)
        prev = hashlib.sha256(prev + block.tobytes()).digest()
        keys.append(prev)
    return keys


@dataclasses.dataclass(frozen=True)
class KVCacheConfig:
    """Geometry of the paged pool. Built from FFConfig + model shape via
    :meth:`from_ff` so every serving component sizes itself from the
    same knobs (config.py kv_page_size / kv_num_pages / kv_dtype /
    kv_pool_mb / serve_max_seqs).

    ``kv_dtype`` selects the PAGE STORAGE format: float32 (exact),
    bfloat16 (values round on write; exact when the engine's activation
    dtype is already bf16), or int8 (quantized with per-page scale
    arrays — one f32 scale per head per in-page token slot, see
    `scale_shape`). Scales are per-slot rather than per-whole-page
    because pages fill INCREMENTALLY (decode appends one token at a
    time): a page-global amax would have to re-quantize every resident
    token whenever a new token raised it, which is neither cheap nor
    rollback-safe, while per-slot scales keep quantization write-local
    so chunk boundaries, preemption replays, and speculative rollbacks
    cannot change what any resident token dequantizes to.

    All BYTE accounting (``page_bytes``, ``pool_bytes``, the
    ``kv_pool_mb`` sizing below) derives from the configured dtype's
    itemsize — never a hardcoded 4 — so watermark fractions, ladder
    rung thresholds and ``ensure_capacity`` (all page-COUNT math over
    ``usable_pages``) automatically see the larger effective pool a
    quantized format buys at the same byte budget.

    ``tensor_parallel`` is the serve mesh's tensor degree (docs/
    serving.md "Sharded serving"): pages shard on the HEAD axis, so
    every device holds all ``num_pages`` pages at ``num_heads / t``
    heads each. The page COUNT — and with it every watermark /
    degradation-ladder / ``ensure_capacity`` fraction — is therefore
    per-device-identical, while the per-device BYTES drop t×
    (``page_device_bytes``). ``kv_pool_mb`` is a PER-DEVICE HBM budget
    (the physically meaningful knob): sizing divides it by
    ``page_device_bytes``, so a sharded pool holds ~t× the pages at
    the same per-chip budget and the ladder rungs fire at the same
    relative per-device pressure. All host-side page / refcount /
    prefix bookkeeping stays replicated and tp-agnostic."""

    num_layers: int
    num_heads: int
    head_dim: int
    page_size: int = 16
    num_pages: int = 257  # including the reserved sink page 0
    max_seqs: int = 8
    max_seq_len: int = 512  # logical cap; rounds up to whole pages
    kv_dtype: str = "float32"
    tensor_parallel: int = 1  # head-sharding degree of the serve mesh

    @classmethod
    def from_ff(cls, config, *, num_layers: int, num_heads: int,
                head_dim: int, max_seq_len: int = 512,
                tensor_parallel: int = 1) -> "KVCacheConfig":
        kv_dtype = str(getattr(config, "kv_dtype", "float32"))
        num_pages = int(getattr(config, "kv_num_pages", 257))
        pool_mb = float(getattr(config, "kv_pool_mb", 0.0) or 0.0)
        tp = max(1, int(tensor_parallel))
        if pool_mb > 0:
            # byte-budget sizing: the page count FOLLOWS the storage
            # format (the quantized-capacity lever — int8 pages cost
            # ~1/4 the bytes, so the same budget holds ~4x the pages)
            # AND the sharding degree: the budget is per-DEVICE HBM,
            # and a head-sharded page costs 1/t of its bytes on each
            # device, so the same per-chip budget holds ~t× the pages
            # — which is exactly what keeps every page-count-fraction
            # threshold (watermark, ladder rungs) firing at the same
            # relative per-device pressure under sharding.
            probe = cls(num_layers=num_layers, num_heads=num_heads,
                        head_dim=head_dim,
                        page_size=int(getattr(config, "kv_page_size", 16)),
                        num_pages=2, max_seqs=1,
                        max_seq_len=max_seq_len, kv_dtype=kv_dtype,
                        tensor_parallel=tp)
            num_pages = 1 + max(1, int(pool_mb * (1 << 20))
                                // probe.page_device_bytes)
        return cls(num_layers=num_layers, num_heads=num_heads,
                   head_dim=head_dim,
                   page_size=int(getattr(config, "kv_page_size", 16)),
                   num_pages=num_pages,
                   max_seqs=int(getattr(config, "serve_max_seqs", 8)),
                   max_seq_len=max_seq_len, kv_dtype=kv_dtype,
                   tensor_parallel=tp)

    @property
    def pages_per_seq(self) -> int:
        """Static page-table width (logical max_seq_len in pages)."""
        return -(-self.max_seq_len // self.page_size)

    @property
    def usable_pages(self) -> int:
        return self.num_pages - 1  # minus the sink

    # ---------------- storage format / byte accounting ----------------
    @property
    def quantized(self) -> bool:
        return self.kv_dtype in QUANTIZED_KV_DTYPES

    @property
    def storage_dtype(self):
        """The dtype actually stored in the page arrays (resolves the
        float8_e4m3 -> float8_e4m3fn alias)."""
        return kv_storage_dtype(self.kv_dtype)

    @property
    def kv_itemsize(self) -> int:
        return int(self.storage_dtype.itemsize)

    @property
    def scale_shape(self):
        """Per-page scale-array geometry (int8 pages only): one f32
        scale per (layer, page, in-page slot, head) for K and for V."""
        return (self.num_layers, self.num_pages, self.page_size,
                self.num_heads)

    @property
    def page_bytes(self) -> int:
        """Device bytes ONE page costs across all layers: K + V values
        at kv_dtype itemsize, plus the f32 scale rows when quantized.
        The basis for every byte-level pool computation (never assume
        4 bytes/element)."""
        values = (2 * self.num_layers * self.page_size * self.num_heads
                  * self.head_dim * self.kv_itemsize)
        scales = (2 * self.num_layers * self.page_size * self.num_heads
                  * 4) if self.quantized else 0
        return values + scales

    @property
    def f32_page_bytes(self) -> int:
        """What the same page geometry costs in float32 pages — the
        baseline for the quantized-capacity comparison."""
        return (2 * self.num_layers * self.page_size * self.num_heads
                * self.head_dim * 4)

    @property
    def pool_bytes(self) -> int:
        return self.num_pages * self.page_bytes

    # ---------------- per-device accounting (sharded serving) ---------
    @property
    def heads_per_device(self) -> int:
        return self.num_heads // max(1, self.tensor_parallel)

    @property
    def page_device_bytes(self) -> int:
        """Device bytes ONE page costs under head sharding: both the
        value blocks and the scale rows carry the head axis, so the
        whole page cost divides exactly by the tensor degree."""
        return self.page_bytes // max(1, self.tensor_parallel)

    @property
    def pool_device_bytes(self) -> int:
        return self.num_pages * self.page_device_bytes

    @property
    def effective_page_ratio(self) -> float:
        """Pages this format fits per byte, relative to f32 — the
        capacity multiplier int8 buys at an equal pool budget."""
        return self.f32_page_bytes / self.page_bytes

    def validate(self) -> None:
        if self.page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {self.page_size}")
        if self.num_pages < 2:
            raise ValueError(
                f"num_pages must be >= 2 (page 0 is the reserved sink), "
                f"got {self.num_pages}")
        if self.kv_dtype not in KV_DTYPES:
            raise ValueError(
                f"kv_dtype must be one of {KV_DTYPES}, got "
                f"{self.kv_dtype!r}")
        if self.pages_per_seq > self.usable_pages:
            raise ValueError(
                f"one max-length sequence needs {self.pages_per_seq} pages "
                f"but the pool only has {self.usable_pages} usable")
        if self.tensor_parallel < 1:
            raise ValueError(
                f"tensor_parallel must be >= 1, got "
                f"{self.tensor_parallel}")
        if self.num_heads % max(1, self.tensor_parallel) != 0:
            raise ValueError(
                f"head-sharded serving needs num_heads "
                f"({self.num_heads}) divisible by the tensor degree "
                f"({self.tensor_parallel})")


class PagedKVCache:
    """Host-side page allocator + per-slot page tables + prefix cache.

    Slots are the static decode-batch lanes (0..max_seqs-1); the
    scheduler binds a running request to a slot and this class binds the
    slot to pages. All arrays are padded to static shapes so the jitted
    steps see one geometry forever:

      page_tables  (max_seqs, pages_per_seq) int32, 0 = sink/unmapped
      seq_lens     (max_seqs,) int32, 0 = slot empty

    Every usable page is in exactly one of three states:
      free    — unhashed, in `_free` (LIFO: warmest reuse first)
      cached  — hashed, refcount 0, in the `_lru` (matchable, evictable)
      mapped  — refcount > 0 (referenced by >= 1 slot's table)
    """

    def __init__(self, cfg: KVCacheConfig, prefix_cache: bool = True):
        cfg.validate()
        self.cfg = cfg
        self.prefix_enabled = bool(prefix_cache)
        self._free: List[int] = list(range(cfg.num_pages - 1, 0, -1))
        self._lru: "OrderedDict[int, None]" = OrderedDict()
        self._ref = np.zeros((cfg.num_pages,), dtype=np.int64)
        self._hash_of_page: Dict[int, bytes] = {}
        self._page_of_hash: Dict[bytes, int] = {}
        self.page_tables = np.zeros((cfg.max_seqs, cfg.pages_per_seq),
                                    dtype=np.int32)
        self.seq_lens = np.zeros((cfg.max_seqs,), dtype=np.int32)
        self._slot_free = list(range(cfg.max_seqs - 1, -1, -1))
        # quantized-page scale bookkeeping (register_scale_meta):
        # geometry of the engine's scale arrays, checked by
        # check_invariants against cfg.scale_shape
        self._scale_meta = None
        # pages whose content arrived over the disaggregated handoff
        # (import_pages) rather than from this engine's own compute:
        # they must stay hashed for as long as they are resident — an
        # imported page the registry stopped vouching for would be
        # unreachable garbage (check_invariants)
        self._imported: set = set()
        # hierarchical prefix cache (serve/host_tier.HostPageStore):
        # when armed, eviction queues (page, key) here instead of
        # silently dropping the identity; the ENGINE drains the queue —
        # DMAing the still-resident device rows into the store — before
        # every dispatch that could overwrite pages (the device pools
        # only mutate through jitted dispatches, so a queued page's
        # content stays valid exactly until then)
        self.host_tier = None
        self._pending_spills: List[Tuple[int, bytes]] = []
        # serving metrics, merged into ServeEngine.last_stats
        self.stats = {"prefix_hit_pages": 0, "prefix_evictions": 0,
                      "pages_committed": 0, "shared_attaches": 0,
                      "max_page_refs": 0, "rollback_pages": 0,
                      "lru_shed_pages": 0, "slots_reclaimed": 0,
                      "exported_pages": 0, "imported_pages": 0,
                      "import_dedup_pages": 0}

    # ---------------- capacity queries (scheduler admission) ----------
    @property
    def free_pages(self) -> int:
        """RECLAIMABLE pages: truly free plus cached-but-unreferenced
        (the LRU is evicted on demand by allocation)."""
        return len(self._free) + len(self._lru)

    @property
    def free_slots(self) -> int:
        return len(self._slot_free)

    def pages_for(self, tokens: int) -> int:
        return -(-tokens // self.cfg.page_size)

    def mapped_pages(self, slot: int) -> int:
        return int(np.count_nonzero(self.page_tables[slot]))

    def mapped_tokens(self, slot: int) -> int:
        """Token capacity already backed by this slot's pages."""
        return self.mapped_pages(slot) * self.cfg.page_size

    def ref(self, page: int) -> int:
        return int(self._ref[page])

    def debug_state(self) -> dict:
        """Bounded JSON-ready pool snapshot for the failure flight
        recorder (docs/observability.md): page-state partition (free /
        parked / mapped), slot residency, refcount spread, and the
        lifetime stats — the numbers a post-mortem needs to answer
        "was the pool wedged" without shipping the page tables."""
        c = self.cfg
        mapped = int(np.count_nonzero(self._ref))
        return {
            "usable_pages": c.usable_pages,
            "free_pages": len(self._free),
            "parked_pages": len(self._lru),
            "mapped_pages": mapped,
            "reclaimable_pages": self.free_pages,
            "occupancy": 1.0 - self.free_pages / c.usable_pages,
            "free_slots": self.free_slots,
            "max_seqs": c.max_seqs,
            "seq_lens": [int(n) for n in self.seq_lens],
            "hashed_pages": len(self._page_of_hash),
            "imported_resident": len(self._imported),
            "max_page_ref": int(self._ref.max()) if mapped else 0,
            "kv_dtype": c.kv_dtype,
            "page_size": c.page_size,
            # eviction order (oldest first, bounded): what rung-2 /
            # allocation pressure would shed next — the view rung
            # post-mortems were missing
            "lru_order": [int(p) for p in list(self._lru)[:64]],
            "lru_truncated": max(0, len(self._lru) - 64),
            "pending_spills": len(self._pending_spills),
            "host_tier": (self.host_tier.debug_state()
                          if self.host_tier is not None else None),
            "stats": dict(self.stats),
        }

    # ---------------- prefix cache ------------------------------------
    def match_prefix(self, keys: Sequence[bytes]) -> List[int]:
        """Longest run of resident pages whose chain keys match `keys`
        from the start. Returned pages are NOT reserved — the caller
        must `attach_prefix` them before any allocation can evict the
        refcount-0 ones out of the LRU."""
        pages: List[int] = []
        if not self.prefix_enabled:
            return pages
        for key in keys:
            p = self._page_of_hash.get(key)
            if p is None:
                break
            pages.append(p)
        return pages

    def match_prefix_host(self, keys: Sequence[bytes],
                          resident: int) -> int:
        """The host-tier fall-through of `match_prefix`: how many keys
        BEYOND the `resident` HBM-matched run are held by the armed
        host store (0 when no tier). The pages are NOT reloaded here —
        the scheduler prices DMA-vs-recompute first and only then asks
        the engine to re-import (ServeEngine._host_reload)."""
        if self.host_tier is None or not self.prefix_enabled:
            return 0
        return self.host_tier.match_chain(list(keys[resident:]))

    def touch(self, pages: Sequence[int]) -> None:
        """Refresh parked pages to most-recently-used, so an imminent
        allocation burst (a host-tier reload's import) cannot evict
        the very HBM run an admission just matched."""
        for p in pages:
            p = int(p)
            if p in self._lru:
                self._lru.move_to_end(p)

    def take_pending_spills(self) -> List[Tuple[int, bytes]]:
        """Claim the queued (page, chain key) spill records, clearing
        the queue. The engine calls this immediately before any
        dispatch that writes the device pools and ships each page's
        rows to the host tier — past that point the queued pages may
        be overwritten and the records would vouch for garbage."""
        out, self._pending_spills = self._pending_spills, []
        return out

    def commit_page(self, slot: int, page_idx: int, key: bytes) -> bool:
        """Register a COMPLETED page of `slot` under its content chain
        key, making it matchable by future prompts. No-op when hashing
        is off, the page is already registered, or another page already
        owns the key (first writer wins; deduping the loser is not
        worth a device copy). Returns True when registered."""
        if not self.prefix_enabled:
            return False
        page = int(self.page_tables[slot, page_idx])
        if page == 0:
            raise RuntimeError(
                f"commit_page on unmapped page {page_idx} of slot {slot}")
        if page in self._hash_of_page or key in self._page_of_hash:
            return False
        self._hash_of_page[page] = key
        self._page_of_hash[key] = page
        self.stats["pages_committed"] += 1
        return True

    def _unregister(self, page: int) -> None:
        key = self._hash_of_page.pop(page, None)
        if key is not None:
            del self._page_of_hash[key]
        # a de-hashed imported page is no longer vouched-for handoff
        # content — it is just a free/garbage page again
        self._imported.discard(page)

    def _pop_parked(self, *, spill: bool = True) -> int:
        """Retire the least-recently-parked cached page — the ONE
        eviction primitive `_take_page` and `shrink_lru` share. Split
        into two halves: reclaiming CAPACITY (pop from the LRU) and
        forgetting IDENTITY (unregister the hash) — when `spill` and a
        host tier is armed, the identity is queued as a pending spill
        instead of dropped, so the engine can DMA the page's
        still-resident device rows into the host store before anything
        overwrites them ("spill instead of discard")."""
        page, _ = self._lru.popitem(last=False)
        if spill and self.host_tier is not None:
            key = self._hash_of_page.get(page)
            if key is not None:
                self._pending_spills.append((page, key))
        self._unregister(page)
        return page

    def _take_page(self) -> int:
        """A writable page: the free list first, then evict the
        least-recently-parked cached page (spilling its identity to
        the host tier when one is armed, else dropping its hash)."""
        if self._free:
            return self._free.pop()
        if self._lru:
            page = self._pop_parked()
            self.stats["prefix_evictions"] += 1
            return page
        raise RuntimeError(
            "page pool exhausted (scheduler must check free_pages and "
            "preempt before allocating)")

    def clear_prefix(self) -> int:
        """Drop the ENTIRE prefix registry: every parked LRU page
        returns to the plain free list and every mapped page loses its
        hash. The crash-containment action — after a mid-batch engine
        failure the device arrays the registry's content lived in are
        stale or consumed, so nothing on them may be vouched for.
        Returns the number of hashes dropped."""
        n = len(self._hash_of_page)
        while self._lru:
            page, _ = self._lru.popitem(last=False)
            self._unregister(page)
            self._free.append(page)
        for page in list(self._hash_of_page):
            self._unregister(page)
        # queued spills point at the same stale/consumed device rows —
        # shipping them to the host tier would vouch for garbage
        self._pending_spills.clear()
        return n

    def shrink_lru(self, keep: int, *, spill: bool = True) -> int:
        """Reclaim capacity: evict parked (refcount-0, hashed) pages
        oldest-first until at most `keep` remain, returning them to the
        plain free list. The degradation ladder's rung-2 action: under
        page pressure a parked page is a liability — a prefix attach
        would pin it at refcount > 0 right when admissions need every
        reclaimable page. Whether the IDENTITY is also forgotten is the
        `_pop_parked` split: with a host tier armed (and `spill` left
        on) rung 2 becomes "spill instead of discard" — the key and
        content move down a tier instead of being recomputed from
        tokens later. Returns the number of pages shed."""
        shed = 0
        while len(self._lru) > max(0, int(keep)):
            page = self._pop_parked(spill=spill)
            self._free.append(page)
            shed += 1
        self.stats["lru_shed_pages"] += shed
        return shed

    # ---------------- disaggregated page handoff ----------------------
    # Host-side half of the prefill->decode transfer (serve/disagg.py):
    # export names the FULL, resident pages of a slot with their chain
    # keys; import allocates pages for foreign keys and parks them in
    # the prefix LRU — hashed, refcount 0, matchable — which is
    # EXACTLY the state a locally-computed page reaches when its last
    # owner finishes, so everything downstream (match_prefix /
    # attach_prefix / eviction / the ladder) treats handed-off content
    # identically to local content. The device rows ride separately
    # through ServeEngine.export_kv/import_kv (this class never
    # touches device memory).

    def export_pages(self, slot: int, tokens: Sequence[int], *,
                     prev: bytes = b""
                     ) -> Tuple[List[int], List[bytes], int]:
        """(pages, chain keys, covered tokens) for every FULL page of
        `slot`'s resident sequence — the transfer unit of a
        disaggregated handoff. `tokens` is the slot's context (the
        caller owns it; page content is a pure function of the token
        prefix, which is what makes the chain key a sound transfer
        identity). The partial tail page is never exported: like
        prefix sharing, only whole pages have a content identity —
        the importer recomputes the tail (< page_size tokens), exactly
        as a prefix-cache hit would. `prev` seeds the chain — the
        tenant prefix salt (serve/adapters.tenant_prefix_salt): an
        adapted tenant's pages carry tenant-disjoint keys, so a
        handoff can never alias one tenant's K/V to another's."""
        ps = self.cfg.page_size
        full = int(self.seq_lens[slot]) // ps
        if full * ps > len(tokens):
            raise ValueError(
                f"slot {slot} has {self.seq_lens[slot]} resident "
                f"tokens but only {len(tokens)} were supplied")
        pages = [int(self.page_tables[slot, i]) for i in range(full)]
        if any(p == 0 for p in pages):
            raise RuntimeError(
                f"slot {slot} table is not a mapped prefix over its "
                f"resident length")
        keys = prefix_page_keys(tokens, ps, full, prev=prev)
        self.stats["exported_pages"] += len(pages)
        return pages, keys, full * ps

    def import_pages(self, keys: Sequence[bytes]
                     ) -> List[Tuple[int, int]]:
        """Adopt a handed-off page chain: for every chain key not
        already resident, allocate a page, register the key, and park
        the page in the prefix LRU (refcount 0, hashed, matchable —
        the same state finish-time eviction leaves a local page in).
        Returns [(chain_index, page)] for the pages whose device rows
        the caller must now write (ServeEngine.import_kv); keys that
        are already resident dedupe to nothing — a shared system
        preamble crosses the link ONCE per decode engine, not once per
        request. The caller must have checked `free_pages` against
        len(keys): running the allocator dry here is a cluster
        backpressure bug (DisaggCluster skips the import instead)."""
        if not self.prefix_enabled:
            raise RuntimeError(
                "import_pages needs the prefix cache: an imported page "
                "is only reachable through its chain-key registration")
        out: List[Tuple[int, int]] = []
        for i, key in enumerate(keys):
            if key in self._page_of_hash:
                self.stats["import_dedup_pages"] += 1
                continue
            page = self._take_page()
            self._hash_of_page[page] = key
            self._page_of_hash[key] = page
            self._lru[page] = None     # most-recently parked
            self._imported.add(page)
            out.append((i, page))
        self.stats["imported_pages"] += len(out)
        return out

    def imported_pages(self) -> Tuple[int, ...]:
        """Pages whose resident content arrived over the handoff link
        (still hashed — eviction drops them from this set too)."""
        return tuple(sorted(self._imported))

    def key_resident(self, key: bytes) -> bool:
        """Whether a chain key is already registered here — what the
        cluster's backpressure check counts a shipment's NEW pages
        with (resident keys dedupe on import)."""
        return key in self._page_of_hash

    # ---------------- slot lifecycle ----------------------------------
    def release_all(self) -> int:
        """Free every occupied slot (crash recovery: a serving loop
        died between allocation and the bookkeeping that would have
        freed it). Committed full pages park in the prefix LRU exactly
        as finish-time eviction would leave them — their K/V was fully
        written before commit_page registered them, so they stay
        safely matchable. Returns the number of slots reclaimed."""
        occupied = set(range(self.cfg.max_seqs)) - set(self._slot_free)
        for s in sorted(occupied):
            # a mid-write tail page may carry no hash; free_slot already
            # routes hashed -> LRU, unhashed -> free list. But a hashed
            # page only PARTIALLY covered by seq_lens (a crash between
            # advance and commit cannot produce one — commit follows
            # advance — so this is belt and braces) must not stay
            # matchable: rollback to the resident length first.
            self.rollback(s, int(self.seq_lens[s]))
            self.free_slot(s)
        self.stats["slots_reclaimed"] += len(occupied)
        return len(occupied)

    def alloc_slot(self) -> int:
        """Claim an empty decode slot. Pages arrive separately via
        attach_prefix (shared) and ensure_capacity (fresh)."""
        if not self._slot_free:
            raise RuntimeError("no free slot (scheduler must check "
                               "free_slots first)")
        return self._slot_free.pop()

    def attach_prefix(self, slot: int, pages: Sequence[int],
                      ntokens: int) -> None:
        """Map already-resident prefix pages into an empty slot and mark
        their `ntokens` tokens resident without any compute. Bumps each
        page's refcount (pulling refcount-0 pages out of the LRU)."""
        if self.seq_lens[slot] != 0 or self.mapped_pages(slot) != 0:
            raise RuntimeError(f"attach_prefix on non-empty slot {slot}")
        if ntokens != len(pages) * self.cfg.page_size:
            raise ValueError(
                f"prefix of {ntokens} tokens does not fill "
                f"{len(pages)} pages exactly (only whole pages share)")
        for i, p in enumerate(pages):
            p = int(p)
            if self._ref[p] == 0:
                if p not in self._lru:
                    raise RuntimeError(
                        f"page {p} has refcount 0 but is not cached")
                del self._lru[p]
            else:
                self.stats["shared_attaches"] += 1
            self._ref[p] += 1
            self.stats["max_page_refs"] = max(self.stats["max_page_refs"],
                                              int(self._ref[p]))
            self.page_tables[slot, i] = p
        self.stats["prefix_hit_pages"] += len(pages)
        self.seq_lens[slot] = ntokens

    def ensure_capacity(self, slot: int, total_tokens: int) -> int:
        """Allocate fresh (refcount-1, unhashed) pages so the slot can
        hold `total_tokens`. Returns the number of pages allocated.
        The caller (scheduler) must have verified `pages_to_extend`
        against `free_pages` — running dry here is a scheduling bug."""
        if total_tokens > self.cfg.pages_per_seq * self.cfg.page_size:
            raise ValueError(
                f"{total_tokens} tokens exceeds the page-table ceiling")
        have = self.mapped_pages(slot)
        need = self.pages_for(total_tokens)
        for i in range(have, need):
            page = self._take_page()
            self._ref[page] = 1
            self.page_tables[slot, i] = page
        return max(0, need - have)

    def pages_to_extend(self, slot: int, total_tokens: int) -> int:
        return max(0, self.pages_for(total_tokens) - self.mapped_pages(slot))

    def advance(self, slot: int, new_len: int) -> None:
        """Mark tokens up to `new_len` resident (a completed prefill
        chunk / decode write). Pages must already be mapped."""
        if new_len < int(self.seq_lens[slot]):
            raise ValueError(
                f"advance moved slot {slot} backwards "
                f"({self.seq_lens[slot]} -> {new_len})")
        if self.pages_for(new_len) > self.mapped_pages(slot):
            raise RuntimeError(
                f"slot {slot} advanced to {new_len} tokens past its "
                f"{self.mapped_pages(slot)} mapped pages")
        self.seq_lens[slot] = new_len

    def append_token(self, slot: int) -> int:
        """Advance the slot's length by one decoded token, allocating a
        page on demand when the position crosses a page boundary;
        returns the new token's position."""
        if self.seq_lens[slot] == 0:
            raise RuntimeError(f"append_token on empty slot {slot}")
        pos = int(self.seq_lens[slot])
        self.ensure_capacity(slot, pos + 1)
        self.seq_lens[slot] = pos + 1
        return pos

    def rollback(self, slot: int, new_len: int) -> int:
        """Rewind the slot to `new_len` resident tokens and unmap every
        page wholly past the new boundary. Returns the pages released.

        This is the speculative-decoding undo: rejected draft tokens
        have already scattered K/V into pages the scheduler mapped
        ahead (ensure_capacity), and once verification truncates the
        sequence those tail pages hold garbage. Positions inside the
        kept pages need no cleanup — reads are masked by seq_lens and
        the slots are overwritten when the sequence actually reaches
        them — but whole pages past `pages_for(new_len)` must leave
        the table so the pool's accounting stays exact.

        A released page is NEVER parked in the prefix LRU, and any
        hash it carries is dropped when its refcount reaches 0: its
        content is no longer vouched for by a resident sequence, so a
        post-rollback tail page must not be prefix-matchable (the
        check_invariants hashed-page-coverage rule). In the engine's
        flow these pages are always fresh refcount-1 unhashed
        allocations — commit_page only ever registers fully VERIFIED
        pages — but the method is defensive about shared/hashed ones
        so direct users cannot corrupt the registry."""
        if new_len < 0:
            raise ValueError(f"rollback to negative length {new_len}")
        ps = self.cfg.page_size
        if new_len < int(self.seq_lens[slot]):
            self.seq_lens[slot] = new_len
        released = 0
        for i in range(self.pages_for(new_len), self.cfg.pages_per_seq):
            p = int(self.page_tables[slot, i])
            if p == 0:
                break  # tables are contiguous prefixes
            self._ref[p] -= 1
            if self._ref[p] == 0:
                self._unregister(p)
                self._free.append(p)
            elif not self._vouched(p):
                self._unregister(p)   # surviving owners rolled back too
            self.page_tables[slot, i] = 0
            released += 1
        # the boundary page stays mapped when new_len cuts into it, but
        # a hash on it now overclaims (the registry key vouches for the
        # FULL page) — drop it unless another sequence still covers it
        if new_len % ps:
            p = int(self.page_tables[slot, new_len // ps])
            if p != 0 and p in self._hash_of_page and not self._vouched(p):
                self._unregister(p)
        self.stats["rollback_pages"] += released
        return released

    def _vouched(self, page: int) -> bool:
        """True when some slot's RESIDENT (seq_lens-covered) full pages
        include `page` — the condition for its content hash to stay in
        the registry (check_invariants' hashed-page coverage rule)."""
        for s in range(self.cfg.max_seqs):
            full = int(self.seq_lens[s]) // self.cfg.page_size
            if page in (int(p) for p in self.page_tables[s, :full]):
                return True
        return False

    def free_slot(self, slot: int) -> None:
        """Release the slot: every mapped page's refcount drops; pages
        reaching 0 go back to the free list — or, if content-hashed, to
        the reclaimable LRU so a future prompt can still match them.
        This is both the finished-sequence eviction path and the
        preemption path (a preempted sequence's prefix stays matchable,
        which is what makes preemption cheap to undo)."""
        for i in range(self.cfg.pages_per_seq):
            p = int(self.page_tables[slot, i])
            if p == 0:
                continue
            self._ref[p] -= 1
            if self._ref[p] == 0:
                if p in self._hash_of_page:
                    self._lru[p] = None   # most-recently parked
                else:
                    self._free.append(p)
            self.page_tables[slot, i] = 0
        self.seq_lens[slot] = 0
        self._slot_free.append(slot)

    # ---------------- device arrays -----------------------------------
    def alloc_device_cache(self, dtype=None, sharding=None):
        """The (k_pages, v_pages) device arrays, each
        (num_layers, num_pages, page_size, num_heads, head_dim) at the
        configured kv_dtype (dtype overrides — the pre-quantization
        callers passed explicit dtypes). `sharding` (a NamedSharding
        over the serve mesh's head axis) places the pool head-sharded
        for tensor-parallel serving — each device holds its H/t heads
        of every page. Created once per engine; thereafter they only
        flow through jitted steps (donated), never through this
        manager. Quantized pools pair with :meth:`alloc_scale_arrays`."""
        import jax
        import jax.numpy as jnp
        c = self.cfg
        shape = (c.num_layers, c.num_pages, c.page_size, c.num_heads,
                 c.head_dim)
        dt = dtype or c.storage_dtype
        k, v = jnp.zeros(shape, dt), jnp.zeros(shape, dt)
        if sharding is not None:
            k = jax.device_put(k, sharding)
            v = jax.device_put(v, sharding)
        return k, v

    def alloc_scale_arrays(self, sharding=None):
        """The (k_scales, v_scales) f32 per-page scale arrays for
        quantized (int8/fp8) pools (cfg.scale_shape). Like the page
        arrays they flow functionally through the jitted steps, donated
        — and shard on the same head axis."""
        import jax
        import jax.numpy as jnp
        if not self.cfg.quantized:
            raise RuntimeError(
                f"scale arrays exist only for quantized (int8/fp8) "
                f"pools (kv_dtype={self.cfg.kv_dtype})")
        ks = jnp.zeros(self.cfg.scale_shape, jnp.float32)
        vs = jnp.zeros(self.cfg.scale_shape, jnp.float32)
        if sharding is not None:
            ks = jax.device_put(ks, sharding)
            vs = jax.device_put(vs, sharding)
        return ks, vs

    def register_scale_meta(self, k_scales, v_scales) -> None:
        """Record the scale-array geometry the engine allocated so
        check_invariants can vouch for the quantized-page bookkeeping
        (shape/dtype drift between the host page accounting and the
        device scale arrays would silently dequantize garbage)."""
        self._scale_meta = (tuple(k_scales.shape), str(k_scales.dtype),
                            tuple(v_scales.shape), str(v_scales.dtype))

    def parked_pages(self) -> Tuple[int, ...]:
        """The prefix-cache-parked pages: complete, unreferenced,
        prefix-matchable — content that must outlive its writer for a
        later request to attach (the post-run surface
        ServeEngine.check_kv_scales audits)."""
        return tuple(int(p) for p in self._lru)

    def pool_report(self) -> Dict[str, object]:
        """The KV-pool line of ServeEngine.last_stats / serve_report:
        storage format, per-page and pool bytes (itemsize-derived),
        effective pages, and the capacity multiplier vs f32 pages.
        Occupancy here is INSTANTANEOUS (meaningful mid-run; zero once
        generate() has released every slot) — last_stats overrides it
        with the run's peak."""
        c = self.cfg
        return {
            "kv_dtype": c.kv_dtype,
            "bytes_per_page": c.page_bytes,
            "effective_pages": c.usable_pages,
            "pool_bytes": c.pool_bytes,
            "tensor_parallel": c.tensor_parallel,
            "bytes_per_page_device": c.page_device_bytes,
            "pool_device_bytes": c.pool_device_bytes,
            "occupancy": 1.0 - self.free_pages / c.usable_pages,
            "page_ratio_vs_f32": round(c.effective_page_ratio, 3),
            "pages_saved_vs_f32": int(
                c.usable_pages - c.usable_pages / c.effective_page_ratio),
        }

    # ---------------- invariant checks (tests) ------------------------
    def check_invariants(self) -> None:
        """Property-style asserts: refcounts equal the number of table
        references, the free/cached/mapped states partition the pool,
        no page leaks or double-frees, tables are contiguous prefixes,
        and the hash registry is a consistent bijection."""
        c = self.cfg
        table_refs: Dict[int, int] = {}
        for s in range(c.max_seqs):
            row = self.page_tables[s]
            nz = np.flatnonzero(row)
            n_mapped = len(nz)
            assert np.array_equal(nz, np.arange(n_mapped)), (
                f"slot {s} page table is not a contiguous prefix: {row}")
            assert int(self.seq_lens[s]) <= n_mapped * c.page_size, (
                f"slot {s} length {self.seq_lens[s]} exceeds its "
                f"{n_mapped} mapped pages")
            for p in row[:n_mapped]:
                table_refs[int(p)] = table_refs.get(int(p), 0) + 1
        assert 0 not in table_refs, "sink page mapped to a slot"
        free, lru = set(self._free), set(self._lru)
        assert len(free) == len(self._free), "free list has duplicates"
        assert not (free & lru), "page both free and cached"
        for p in range(1, c.num_pages):
            r = int(self._ref[p])
            assert r == table_refs.get(p, 0), (
                f"page {p} refcount {r} != {table_refs.get(p, 0)} "
                f"table references")
            states = (p in free) + (p in lru) + (r > 0)
            assert states == 1, (
                f"page {p} in {states} states (free={p in free}, "
                f"cached={p in lru}, refs={r})")
            if p in lru:
                assert p in self._hash_of_page, f"cached page {p} unhashed"
        assert len(table_refs) + len(free) + len(lru) == c.usable_pages, (
            "page leak: states do not partition the pool")
        assert len(self._hash_of_page) == len(self._page_of_hash), (
            "hash registry is not a bijection")
        for page, key in self._hash_of_page.items():
            assert self._page_of_hash.get(key) == page, (
                f"hash registry maps page {page} inconsistently")
        # a hashed (prefix-matchable) page must be VOUCHED for: either
        # parked in the LRU (its last owner completed it before
        # freeing) or fully covered by some slot's resident length. A
        # mapped page past any coverage — a speculative tail, or a
        # rolled-back region — holds unverified K/V and being matchable
        # would hand garbage to a future prompt (the rollback contract).
        covered_pages = set()
        for s in range(c.max_seqs):
            full = int(self.seq_lens[s]) // c.page_size
            covered_pages.update(int(p) for p in self.page_tables[s, :full])
        for page in self._hash_of_page:
            assert page in self._lru or page in covered_pages, (
                f"hashed page {page} is neither parked nor fully "
                f"covered by a resident sequence (rolled-back or "
                f"speculative pages must not be prefix-matchable)")
        if not self.prefix_enabled:
            assert not self._hash_of_page and not self._lru, (
                "prefix cache disabled but registry non-empty")
        # disaggregated-handoff bookkeeping: an IMPORTED page's content
        # was never computed here, so it is reachable ONLY through its
        # chain-key registration — a resident imported page without a
        # hash would be unidentifiable garbage. Every imported page
        # must therefore still be hashed (eviction/_unregister removes
        # it from the imported set atomically with its key) and in one
        # of the hashed states the coverage rule above already vouches
        # for (parked, or mapped under a resident sequence).
        for page in self._imported:
            assert page in self._hash_of_page, (
                f"imported page {page} lost its chain key while still "
                f"tracked as handoff content")
        # quantized-page scale bookkeeping: an int8 pool must have
        # registered scale arrays whose geometry matches the page
        # geometry exactly — a drifted shape would dequantize every
        # resident token against the wrong scale rows — and a
        # non-quantized pool must not carry scale state at all.
        if c.quantized:
            if self._scale_meta is not None:
                ks_shape, ks_dt, vs_shape, vs_dt = self._scale_meta
                assert ks_shape == c.scale_shape == vs_shape, (
                    f"scale arrays {ks_shape}/{vs_shape} do not match "
                    f"the pool geometry {c.scale_shape}")
                assert ks_dt == vs_dt == "float32", (
                    f"scale arrays must be float32, got {ks_dt}/{vs_dt}")
        else:
            assert self._scale_meta is None, (
                f"kv_dtype={c.kv_dtype} pool carries scale bookkeeping")
