"""Block-paged KV-cache manager.

The device cache is a fixed pool of PAGES — (page_size, heads, head_dim)
K and V blocks per layer — and each sequence owns a PAGE TABLE mapping
its logical token positions to physical pages, exactly the layout of
"Ragged Paged Attention" serving kernels (PAPERS.md): token t of a
sequence lives at page `table[t // page_size]`, offset `t % page_size`.

Why pages instead of one (max_seqs, max_len) rectangle: a rectangle
reserves max_len tokens of HBM per slot whether or not the sequence uses
them; pages let short and long sequences share one pool, so capacity is
bounded by TOTAL resident tokens, not max_seqs * max_len. Freeing a
finished sequence returns whole pages to the pool — reuse is
defrag-free because pages are fixed-size and position-independent.

Page 0 is reserved as the write SINK: padding lanes of the static-shape
prefill/decode steps (positions past a prompt's real length, inactive
decode slots) scatter their K/V there through page-table entries of 0,
so the jitted steps never need a masked scatter. Reads are masked by
sequence length, so sink contents are never observed.

Host/device split: this class owns only HOST bookkeeping (free list,
page tables, lengths) as numpy arrays the scheduler mutates freely; the
device arrays are created once by `alloc_device_cache()` and flow
functionally through the engine's jitted steps (donated in, returned
out) — the manager never touches device memory.
"""

from __future__ import annotations

import dataclasses
from typing import List

import numpy as np


@dataclasses.dataclass(frozen=True)
class KVCacheConfig:
    """Geometry of the paged pool. Built from FFConfig + model shape via
    :meth:`from_ff` so every serving component sizes itself from the
    same knobs (config.py kv_page_size / kv_num_pages /
    serve_max_seqs)."""

    num_layers: int
    num_heads: int
    head_dim: int
    page_size: int = 16
    num_pages: int = 257  # including the reserved sink page 0
    max_seqs: int = 8
    max_seq_len: int = 512  # logical cap; rounds up to whole pages

    @classmethod
    def from_ff(cls, config, *, num_layers: int, num_heads: int,
                head_dim: int, max_seq_len: int = 512) -> "KVCacheConfig":
        return cls(num_layers=num_layers, num_heads=num_heads,
                   head_dim=head_dim,
                   page_size=int(getattr(config, "kv_page_size", 16)),
                   num_pages=int(getattr(config, "kv_num_pages", 257)),
                   max_seqs=int(getattr(config, "serve_max_seqs", 8)),
                   max_seq_len=max_seq_len)

    @property
    def pages_per_seq(self) -> int:
        """Static page-table width (logical max_seq_len in pages)."""
        return -(-self.max_seq_len // self.page_size)

    @property
    def usable_pages(self) -> int:
        return self.num_pages - 1  # minus the sink

    def validate(self) -> None:
        if self.page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {self.page_size}")
        if self.num_pages < 2:
            raise ValueError(
                f"num_pages must be >= 2 (page 0 is the reserved sink), "
                f"got {self.num_pages}")
        if self.pages_per_seq > self.usable_pages:
            raise ValueError(
                f"one max-length sequence needs {self.pages_per_seq} pages "
                f"but the pool only has {self.usable_pages} usable")


class PagedKVCache:
    """Host-side page allocator + per-slot page tables.

    Slots are the static decode-batch lanes (0..max_seqs-1); the
    scheduler binds a running request to a slot and this class binds the
    slot to pages. All arrays are padded to static shapes so the jitted
    steps see one geometry forever:

      page_tables  (max_seqs, pages_per_seq) int32, 0 = sink/unmapped
      seq_lens     (max_seqs,) int32, 0 = slot empty
    """

    def __init__(self, cfg: KVCacheConfig):
        cfg.validate()
        self.cfg = cfg
        # LIFO free list: most-recently-freed pages are reused first
        # (their cache lines are warmest); page 0 never enters the pool.
        self._free: List[int] = list(range(cfg.num_pages - 1, 0, -1))
        self.page_tables = np.zeros((cfg.max_seqs, cfg.pages_per_seq),
                                    dtype=np.int32)
        self.seq_lens = np.zeros((cfg.max_seqs,), dtype=np.int32)
        self._slot_free = list(range(cfg.max_seqs - 1, -1, -1))

    # ---------------- capacity queries (scheduler admission) ----------
    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def free_slots(self) -> int:
        return len(self._slot_free)

    def pages_needed(self, total_tokens: int) -> int:
        """Pages a sequence of `total_tokens` (prompt + all new tokens)
        will occupy — the scheduler reserves this worst case at
        admission so a running sequence can never strand mid-decode with
        an empty pool (no preemption path)."""
        return -(-total_tokens // self.cfg.page_size)

    def can_admit(self, total_tokens: int) -> bool:
        return (self.free_slots > 0
                and total_tokens <= self.cfg.max_seq_len
                and self.pages_needed(total_tokens) <= self.free_pages)

    # ---------------- slot lifecycle ----------------------------------
    def alloc_slot(self, prompt_len: int, reserve_tokens: int) -> int:
        """Claim a decode slot and map pages for `reserve_tokens` total
        tokens (prompt + max new). Returns the slot id. The prompt is
        considered resident immediately (seq_len = prompt_len); decode
        then advances the length one token at a time through
        :meth:`append_token`."""
        if prompt_len < 1:
            raise ValueError("prompt must be at least 1 token")
        if prompt_len > reserve_tokens:
            raise ValueError(
                f"reserve_tokens ({reserve_tokens}) must cover the "
                f"prompt ({prompt_len})")
        if not self.can_admit(reserve_tokens):
            raise RuntimeError(
                f"admission bug: alloc_slot for {reserve_tokens} tokens "
                f"with {self.free_pages} pages / {self.free_slots} slots "
                f"free (scheduler must check can_admit first)")
        slot = self._slot_free.pop()
        n = self.pages_needed(reserve_tokens)
        for i in range(n):
            self.page_tables[slot, i] = self._free.pop()
        self.seq_lens[slot] = prompt_len
        return slot

    def append_token(self, slot: int) -> int:
        """Advance the slot's length by one decoded token; returns the
        new token's position. Pages were reserved at admission, so this
        never allocates."""
        if self.seq_lens[slot] == 0:
            raise RuntimeError(f"append_token on empty slot {slot}")
        pos = int(self.seq_lens[slot])
        page_idx = pos // self.cfg.page_size
        if self.page_tables[slot, page_idx] == 0:
            raise RuntimeError(
                f"slot {slot} ran past its reserved pages at position "
                f"{pos} (admission reserved too few)")
        self.seq_lens[slot] = pos + 1
        return pos

    def free_slot(self, slot: int) -> None:
        """Return the slot's pages to the pool and clear its table —
        the eviction path the scheduler runs the moment a sequence
        finishes, which is what lets the waiting queue backfill."""
        for i in range(self.cfg.pages_per_seq):
            p = int(self.page_tables[slot, i])
            if p != 0:
                self._free.append(p)
                self.page_tables[slot, i] = 0
        self.seq_lens[slot] = 0
        self._slot_free.append(slot)

    # ---------------- device arrays -----------------------------------
    def alloc_device_cache(self, dtype=None):
        """The (k_pages, v_pages) device arrays, each
        (num_layers, num_pages, page_size, num_heads, head_dim). Created
        once per engine; thereafter they only flow through jitted steps
        (donated), never through this manager."""
        import jax.numpy as jnp
        c = self.cfg
        shape = (c.num_layers, c.num_pages, c.page_size, c.num_heads,
                 c.head_dim)
        dt = dtype or jnp.float32
        return jnp.zeros(shape, dt), jnp.zeros(shape, dt)

    # ---------------- invariant checks (tests) ------------------------
    def check_invariants(self) -> None:
        """Property-style asserts: every page is either free, mapped to
        exactly one slot, or the sink; lengths fit mapped pages."""
        mapped = [int(p) for row in self.page_tables for p in row if p != 0]
        assert len(mapped) == len(set(mapped)), "page mapped twice"
        assert 0 not in mapped, "sink page mapped to a slot"
        assert not (set(mapped) & set(self._free)), "page both mapped+free"
        assert len(mapped) + len(self._free) == self.cfg.usable_pages, (
            f"page leak: {self.cfg.usable_pages - len(mapped) - len(self._free)}"
            f" pages unaccounted for")
        for s in range(self.cfg.max_seqs):
            n_mapped = int(np.count_nonzero(self.page_tables[s]))
            assert int(self.seq_lens[s]) <= n_mapped * self.cfg.page_size, (
                f"slot {s} length {self.seq_lens[s]} exceeds its "
                f"{n_mapped} mapped pages")
