"""SLO traffic harness: the workload generator the serve bench lacked.

Offline throughput numbers say little about "millions of users": what
decides whether a serving tier holds is how it behaves under a TIMED
arrival stream — bursts, heavy-tailed prompt/output lengths, many
tenants sharing system preambles, users hitting stop mid-generation.
This module synthesizes exactly that traffic, seeded and fully
deterministic, so goodput-under-SLO (requests meeting both the TTFT
and TPOT targets, per second — the metric the multi-replica router
A/B gates on, tools/serve_bench.py ``--workload router``) is a
reproducible number instead of a wall-clock anecdote.

Shapes generated (:func:`make_traffic` over a :class:`TrafficSpec`):

  * arrivals — Poisson (exponential inter-arrival gaps at
    ``rate_rps``) or bursty (the same Poisson process whose rate
    multiplies by ``burst_factor`` inside seeded burst windows — the
    thundering-herd pattern an autoscaler must absorb);
  * multi-tenant prefix mixes — each tenant owns a shared prompt
    prefix (the few-shot / system-preamble pattern), tenants drawn
    Zipf-skewed so a few tenants dominate exactly as production
    traffic does; a request's prompt is its tenant's prefix plus a
    unique heavy-tailed tail;
  * heavy-tailed lengths — prompt tails and output budgets draw from
    a clipped Pareto (a few giants among many small requests: the
    shape that makes p99 — not the mean — the number that matters);
  * mid-generation cancels — a seeded fraction of requests abandons
    after a heavy-tailed number of emitted tokens (the router must
    reclaim their affinity pins and pages);
  * seeded sampling — a fraction decodes with temperature/top-k
    keyed to the request's ``stream_id``, so routed/disaggregated
    token streams must reproduce a single engine's bit-for-bit
    (docs/serving.md "Sampled streams").

Everything keys off ``TrafficSpec.seed``: the same spec always yields
the same request list, which is what makes router A/Bs, autoscaler
decisions and chaos replays comparable across arms and runs.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import numpy as np

__all__ = ["TrafficRequest", "TrafficSpec", "make_traffic",
           "rescale_arrivals", "tenant_prefixes"]


@dataclasses.dataclass
class TrafficRequest:
    """One request of a synthesized stream. ``stream_id`` is its
    global identity: the router submits it as the sampling stream id
    (token streams reproduce on any replica) and keys its tracking
    record by it."""

    stream_id: int
    t_arrival: float
    tenant: int
    prompt: List[int]
    max_new: int
    temperature: float = 0.0
    top_k: Optional[int] = None
    # abandon after this many emitted tokens (None = runs to the end)
    cancel_after_tokens: Optional[int] = None

    @property
    def sampled(self) -> bool:
        return self.temperature > 0.0


@dataclasses.dataclass
class TrafficSpec:
    """Knobs of one synthesized stream (defaults are bench-sized; the
    smoke workload shrinks them). Lengths are clipped to
    ``max_prompt`` / ``max_new_cap`` so every request is admissible
    against the serving engine's ``max_seq_len``."""

    requests: int = 64
    seed: int = 0
    # ---- arrivals ----
    arrival: str = "poisson"          # "poisson" | "bursty"
    rate_rps: float = 8.0             # mean arrival rate
    burst_factor: float = 4.0         # in-burst rate multiplier
    burst_len: int = 8                # mean requests per burst window
    # ---- tenants / prefix mix ----
    # Tenant ids double as adapter names when the engine arms a LoRA
    # pool (serve/adapters.py): tenant 0 is the base model, tenants
    # 1..N-1 must each have a registered adapter before traffic for
    # them is submitted.  The Zipf head (tenant 0) therefore exercises
    # the base path while the tail exercises pool churn.
    tenants: int = 4
    tenant_zipf: float = 1.1          # Zipf skew over tenant draw
    prefix_tokens: int = 48           # shared per-tenant prefix length
    # ---- heavy-tailed lengths (clipped Pareto) ----
    tail_mean: float = 8.0            # unique prompt tail tokens
    output_mean: float = 12.0         # decode budget per request
    pareto_a: float = 2.0             # tail index (lower = heavier)
    max_prompt: int = 96
    max_new_cap: int = 32
    # ---- behaviors ----
    cancel_frac: float = 0.0          # mid-generation abandon fraction
    sample_frac: float = 0.0          # seeded-sampling fraction
    temperature: float = 0.8
    top_k: int = 4
    vocab: int = 512

    def validate(self) -> None:
        if self.requests < 1:
            raise ValueError(f"requests must be >= 1, got "
                             f"{self.requests}")
        if self.arrival not in ("poisson", "bursty"):
            raise ValueError(f"arrival must be 'poisson' or 'bursty', "
                             f"got {self.arrival!r}")
        if self.rate_rps <= 0:
            raise ValueError(f"rate_rps must be > 0, got "
                             f"{self.rate_rps}")
        if self.tenants < 1:
            raise ValueError(f"tenants must be >= 1, got "
                             f"{self.tenants}")
        if not 0.0 <= self.cancel_frac <= 1.0 \
                or not 0.0 <= self.sample_frac <= 1.0:
            raise ValueError("cancel_frac/sample_frac must be in "
                             "[0, 1]")
        if self.prefix_tokens >= self.max_prompt:
            raise ValueError(
                f"prefix_tokens ({self.prefix_tokens}) must leave "
                f"room for a tail under max_prompt "
                f"({self.max_prompt})")


def tenant_prefixes(spec: TrafficSpec) -> Dict[int, List[int]]:
    """The per-tenant shared prompt prefixes, derived from the spec's
    seed alone (a router test can rebuild them to pre-warm a replica
    without replaying traffic)."""
    rng = np.random.default_rng([int(spec.seed), 0x7E9A97])
    return {t: rng.integers(1, spec.vocab,
                            size=spec.prefix_tokens).tolist()
            for t in range(spec.tenants)}


def rescale_arrivals(traffic: List[TrafficRequest],
                     scale: float) -> List[TrafficRequest]:
    """A copy of the stream with every arrival time multiplied by
    ``scale`` — wall-clock pacing's rate knob (docs/serving.md
    "Wall-clock mode"): the same requests (prompts, tenants, sampling,
    cancels untouched, so token identity across arms holds) arriving
    ``1/scale`` times faster. A wall-clock bench shrinks a
    virtual-authoritative stream's timeline to something measurable
    without re-synthesizing the workload."""
    if not scale > 0.0:
        raise ValueError(f"scale must be > 0, got {scale}")
    return [dataclasses.replace(t, t_arrival=t.t_arrival * scale)
            for t in traffic]


def _heavy(rng, mean: float, a: float, lo: int, hi: int) -> int:
    """Clipped-Pareto draw with approximate mean ``mean``: Pareto(a)
    has mean 1/(a-1) (for a > 1), so scale accordingly — the standard
    heavy-tail generator for lengths (a few giants among many small
    draws)."""
    scale = mean * (a - 1.0) if a > 1.0 else mean
    v = 1.0 + rng.pareto(a) * scale
    return int(min(hi, max(lo, round(v))))


def make_traffic(spec: TrafficSpec) -> List[TrafficRequest]:
    """Synthesize the stream: a pure, deterministic function of the
    spec (same spec -> byte-identical requests). Returned sorted by
    arrival time with ``stream_id`` in arrival order."""
    spec.validate()
    rng = np.random.default_rng([int(spec.seed), 0x5EEDED])
    prefixes = tenant_prefixes(spec)
    # Zipf-skewed tenant weights: w_t ~ 1/(t+1)^s, normalized
    w = np.array([1.0 / (t + 1) ** spec.tenant_zipf
                  for t in range(spec.tenants)])
    w /= w.sum()

    # arrival clock: exponential gaps at rate_rps; in bursty mode the
    # stream alternates seeded windows of ~burst_len requests between
    # the base rate and burst_factor x it (mean rate stays comparable,
    # the VARIANCE is the point)
    t = 0.0
    in_burst = False
    window_left = 0
    out: List[TrafficRequest] = []
    for i in range(spec.requests):
        rate = spec.rate_rps
        if spec.arrival == "bursty":
            if window_left <= 0:
                in_burst = not in_burst
                window_left = max(1, int(rng.poisson(spec.burst_len)))
            window_left -= 1
            if in_burst:
                rate = spec.rate_rps * spec.burst_factor
            else:
                rate = spec.rate_rps / max(1.0, spec.burst_factor / 2)
        t += float(rng.exponential(1.0 / rate))
        tenant = int(rng.choice(spec.tenants, p=w))
        tail_cap = spec.max_prompt - spec.prefix_tokens
        tail = _heavy(rng, spec.tail_mean, spec.pareto_a, 1, tail_cap)
        prompt = prefixes[tenant] + rng.integers(
            1, spec.vocab, size=tail).tolist()
        max_new = _heavy(rng, spec.output_mean, spec.pareto_a, 1,
                         spec.max_new_cap)
        temperature, top_k = 0.0, None
        if spec.sample_frac and rng.random() < spec.sample_frac:
            temperature, top_k = spec.temperature, spec.top_k
        cancel = None
        if spec.cancel_frac and rng.random() < spec.cancel_frac \
                and max_new > 1:
            cancel = _heavy(rng, max(1.0, max_new / 3), spec.pareto_a,
                            1, max_new - 1)
        out.append(TrafficRequest(
            stream_id=i, t_arrival=t, tenant=tenant, prompt=prompt,
            max_new=max_new, temperature=temperature, top_k=top_k,
            cancel_after_tokens=cancel))
    return out
