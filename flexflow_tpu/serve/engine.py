"""ServeEngine: one jitted MIXED step over a paged KV-cache.

Wraps an LM built by models/transformer.build_transformer_lm into the
serving hot path. The default (chunked-prefill) engine runs ONE program:

  mixed — a fixed-width batch of `serve_prefill_budget + serve_max_seqs`
    LANES, each lane one (sequence, position) query token. Prompt
    chunks from any number of requests and the single decode token of
    every running sequence pack into the same step: K/V for all lanes
    scatters into each sequence's pages, then every lane attends
    through its page-table row masked at its own position + 1
    (kernels/flash_attention.paged_attention_ragged), so causality is
    exact and decode lanes never stall behind a long prompt. Logits
    reduce to a greedy argmax plus a static top-k head (for seeded
    temperature / top-k sampling) before leaving the device.

Static shapes are the whole game on TPU: the mixed step has ONE
geometry, so XLA compiles ONE serving program — ever. After `warmup()` a
serving process never recompiles (generate() can assert this via
`compile_counts()`), which is what keeps p99 latency flat. The PR 1
per-bucket prefill + full-width decode pair is retained behind
`serve_chunked_prefill=False` (FFConfig) as the legacy path.

Speculative decoding (serve/speculative.py, docs/serving.md) spends
spare prefill-budget lanes of the SAME program: a host-side drafter
appends up to `serve_spec_tokens` proposed tokens after a sequence's
decode lane, verification keeps the longest prefix matching what the
model would have emitted anyway (plus the correction/bonus token that
told us so), and rejected tokens' pages roll back — several tokens per
dispatch on repetitive text, token-identical output always, zero new
program shapes.

The engine owns a PERSISTENT PagedKVCache and device page arrays:
prefix pages committed by one generate() call are matchable by the
next, so a shared system preamble is computed once per process, not
once per batch. Caches flow functionally: the jitted steps take the
page arrays donated and return the updated ones, so the update is
in-place on device and the host never holds two copies.

The engine reads weights straight out of the compiled FFModel's
TrainState and re-implements the block math as pure functions — the
graph executor has no notion of carried state, and threading a cache
through it would force every op to learn about sequence position. The
ops' numerics are mirrored exactly (LayerNorm f32 statistics, f32
matmul accumulation), so `generate_reference` (naive no-cache
re-forward each step) produces identical greedy tokens — the parity
test, which holds through prefix-cache hits, chunked prefill, and
preemption/resume.
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import CompMode
from ..kernels.flash_attention import (paged_attention_decode,
                                       paged_attention_ragged)
from ..kernels.paged_ragged_v2 import (choose_block_kv,
                                       quantize_kv_rows,
                                       ragged_dispatch_passes)
from ..parallel.mesh import TENSOR
from ..utils.faults import FaultInjector, TransientError, injector_for
from ..utils.telemetry import (Telemetry, pow2_bucket, serve_metrics,
                               telemetry_for)
from .kv_cache import KVCacheConfig, PagedKVCache, kv_storage_dtype
from .scheduler import (ChunkPlan, ContinuousBatchingScheduler, Request,
                        RequestOutcome, RequestState, SampleParams)

# pad bias for vocab columns the head padding invents (vocab % t != 0):
# a padded logit must never win argmax or enter the top-k window
_PAD_LOGIT_BIAS = -1e30


class _CompileEvents:
    """Process-wide counter of ACTUAL XLA backend compiles, fed by
    jax.monitoring's public event stream (the
    '/jax/core/compile/backend_compile_duration' event fires once per
    backend compile and never on a jit-cache hit).

    This exists because the zero-recompile serving gate must not go
    vacuous: jit's `_cache_size` is a private API that has moved across
    jax versions, and a gate comparing "?" == "?" passes while the
    engine silently recompiles every step. The engine snapshots this
    counter around each jitted call and attributes any increment to
    that serving function — monkeypatch-free, and it catches even a
    same-signature recompile (e.g. a dropped jit cache) that a
    distinct-shape count would miss. Single listener per process;
    serving calls are not concurrent, so the around-call diff is
    race-free."""

    count = 0
    _installed: Optional[bool] = None

    @classmethod
    def install(cls) -> bool:
        if cls._installed is None:
            try:
                from jax import monitoring
                monitoring.register_event_duration_secs_listener(
                    cls._on_event)
                cls._installed = True
            except Exception:   # monitoring API absent on this jax
                cls._installed = False
        return cls._installed

    @staticmethod
    def _on_event(event: str, duration: float, **kwargs) -> None:
        if event == "/jax/core/compile/backend_compile_duration":
            _CompileEvents.count += 1


def _ln(p, x, eps):
    """LayerNorm with f32 statistics — must mirror ops/elementwise.py
    LayerNorm.forward exactly (the reference-parity contract)."""
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    y = y * p["scale"].astype(jnp.float32) + p["bias"].astype(jnp.float32)
    return y.astype(x.dtype)


def _dense(p, x, activation=None, psum_axis=None):
    """Dense layer. `psum_axis` is the tensor-parallel row-parallel
    hook: under sharding the kernel's CONTRACTION dim is sharded, so
    each device's matmul is a partial sum that all-reduces over the
    axis BEFORE the (replicated) bias — exactly the Megatron pattern
    the cost model prices. None (single device) is the unchanged
    bit-exact path."""
    y = jnp.dot(x, p["kernel"].astype(x.dtype),
                preferred_element_type=jnp.float32).astype(x.dtype)
    if psum_axis is not None:
        y = jax.lax.psum(y, psum_axis)
    if "bias" in p:
        y = y + p["bias"].astype(x.dtype)
    if activation == "relu":
        y = jax.nn.relu(y)
    return y


def probe_serve_arch(model, config=None, context=None):
    """The ServeArch a ServeEngine over ``model`` + ``config`` would
    price, WITHOUT building the engine — what ReplicaPool's 2-D mesh
    resolution (``--serve-replicas auto``) feeds
    search/serve_place.optimize_serve_mesh before any replica exists
    (the searched degree decides how the first engine is built, so
    the arch must be priceable engine-free). Same model introspection
    as ServeEngine._read_arch / serve_arch: decode lanes = the slot
    reserve, prefill lanes = the budget, steady-state context = 3/4
    of the learned positions, adapter-pool geometry from the
    --adapter-* knobs via AdapterConfig.from_ff."""
    from ..search.cost_model import ServeArch
    from .kv_cache import QUANTIZED_KV_DTYPES
    cfg = config if config is not None else model.config
    if model.state is None:
        from ..config import CompMode
        model.compile(comp_mode=CompMode.INFERENCE)
    ops = {op.name: op for op in model.ops}
    for required in ("tok_embed", "pos_embed", "lm_head"):
        if required not in ops:
            raise ValueError(
                f"serve placement needs a build_transformer_lm-shaped "
                f"model (missing op {required!r})")
    num_layers = 0
    while f"layer{num_layers}_attn" in ops:
        num_layers += 1
    if num_layers == 0:
        raise ValueError("model has no layer{i}_attn blocks")
    attn0 = ops["layer0_attn"]
    act_dtype = jnp.dtype(ops["tok_embed"].out_dtype)
    ff_dim = int(model.state.params["layer0_ff1"]["kernel"].shape[1])
    max_seq = int(ops["pos_embed"].num_entries)
    kv_name = str(getattr(cfg, "kv_dtype", "float32"))
    acfg = None
    if int(getattr(cfg, "adapter_rank", 0) or 0) > 0:
        from .adapters import AdapterConfig
        acfg = AdapterConfig.from_ff(
            cfg, num_layers=num_layers, hidden=attn0.embed_dim,
            num_heads=attn0.num_heads, head_dim=attn0.head_dim,
            ff_dim=ff_dim, act_itemsize=int(act_dtype.itemsize))
    return ServeArch(
        num_layers=num_layers, hidden=attn0.embed_dim,
        num_heads=attn0.num_heads, head_dim=attn0.head_dim,
        ff_dim=ff_dim, vocab=int(ops["tok_embed"].num_entries),
        decode_lanes=int(getattr(cfg, "serve_max_seqs", 8)),
        prefill_lanes=int(getattr(cfg, "serve_prefill_budget", 512)),
        context=int(context if context is not None
                    else max(1, max_seq * 3 // 4)),
        kv_dtype=kv_name,
        kv_itemsize=float(kv_storage_dtype(kv_name).itemsize),
        kv_scales=kv_name in QUANTIZED_KV_DTYPES,
        act_itemsize=float(act_dtype.itemsize),
        act_dtype=str(act_dtype.name),
        adapter_rank=acfg.rank if acfg is not None else 0,
        adapter_slots=acfg.num_slots if acfg is not None else 0)


class ServeEngine:
    """Continuous-batching generation over a build_transformer_lm model.

    model must be compiled (any comp_mode); if not, it is compiled here
    in INFERENCE mode (no optimizer slots). All serving knobs come from
    the model's FFConfig (kv_page_size / kv_num_pages / serve_max_seqs /
    serve_prefill_budget / serve_chunked_prefill / serve_prefix_cache /
    serve_admit_watermark); `chunked_prefill` / `prefix_cache` override
    the config (tools that A/B the optimisations build two engines over
    one model).
    """

    # static top-k head width: sampling draws from the top
    # min(TOPK_CAP, vocab) logits of a lane, so the sampled stream
    # leaves the device at fixed shape and the zero-recompile contract
    # survives sampling. top_k > this cap is rejected at generate().
    TOPK_CAP = 64

    # failure flight recorder thresholds: deadline expirations at ONE
    # chunk-boundary sweep that count as a storm (auto post-mortem),
    # and the minimum wall seconds between auto-triggered bundles (a
    # sustained failure produces one black box, not a disk flood)
    DEADLINE_STORM = 3
    POSTMORTEM_MIN_INTERVAL_S = 5.0

    def __init__(self, model, *, max_seq_len: Optional[int] = None,
                 use_pallas: Optional[bool] = None, interpret: bool = False,
                 chunked_prefill: Optional[bool] = None,
                 prefix_cache: Optional[bool] = None,
                 spec_tokens: Optional[int] = None,
                 drafter=None, faults: Optional[FaultInjector] = None,
                 mesh=None, tensor_parallel: Optional[int] = None,
                 telemetry: Optional[Telemetry] = None,
                 host_tier=None, config=None):
        if model.state is None:
            model.compile(comp_mode=CompMode.INFERENCE)
        self.model = model
        # an explicit `config` overrides the model's: how a
        # DisaggCluster gives each role its own serving knobs (prefill
        # budget, scrape endpoint) over ONE shared model
        self.config = config if config is not None else model.config
        self._use_pallas = use_pallas
        self._interpret = interpret
        self._read_arch(model)
        if max_seq_len is None:
            max_seq_len = self.max_positions
        if max_seq_len > self.max_positions:
            raise ValueError(
                f"max_seq_len {max_seq_len} exceeds the LM's learned "
                f"positions ({self.max_positions})")
        self._max_seq_len = int(max_seq_len)
        # tensor-parallel sharded serving (docs/serving.md "Sharded
        # serving"): an explicit `mesh` (1-D, axis "tensor") or
        # `tensor_parallel` degree wins; otherwise FFConfig.serve_mesh
        # resolves it — "auto" closes the paper's loop for inference by
        # asking the placement search (search/serve_place.optimize_serve)
        # which degree minimizes the simulated decode step.
        self._resolve_serve_mesh(mesh, tensor_parallel)
        self.cache_cfg = KVCacheConfig.from_ff(
            self.config, num_layers=self.num_layers,
            num_heads=self.num_heads, head_dim=self.head_dim,
            max_seq_len=max_seq_len, tensor_parallel=self.tp)
        self.cache_cfg.validate()
        cfg = self.config
        self.chunked_prefill = bool(
            getattr(cfg, "serve_chunked_prefill", True)
            if chunked_prefill is None else chunked_prefill)
        self.prefix_cache = bool(
            getattr(cfg, "serve_prefix_cache", True)
            if prefix_cache is None else prefix_cache) \
            and self.chunked_prefill
        self.prefill_budget = int(getattr(cfg, "serve_prefill_budget", 512))
        self.admit_watermark = float(
            getattr(cfg, "serve_admit_watermark", 0.02))
        # robustness (docs/robustness.md): deterministic fault injection
        # (config-scoped when FFConfig.fault_spec is set), bounded
        # retry-with-backoff around jitted dispatch, per-request
        # deadlines, host-side cancellation, and the scheduler's
        # degradation ladder
        self.faults = faults if faults is not None else injector_for(cfg)
        # observability (utils/telemetry.py, docs/observability.md):
        # per-request/per-step spans, the metrics registry, and the
        # simulator-drift calibrator. An explicit `telemetry` bus wins
        # (benches A/B on vs off over one config); else
        # FFConfig.telemetry / trace_out resolve one (off = the shared
        # disabled instance, one attribute read per site). All of it
        # is host-side: telemetry on vs off is token-identical with
        # zero recompiles (ci.sh step 1k gates <= 3% overhead).
        self.telemetry = telemetry if telemetry is not None \
            else telemetry_for(cfg)
        self.trace_out = getattr(cfg, "trace_out", None)
        # telemetry track process name: a ReplicaPool re-homes each
        # replica's tracks (set_track_process) so N replicas' spans
        # don't merge onto one "serve" track in the exported trace
        self._proc = "serve"
        self._ENGINE_TRACK = (self._proc, "engine")
        self._QUEUE_TRACK = (self._proc, "queue")
        # at most ONE live ServeSession owns the scheduler/slots at a
        # time (serve/router.py keeps one open per replica; generate()
        # opens and closes its own)
        self._session: Optional["ServeSession"] = None
        # (ctx bucket) -> (predicted step seconds, per-task-class
        # breakdown) | None when the cost stack cannot price it
        self._drift_cache: Dict[int, Optional[tuple]] = {}
        self._slot_tracks: List[tuple] = []  # interned per-slot track
        # pairs, so the per-step record path never rebuilds f-strings
        self.max_retries = int(getattr(cfg, "serve_max_retries", 3))
        self.retry_backoff = float(
            getattr(cfg, "serve_retry_backoff_s", 0.02))
        # failure flight recorder (docs/observability.md): when
        # postmortem_dir is set (implies telemetry via telemetry_for),
        # the engine dumps a bounded post-mortem bundle on fault-abort,
        # deadline storm, or rung-4 rejection — rate-limited so a
        # storm produces ONE bundle, not a disk flood. dump_postmortem
        # is the explicit trigger and ignores the rate limit.
        self.postmortem_dir = getattr(cfg, "postmortem_dir", None)
        self.postmortem_events = int(
            getattr(cfg, "postmortem_events", 2048))
        self._postmortem_seq = 0
        self._postmortem_last = -float("inf")
        # requests of the most recent generate()/session run, kept for
        # explain_request(rid) (rids restart per session, so this is
        # the last run's namespace); trace ids stay globally unique
        self._last_reqs: Dict[int, Request] = {}
        self.default_deadline = float(
            getattr(cfg, "serve_request_deadline", 0.0))
        self.degrade_ladder = bool(
            getattr(cfg, "serve_degrade_ladder", True))
        self.reject_stalls = int(getattr(cfg, "serve_reject_stalls", 0))
        self._retries = 0           # engine-lifetime retried dispatches
        self._cancels: set = set()  # rids cancel() marked, swept at
        self._active: Dict[int, Request] = {}   # chunk boundaries
        # speculative decoding (serve/speculative.py): max drafted
        # tokens per sequence per step. Needs the mixed program (draft
        # lanes are chunk lanes); 0 disables and the engine is
        # bit-for-bit the non-speculative one. `spec_tokens`/`drafter`
        # override the config for A/B benches and draft-LM plugins.
        if spec_tokens is None:
            spec_tokens = int(getattr(cfg, "serve_spec_tokens", 4)) \
                if getattr(cfg, "serve_spec_decode", True) else 0
        self.spec_tokens = int(spec_tokens) if self.chunked_prefill else 0
        self.drafter = drafter
        # KV-page storage format (serve/kv_cache.py, PR 8): lossless
        # f32 keeps the bit-exactness oracle; bf16 rounds on write
        # (exact when the engine's activations are already bf16); int8
        # quantizes on write against per-page scale arrays and the
        # ragged kernel dequantizes at read. kv_exact records whether
        # page storage preserves activation values bit-for-bit — the
        # condition for the token-identical-to-reference gate (lossy
        # formats gate bounded error + greedy parity instead,
        # tests/test_kv_quant.py).
        self.kv_dtype = self.cache_cfg.kv_dtype
        self.kv_quantized = self.cache_cfg.quantized
        self._kv_store_dtype = self.cache_cfg.storage_dtype
        self.kv_exact = (self.kv_dtype == "float32"
                         or self._kv_store_dtype == self.act_dtype)
        # tie margin of the relaxed quantized parity gate
        # (assert_token_parity): fp8's 3-bit mantissa rounds ~8x
        # coarser than int8's 127-step grid at amax scale
        self.kv_tie_margin = 0.25 if self.kv_dtype == "float8_e4m3" \
            else 0.05
        if self.kv_quantized and not self.chunked_prefill:
            raise ValueError(
                f"kv_dtype={self.kv_dtype!r} needs the chunked mixed "
                f"program (quantize-on-write lives in the mixed step); "
                f"the legacy bucket-prefill path supports "
                f"float32/bfloat16")
        if self.tp > 1 and not self.chunked_prefill:
            raise ValueError(
                "sharded serving (serve_mesh / tensor_parallel > 1) "
                "shards the ONE mixed program; the legacy bucket-"
                "prefill path is single-device only")
        # ragged kernel v2 kv-block shape: explicit knob, else the
        # autotune-by-shape table (kernels/paged_ragged_v2.py) — sized
        # for the PER-DEVICE head count, which is what the sharded
        # kernel actually streams
        self.attn_block_kv = int(getattr(cfg, "serve_attn_block_kv", 0)) \
            or choose_block_kv(self.cache_cfg.page_size,
                               self.cache_cfg.pages_per_seq,
                               self.cache_cfg.heads_per_device,
                               self.head_dim,
                               self.cache_cfg.kv_itemsize)
        # the one mixed-step geometry: every prefill-budget token plus
        # one decode lane per slot always fits
        self.mixed_width = self.prefill_budget + self.cache_cfg.max_seqs
        self.topk_cap = min(self.TOPK_CAP, self.vocab_size)
        # persistent across generate() calls: the prefix cache only
        # pays off if committed pages outlive the batch that wrote them
        self.cache = PagedKVCache(self.cache_cfg,
                                  prefix_cache=self.prefix_cache)
        # hierarchical prefix-cache tier (serve/host_tier.py): a
        # byte-budgeted host-RAM store below the HBM page pool. An
        # explicit `host_tier` (the ReplicaPool's SHARED store) wins;
        # else --host-tier-mb arms a private one. Needs the prefix
        # cache (a spilled page is reachable only through its chain
        # key). Eviction then QUEUES spills the session drains through
        # the fixed-shape export gather, and admission re-imports
        # priced host hits through the import scatter — zero new
        # compiles either way (warmup warms both programs).
        self.host_tier = None
        if self.prefix_cache and bool(
                getattr(cfg, "serve_host_tier", True)):
            if host_tier is not None:
                self.host_tier = host_tier
            elif float(getattr(cfg, "host_tier_mb", 0.0) or 0.0) > 0:
                from .host_tier import HostPageStore
                self.host_tier = HostPageStore(float(cfg.host_tier_mb))
        self.cache.host_tier = self.host_tier
        self._host_mm = None      # lazy machine model for DMA pricing
        self._host_reload_s = 0.0  # priced DMA seconds, pending step
        self._host_reload_stats = {"reload_events": 0,
                                   "reload_pages": 0,
                                   "spilled_pages": 0,
                                   "recompute_chosen": 0,
                                   "reload_priced_s": 0.0}
        self._k_pages = None
        self._v_pages = None
        self._k_scales = None
        self._v_scales = None
        # multi-tenant LoRA adapter pool (serve/adapters.py): fixed
        # rank-padded HBM slabs managed like the KV pool, slot 0 the
        # reserved all-zero base slab so base and adapted lanes mix in
        # the ONE mixed program. Armed by adapter_rank > 0; the slabs
        # flow READ-ONLY through the mixed step (gathered per lane,
        # never donated) and tenant loads run through one jitted
        # donating scatter ("adapter" in the compile accounting).
        self.adapters = None
        self.adapter_cfg = None
        self._adapter_slabs = None     # device pytree, lazy like pages
        self._adapter_specs = None     # PartitionSpec dict (tp > 1)
        self._adapter_shardings = None
        if int(getattr(cfg, "adapter_rank", 0) or 0) > 0:
            if not self.chunked_prefill:
                raise ValueError(
                    "adapter_rank > 0 needs the chunked mixed program "
                    "(the per-lane adapter gather lives in the mixed "
                    "step); the legacy bucket path serves base-only")
            from .adapters import AdapterConfig, AdapterPool
            self.adapter_cfg = AdapterConfig.from_ff(
                cfg, num_layers=self.num_layers, hidden=self.hidden,
                num_heads=self.num_heads, head_dim=self.head_dim,
                ff_dim=self._ff_pad,
                act_itemsize=int(self.act_dtype.itemsize),
                tensor_parallel=self.tp)
            self.adapters = AdapterPool(self.adapter_cfg)
            if self.tp > 1:
                from jax.sharding import NamedSharding
                from jax.sharding import PartitionSpec as P
                # B factors shard where their output dim does (heads /
                # padded ff), A factors contracting a sharded dim
                # (wo's heads, ff2's ff) shard on it; the rank-side
                # rest replicates — per-device deltas are then local
                # partials the existing psums complete exactly
                self._adapter_specs = {
                    "a_qkv": P(),
                    "b_qkv": P(None, None, None, None, TENSOR, None),
                    "a_wo": P(None, None, TENSOR, None, None),
                    "b_wo": P(),
                    "a_ff1": P(),
                    "b_ff1": P(None, None, None, TENSOR),
                    "a_ff2": P(None, None, TENSOR, None),
                    "b_ff2": P(),
                    "scale": P(),
                }
                self._adapter_shardings = {
                    k: NamedSharding(self.tp_mesh, s)
                    for k, s in self._adapter_specs.items()}
        # prompt-length buckets (legacy path + generate_reference):
        # powers of two from one page up to the serveable length. The
        # page-table ceiling rounds UP to whole pages, but a bucket
        # wider than max_seq_len would forward positions the model
        # never learned (and no admissible request can need)
        cap = min(self.cache_cfg.pages_per_seq * self.cache_cfg.page_size,
                  self.cache_cfg.max_seq_len)
        b = max(self.cache_cfg.page_size, 16)
        self.buckets = []
        while b < cap:
            self.buckets.append(b)
            b *= 2
        self.buckets.append(cap)
        # the mixed-step programs: single-device, or shard_map'd over
        # the serve mesh (same lane contract, same donation) — ONE
        # program either way, so the zero-recompile gate is unchanged
        if self.tp > 1:
            self._step_params, self._param_specs = self._shard_params()
            self._mixed_jit = jax.jit(self._mixed_tp_impl,
                                      donate_argnums=(1, 2))
            self._mixed_q_jit = jax.jit(self._mixed_q_tp_impl,
                                        donate_argnums=(1, 2, 3, 4))
        else:
            self._step_params = self.params
            self._mixed_jit = jax.jit(self._mixed_impl,
                                      donate_argnums=(1, 2))
            # quantized pools thread the scale arrays through the same
            # step, donated alongside the pages
            self._mixed_q_jit = jax.jit(self._mixed_q_impl,
                                        donate_argnums=(1, 2, 3, 4))
        self._prefill_jit = jax.jit(self._prefill_impl,
                                    donate_argnums=(1, 2))
        self._decode_jit = jax.jit(self._decode_impl, donate_argnums=(1, 2))
        self._forward_jit = jax.jit(self._forward_logits)  # naive reference
        if self.adapters is not None:
            # the on-demand tenant load: donate-in-place row write into
            # the slabs, ONE program for every (slot, tenant) — the
            # admission stall is a dispatch, never a recompile
            self._adapter_load_jit = jax.jit(
                self._adapter_load_impl, donate_argnums=(0,),
                out_shardings=self._adapter_shardings)
        # disaggregated page handoff (serve/disagg.py): fixed-shape
        # gather/scatter programs moving whole page rows (values +
        # scale rows on quantized pools) between this engine's pool
        # and the host. The page-index vector is padded to
        # pages_per_seq with 0 — the sink-page convention — so ONE
        # program geometry serves every shipment size and the
        # zero-recompile contract extends to handoff traffic. Import
        # donates the pool arrays exactly like the mixed step.
        self._n_pools = 4 if self.kv_quantized else 2
        _imp_donate = tuple(range(1, 1 + self._n_pools))
        if self.tp > 1:
            self._export_jit = jax.jit(self._export_tp_impl,
                                       static_argnums=(0,))
            self._import_jit = jax.jit(self._import_tp_impl,
                                       static_argnums=(0,),
                                       donate_argnums=_imp_donate)
        else:
            self._export_jit = jax.jit(self._export_impl,
                                       static_argnums=(0,))
            self._import_jit = jax.jit(self._import_impl,
                                       static_argnums=(0,),
                                       donate_argnums=_imp_donate)
        # per-function compile accounting, owned by the ProgramRegistry
        # (core/programs.py): every serving dispatch resolves through
        # registry.call, which AOT-compiles on a new argument signature
        # and counts EXACTLY — no monitoring-snapshot coverage gap on
        # compiles inside warmup_handoff / adapter load — and which
        # restores serialized executables from --program-cache-dir so a
        # cold replica boots warm (zero compiles). `_compiles` stays
        # the registry's live per-family dict (test/bench API compat);
        # `_events_ok` is always True now that counting is exact.
        from ..core.programs import ProgramRegistry
        self.programs = ProgramRegistry(
            self._program_fingerprint(),
            cache_dir=getattr(cfg, "program_cache_dir", None))
        for fam in ("prefill", "decode", "mixed", "adapter"):
            self.programs.register(fam)
        # export/import carry the pool count as a static argnum: its
        # VALUE keys the cache and is stripped at executable dispatch
        self.programs.register("export", static_argnums=(0,))
        self.programs.register("import", static_argnums=(0,))
        self.programs_restored = self.programs.load_warm()
        self._events_ok = True
        self._compiles = self.programs._compiles
        self.boot_stats: Optional[dict] = None
        self.last_stats: Optional[dict] = None
        # live scrape endpoint (--metrics-port, docs/observability.md):
        # /metrics serves the engine-lifetime registry as Prometheus
        # text, /healthz liveness — the autoscaler's poll target.
        # Started LAST (a construction failure above must not leak a
        # bound port/thread), stopped by close(); scrapes read the
        # registry from the server thread, never touching the serving
        # hot path.
        self.metrics_server = None
        mport = getattr(cfg, "metrics_port", None)
        if mport is not None:
            from ..utils.telemetry import MetricsServer
            self.metrics_server = MetricsServer(
                self.telemetry.to_prometheus, port=int(mport),
                host=str(getattr(cfg, "metrics_host", "127.0.0.1")))

    def _call_counted(self, name, fn, *args):
        attempt = 0
        while True:
            try:
                # fault-injection site: serve.mixed / serve.prefill /
                # serve.decode, fired at the dispatch boundary (BEFORE
                # the jitted call, so donated buffers are untouched
                # when an injected fault raises)
                self.faults.fire(f"serve.{name}")
                # the registry resolves (family, argument signature) to
                # a compiled executable: hit -> dispatch (possibly an
                # executable deserialized at boot — the warm path),
                # miss -> AOT lower().compile(), timed and counted
                out = self.programs.call(name, fn, *args)
                break
            except TransientError:
                # bounded retry-with-backoff: transient dispatch faults
                # (injected chaos, a flaky device tunnel) are absorbed
                # here instead of failing the batch. Only retry while
                # the donated page arrays are still live — a dispatch
                # that consumed them before dying cannot be redone.
                attempt += 1
                if attempt > self.max_retries or any(
                        a.is_deleted() for a in args
                        if hasattr(a, "is_deleted")):
                    raise
                self._retries += 1
                if self.telemetry.enabled:
                    self.telemetry.instant(
                        self._ENGINE_TRACK, "retry",
                        args={"site": f"serve.{name}",
                              "attempt": attempt})
                if self.retry_backoff:
                    tb = time.perf_counter()
                    time.sleep(self.retry_backoff * (2 ** (attempt - 1)))
                    if self.telemetry.enabled:
                        # the backoff is dead time EVERY request in
                        # this step pays: a complete span (not an
                        # instant) so explain_request can carve it out
                        # of the covering chunk spans as "retry"
                        self.telemetry.span(
                            self._ENGINE_TRACK, "retry_backoff", tb,
                            time.perf_counter(),
                            args={"site": f"serve.{name}",
                                  "attempt": attempt})
        return out

    def _program_fingerprint(self) -> Dict:
        """The cache identity of this engine's program set: everything
        that shapes or numbers a serving executable. Two engines with
        equal fingerprints compile bit-identical programs (the AOT
        snapshot in --program-cache-dir is keyed on its hash); flipping
        ANY folded field — kv dtype, adapter rank, tp degree, the jax
        version — must miss the cache (tests/test_programs.py pins
        each)."""
        c = self.cache_cfg
        ac = self.adapter_cfg
        return {
            "kind": "serve",
            "jax": jax.__version__,
            "backend": jax.default_backend(),
            "devices": jax.device_count(),
            "num_layers": self.num_layers,
            "hidden": self.hidden,
            "num_heads": self.num_heads,
            "head_dim": self.head_dim,
            "ff_pad": self._ff_pad,
            "vocab": self.vocab_size,
            "max_positions": self.max_positions,
            "layer_norm": self.layer_norm,
            "act_dtype": str(self.act_dtype),
            "max_seq_len": self._max_seq_len,
            "chunked_prefill": self.chunked_prefill,
            "prefill_budget": self.prefill_budget,
            "mixed_width": self.mixed_width,
            "topk_cap": self.topk_cap,
            "buckets": tuple(self.buckets),
            "kv_dtype": self.kv_dtype,
            "kv_store_dtype": str(self._kv_store_dtype),
            "page_size": c.page_size,
            "pages_per_seq": c.pages_per_seq,
            "num_pages": c.num_pages,
            "max_seqs": c.max_seqs,
            "attn_block_kv": self.attn_block_kv,
            "adapter_rank": 0 if ac is None else ac.rank,
            "adapter_slots": 0 if ac is None else ac.num_slots,
            "tp": self.tp,
            "use_pallas": bool(self._use_pallas),
            "interpret": bool(self._interpret),
        }

    # ---------------- model introspection -----------------------------
    def _read_arch(self, model) -> None:
        ops = {op.name: op for op in model.ops}
        for required in ("tok_embed", "pos_embed", "lm_head"):
            if required not in ops:
                raise ValueError(
                    f"ServeEngine needs a build_transformer_lm-shaped "
                    f"model (missing op {required!r})")
        self.vocab_size = ops["tok_embed"].num_entries
        self.max_positions = ops["pos_embed"].num_entries
        self.layer_norm = "layer0_ln1" in ops
        self.num_layers = 0
        while f"layer{self.num_layers}_attn" in ops:
            self.num_layers += 1
        if self.num_layers == 0:
            raise ValueError("model has no layer{i}_attn blocks")
        attn0 = ops[f"layer{0}_attn"]
        if not attn0.causal:
            raise ValueError("serving needs causal attention blocks")
        self.num_heads = attn0.num_heads
        self.head_dim = attn0.head_dim
        self.hidden = attn0.embed_dim
        self.ln_eps = ops["layer0_ln1"].eps if self.layer_norm else 1e-5
        # serving activation dtype = whatever the LM graph's embeddings
        # emit (build_transformer_lm wires FFConfig.compute_dtype here):
        # every block below follows its input dtype, so a bf16 LM
        # serves bf16 end-to-end — and generate_reference embeds
        # through the SAME cast, so the greedy parity oracle holds at
        # the engine's own precision. KV pages keep their configured
        # (f32) dtype: bf16 K/V upcasts exactly, so cached and
        # recomputed attention stay bit-identical.
        self.act_dtype = jnp.dtype(ops["tok_embed"].out_dtype)
        self.params = model.state.params  # live references, not copies
        self.ff_dim = int(self.params["layer0_ff1"]["kernel"].shape[1])

    # ---------------- tensor-parallel sharding -------------------------
    def _resolve_serve_mesh(self, mesh, tensor_parallel) -> None:
        """Resolve (tp, tp_mesh) from the explicit args or
        FFConfig.serve_mesh ('' = single device, 'N' = degree N,
        'auto' = the placement search picks)."""
        cfg = self.config
        self.serve_placement = None  # set by the 'auto' path below
        if mesh is None and tensor_parallel is None:
            sm = str(getattr(cfg, "serve_mesh", "") or "").strip()
            if sm == "auto":
                from ..search.serve_place import optimize_serve
                place = optimize_serve(self.serve_arch(),
                                       len(jax.devices()), config=cfg)
                self.serve_placement = place
                tensor_parallel = place.tensor_parallel
            elif sm:
                tensor_parallel = int(sm)
        self.tp = 1
        self.tp_mesh = None
        if mesh is not None:
            if TENSOR not in mesh.shape:
                raise ValueError(
                    f"serve mesh needs a {TENSOR!r} axis, got "
                    f"{dict(mesh.shape)}")
            self.tp = int(mesh.shape[TENSOR])
            self.tp_mesh = mesh if self.tp > 1 else None
        elif tensor_parallel is not None and int(tensor_parallel) > 1:
            from ..parallel.mesh import serve_tensor_mesh
            self.tp = int(tensor_parallel)
            self.tp_mesh = serve_tensor_mesh(self.tp)
        if self.tp > 1 and self.num_heads % self.tp != 0:
            raise ValueError(
                f"sharded serving needs num_heads ({self.num_heads}) "
                f"divisible by the tensor degree ({self.tp})")
        # ff/vocab need not divide: their shards PAD (zero ff columns
        # contribute exact zeros; pad vocab columns carry a -1e30 bias
        # so they never win argmax) — exactness is unaffected
        self._ff_pad = -(-self.ff_dim // self.tp) * self.tp
        self._vocab_pad = -(-self.vocab_size // self.tp) * self.tp

    def serve_arch(self, context: Optional[int] = None):
        """The ServeArch the placement search prices for this engine's
        model + serving knobs (search/cost_model.serve_step_tasks):
        decode lanes = the slot reserve, prefill lanes = the budget,
        steady-state context defaulting to 3/4 of the serveable length,
        KV traffic at the configured page format's itemsize."""
        from ..search.cost_model import ServeArch
        cfg = self.config
        kv_name = str(getattr(cfg, "kv_dtype", "float32"))
        from .kv_cache import QUANTIZED_KV_DTYPES
        # adapter-pool pricing terms: the armed engine's true pool
        # geometry, or (on the serve_mesh=auto path, which prices the
        # arch BEFORE the pool exists) an unsharded estimate from the
        # same from_ff sizing — the search sees the residency cost it
        # is trading tensor degree against
        acfg = getattr(self, "adapter_cfg", None)
        if acfg is None and int(getattr(cfg, "adapter_rank", 0) or 0) > 0:
            from .adapters import AdapterConfig
            acfg = AdapterConfig.from_ff(
                cfg, num_layers=self.num_layers, hidden=self.hidden,
                num_heads=self.num_heads, head_dim=self.head_dim,
                ff_dim=self.ff_dim,
                act_itemsize=int(self.act_dtype.itemsize))
        return ServeArch(
            num_layers=self.num_layers, hidden=self.hidden,
            num_heads=self.num_heads, head_dim=self.head_dim,
            ff_dim=self.ff_dim, vocab=self.vocab_size,
            decode_lanes=int(getattr(cfg, "serve_max_seqs", 8)),
            prefill_lanes=int(getattr(cfg, "serve_prefill_budget", 512)),
            context=int(context if context is not None
                        else max(1, self._max_seq_len * 3 // 4)),
            kv_dtype=kv_name,
            kv_itemsize=float(kv_storage_dtype(kv_name).itemsize),
            kv_scales=kv_name in QUANTIZED_KV_DTYPES,
            act_itemsize=float(self.act_dtype.itemsize),
            act_dtype=str(self.act_dtype.name),
            adapter_rank=acfg.rank if acfg is not None else 0,
            adapter_slots=acfg.num_slots if acfg is not None else 0)

    def _shard_params(self):
        """Shard (and where needed pad) the LM parameters over the
        serve mesh, returning (params, PartitionSpec pytree):

          wq/wk/wv (E, H, D)  -> heads column-parallel
          wo       (H, D, E)  -> heads row-parallel (psum after)
          ff1      (E, F)     -> column-parallel (+ bias shard)
          ff2      (F, E)     -> row-parallel (psum before bias)
          lm_head  (E, V)     -> vocab column-parallel (all-gather at
                                 the logits; pad columns biased -inf)
          tok_embed (V, E)    -> vocab row-parallel (masked local
                                 gather + exact psum — one device owns
                                 each row, the rest contribute 0.0)
          everything else     -> replicated (LNs, pos_embed, biases)

        The originals in self.params stay untouched — the reference
        paths (generate_reference, assert_token_parity's margin
        forward) keep running single-device on them."""
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P
        mesh = self.tp_mesh

        def pad_to(a, axis, size, value=0.0):
            extra = size - a.shape[axis]
            if extra <= 0:
                return a
            widths = [(0, 0)] * a.ndim
            widths[axis] = (0, extra)
            return jnp.pad(a, widths, constant_values=value)

        def put(a, *spec):
            return jax.device_put(a, NamedSharding(mesh, P(*spec)))

        out: Dict[str, dict] = {}
        specs: Dict[str, dict] = {}
        for name, p in self.params.items():
            o, s = {}, {}
            for key, arr in p.items():
                arr = jnp.asarray(arr)
                spec = ()
                if name == "tok_embed" and key == "kernel":
                    arr = pad_to(arr, 0, self._vocab_pad)
                    spec = (TENSOR,)
                elif name.endswith("_attn") and key in ("wq", "wk",
                                                        "wv"):
                    spec = (None, TENSOR)
                elif name.endswith("_attn") and key == "wo":
                    spec = (TENSOR,)
                elif name.endswith("_ff1") and key == "kernel":
                    arr = pad_to(arr, 1, self._ff_pad)
                    spec = (None, TENSOR)
                elif name.endswith("_ff1") and key == "bias":
                    arr = pad_to(arr, 0, self._ff_pad)
                    spec = (TENSOR,)
                elif name.endswith("_ff2") and key == "kernel":
                    arr = pad_to(arr, 0, self._ff_pad)
                    spec = (TENSOR,)
                elif name == "lm_head" and key == "kernel":
                    arr = pad_to(arr, 1, self._vocab_pad)
                    spec = (None, TENSOR)
                elif name == "lm_head" and key == "bias":
                    arr = pad_to(arr, 0, self._vocab_pad,
                                 value=_PAD_LOGIT_BIAS)
                    spec = (TENSOR,)
                o[key] = put(arr, *spec)
                s[key] = P(*spec)
            if name == "lm_head" and "bias" not in p \
                    and self._vocab_pad > self.vocab_size:
                # padded vocab columns must never win argmax:
                # synthesize a bias (+0.0 on real columns is exact)
                b = jnp.zeros((self._vocab_pad,), self.act_dtype)
                b = b.at[self.vocab_size:].set(_PAD_LOGIT_BIAS)
                o["bias"] = put(b, TENSOR)
                s["bias"] = P(TENSOR)
            out[name], specs[name] = o, s
        return out, specs

    def _page_shardings(self):
        """(page, scale) NamedShardings over the serve mesh's head
        axis, or (None, None) single-device."""
        if self.tp_mesh is None:
            return None, None
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P
        return (NamedSharding(self.tp_mesh,
                              P(None, None, None, TENSOR, None)),
                NamedSharding(self.tp_mesh, P(None, None, None, TENSOR)))

    def _sharding_stats(self) -> Optional[dict]:
        """The last_stats/serve_report sharding block: mesh shape,
        heads per device, per-device KV pool bytes, and the analytic
        per-step collective payload (2 all-reduces of the lane
        activations per layer + the embedding psum + the final logits
        all-gather)."""
        if self.tp <= 1:
            return None
        c = self.cache_cfg
        T = self.mixed_width
        act = int(self.act_dtype.itemsize)
        coll = ((2 * self.num_layers + 1) * T * self.hidden * act
                + T * self._vocab_pad * act)
        return {
            "mesh": {TENSOR: self.tp},
            "tensor_parallel": self.tp,
            "heads_per_device": self.num_heads // self.tp,
            "kv_pool_device_bytes": int(c.pool_device_bytes),
            "collective_bytes_per_step": int(coll),
        }

    def mixed_step_cost_analysis(self) -> Optional[dict]:
        """XLA's own cost analysis of the compiled mixed program — the
        PER-DEVICE program under sharding, so serve_bench's sharded
        FLOPs-per-device gate reads a measured number, not the analytic
        formula it is checking. Lowers the engine's mixed step at its
        fixed geometry over abstract ShapeDtypeStructs for the pool
        operands (AOT — nothing executes, and no duplicate KV pool is
        materialized next to the resident one) and returns the
        backend's dict ({'flops': ...,} etc.), or None where the
        backend doesn't implement cost analysis. The AOT compile is
        out-of-band of `_call_counted`'s per-program snapshots, but
        call it outside timed/recompile-gated regions anyway."""
        c = self.cache_cfg
        T = self.mixed_width
        page_sh, scale_sh = self._page_shardings()
        pool = jax.ShapeDtypeStruct(
            (c.num_layers, c.num_pages, c.page_size, c.num_heads,
             c.head_dim), c.storage_dtype, sharding=page_sh)
        i32 = jnp.int32
        lane = jnp.zeros((T,), i32)
        args = (self._step_params, pool, pool)
        jitted = self._mixed_jit
        if self.kv_quantized:
            scales = jax.ShapeDtypeStruct(
                c.scale_shape, jnp.float32, sharding=scale_sh)
            args += (scales, scales)
            jitted = self._mixed_q_jit
        args += (lane, lane, lane, lane,
                 jnp.zeros((c.max_seqs, c.pages_per_seq), i32),
                 lane, lane)
        if self.adapters is not None:
            slabs = {
                key: jax.ShapeDtypeStruct(
                    shape,
                    jnp.float32 if key == "scale" else self.act_dtype,
                    sharding=(self._adapter_shardings or {}).get(key))
                for key, shape in self._adapter_slab_shapes().items()}
            args += (lane, slabs)
        else:
            args += (None, None)
        try:
            ca = jitted.lower(*args).compile().cost_analysis()
        except (NotImplementedError, jax.errors.JaxRuntimeError):
            return None
        if isinstance(ca, (list, tuple)):  # older jax: one per device
            ca = ca[0] if ca else None
        return dict(ca) if ca else None

    # ---------------- pure block math ----------------------------------
    def _embed(self, params, tokens, positions):
        # mode="clip": padded lanes/positions past the learned tables
        # must read SOME finite row — they are masked or never read
        # back, but jnp.take's "fill" OOB default yields NaN, and a
        # NaN K/V poisons every lane that softmax-weights it (0 * NaN
        # = NaN survives the causal mask's zeroed probability). Bit
        # for bit identical for all in-range indices. (The same OOB
        # trap as ops/embedding's flat slot-offset gather, PR 2.)
        te = jnp.take(params["tok_embed"]["kernel"], tokens, axis=0,
                      mode="clip")
        pe = jnp.take(params["pos_embed"]["kernel"], positions, axis=0,
                      mode="clip")
        return (te + pe).astype(self.act_dtype)

    def _attn_qkv(self, p, h, lora=None):
        """h (..., E) -> q, k, v (..., H, D). `lora` (mixed step only,
        h is (T, E)) is the lanes' gathered per-layer adapter rows
        (a_qkv (T, 3, E, r), b_qkv (T, 3, r, H[/t], D), scale (T,)):
        each lane adds ITS tenant's low-rank delta; slot-0 lanes gather
        the zero slab and their delta is exactly 0.0."""
        q = jnp.einsum("...e,ehd->...hd", h, p["wq"].astype(h.dtype))
        k = jnp.einsum("...e,ehd->...hd", h, p["wk"].astype(h.dtype))
        v = jnp.einsum("...e,ehd->...hd", h, p["wv"].astype(h.dtype))
        if lora is not None:
            aq, bq, s = lora
            u = jnp.einsum("te,tjer->tjr", h, aq.astype(h.dtype))
            d = jnp.einsum("tjr,tjrhd->tjhd", u, bq.astype(h.dtype))
            d = d * s.astype(h.dtype)[:, None, None, None]
            q = q + d[:, 0]
            k = k + d[:, 1]
            v = v + d[:, 2]
        return q, k, v

    def _attn_out(self, p, o, x, psum_axis=None, lora=None):
        y = jnp.einsum("...hd,hde->...e", o, p["wo"].astype(o.dtype))
        if lora is not None:
            # a_wo contracts the (sharded) head dim, so under tp the
            # delta is a local partial the psum below completes —
            # exact by linearity
            a, b, s = lora
            u = jnp.einsum("thd,thdr->tr", o, a.astype(o.dtype))
            y = y + jnp.einsum("tr,tre->te", u, b.astype(o.dtype)) \
                * s.astype(o.dtype)[:, None]
        if psum_axis is not None:
            # head-row-parallel wo: each device contracted its H/t
            # heads; the all-reduce completes the sum (Megatron)
            y = jax.lax.psum(y, psum_axis)
        if "bo" in p:
            y = y + p["bo"].astype(y.dtype)
        return x + y

    def _ffn(self, params, i, x, psum_axis=None, lora=None):
        h = _ln(params[f"layer{i}_ln2"], x, self.ln_eps) \
            if self.layer_norm else x
        if lora is None:
            h = _dense(params[f"layer{i}_ff1"], h, activation="relu")
            h = _dense(params[f"layer{i}_ff2"], h, psum_axis=psum_axis)
            return x + h
        # adapted FFN: ff1's delta lands PRE-activation (the merged
        # reference folds A@B into the kernel, which relu then sees)
        # and ff2's delta is a pre-psum local partial like wo's
        a1, b1, a2, b2, s = lora
        s = s.astype(h.dtype)
        p1 = params[f"layer{i}_ff1"]
        z = jnp.dot(h, p1["kernel"].astype(h.dtype),
                    preferred_element_type=jnp.float32).astype(h.dtype)
        u1 = jnp.einsum("te,ter->tr", h, a1.astype(h.dtype))
        z = z + jnp.einsum("tr,trf->tf", u1, b1.astype(h.dtype)) \
            * s[:, None]
        if "bias" in p1:
            z = z + p1["bias"].astype(z.dtype)
        h2 = jax.nn.relu(z)
        p2 = params[f"layer{i}_ff2"]
        y = jnp.dot(h2, p2["kernel"].astype(h2.dtype),
                    preferred_element_type=jnp.float32).astype(h2.dtype)
        u2 = jnp.einsum("tf,tfr->tr", h2, a2.astype(h2.dtype))
        y = y + jnp.einsum("tr,tre->te", u2, b2.astype(h2.dtype)) \
            * s[:, None]
        if psum_axis is not None:
            y = jax.lax.psum(y, psum_axis)
        if "bias" in p2:
            y = y + p2["bias"].astype(y.dtype)
        return x + y

    def _head(self, params, x):
        if self.layer_norm:
            x = _ln(params["final_ln"], x, self.ln_eps)
        return _dense(params["lm_head"], x)

    # ---------------- sharded block math (inside shard_map) ------------
    def _embed_tp(self, params, tokens, positions, axis):
        """Vocab-row-sharded token embedding: each device gathers the
        rows it owns and contributes exact 0.0 for the rest, so the
        psum reproduces the unsharded rows BIT-identically (x + 0.0 is
        exact — the one cross-device sum in the program with no
        rounding cost). The same OOB discipline as ops/embedding's
        flat slot-offset gather (_slot_gather): local indices clamp
        in-range so no lane ever reads a NaN 'fill' row, and the mask
        zeroes anything the clamp aliased. pos_embed is replicated
        (positions are tiny next to vocab)."""
        kern = params["tok_embed"]["kernel"]          # (Vp/t, E) local
        rows = kern.shape[0]
        lo = jax.lax.axis_index(axis) * rows
        idx = tokens - lo
        te = jnp.take(kern, jnp.clip(idx, 0, rows - 1), axis=0)
        te = jnp.where(((idx >= 0) & (idx < rows))[:, None], te, 0)
        te = jax.lax.psum(te, axis)
        pe = jnp.take(params["pos_embed"]["kernel"], positions, axis=0,
                      mode="clip")
        return (te + pe).astype(self.act_dtype)

    def _head_tp(self, params, x, axis):
        """Vocab-column-sharded head: each device computes its V/t
        logit columns (full contraction over E — no partial sums) and
        ONE all-gather assembles the (T, vocab_pad) logits, replicated,
        for the argmax/top-k tail. This is the program's only
        all-gather — the 'sharded vocab, gather only at the final
        logits' contract."""
        if self.layer_norm:
            x = _ln(params["final_ln"], x, self.ln_eps)
        local = _dense(params["lm_head"], x)           # (T, Vp/t)
        return jax.lax.all_gather(local, axis, axis=1, tiled=True)

    # ---------------- full-sequence forward (prefill + reference) ------
    def _forward_tokens(self, params, tokens, length, kv=None):
        """Causal forward over (1, S) padded tokens; returns the
        logits of position length-1 plus the (possibly updated)
        caches. `kv = (k_pages, v_pages, pt_row)` scatters each
        layer's K/V into the sequence's pages on the way through
        (legacy prefill); kv=None is the pure no-cache forward (the
        naive reference) — ONE implementation so the parity oracle and
        the legacy serving path can never drift apart."""
        ps = self.cache_cfg.page_size
        s = tokens.shape[1]
        positions = jnp.arange(s, dtype=jnp.int32)[None, :]
        x = self._embed(params, tokens, positions)        # (1, S, E)
        if kv is not None:
            k_pages, v_pages, pt_row = kv
            pages = jnp.take(pt_row, positions[0] // ps)  # (S,)
            offs = positions[0] % ps
        scale = 1.0 / np.sqrt(self.head_dim)
        causal = jnp.tril(jnp.ones((s, s), dtype=bool))
        for i in range(self.num_layers):
            p = params[f"layer{i}_attn"]
            h = _ln(params[f"layer{i}_ln1"], x, self.ln_eps) \
                if self.layer_norm else x
            q, k, v = self._attn_qkv(p, h)                # (1, S, H, D)
            if kv is not None:
                k_pages = k_pages.at[i, pages, offs].set(
                    k[0].astype(k_pages.dtype))
                v_pages = v_pages.at[i, pages, offs].set(
                    v[0].astype(v_pages.dtype))
            logits = jnp.einsum("bihd,bjhd->bhij", q, k,
                                preferred_element_type=jnp.float32) * scale
            logits = jnp.where(causal, logits, -jnp.inf)
            # probs STAY f32 through the p.v product — the paged
            # kernels' convention (_paged_online_page: "p stays f32 and
            # v upcasts") — so a bf16 engine's reference forward and
            # its paged path diverge only at f32 epsilon, not at bf16
            # prob-rounding scale (which flips greedy argmaxes). For
            # f32 engines this is bit-identical to rounding probs.
            probs = jax.nn.softmax(logits, axis=-1)
            o = jnp.einsum("bhij,bjhd->bihd", probs,
                           v.astype(jnp.float32),
                           preferred_element_type=jnp.float32
                           ).astype(x.dtype)
            x = self._attn_out(p, o, x)
            x = self._ffn(params, i, x)
        logits = self._head(params, x)                    # (1, S, V)
        last = jnp.take(logits[0], length - 1, axis=0)    # (V,)
        return last, (None if kv is None else (k_pages, v_pages))

    # ---------------- the mixed step (chunked prefill + decode) --------
    def _mixed_impl(self, params, k_pages, v_pages, tokens, positions,
                    write_pages, write_offs, page_tables, lane_slots,
                    lane_lens, lane_adapters=None, adapters=None):
        """ONE serving step over `mixed_width` LANES. Per lane (all
        (T,) int32, HOST-built): the token to embed, its position, the
        physical (page, offset) its K/V lands in (inactive lanes aim at
        the sink page 0), the page-table row it reads
        (lane_slots -> page_tables (max_seqs, pages_per_seq)) and its
        visible length (position + 1; inactive lanes clamp to 1 so the
        masked softmax stays NaN-free). All lanes' K/V is scattered
        per layer BEFORE attention, so chunk tokens of one sequence see
        each other causally and decode lanes see every prefix page —
        including pages another request's chunk computes in this very
        step (the intra-step prefix-sharing contract,
        serve/scheduler.py). Inactive lanes compute garbage the host
        never reads. Returns (greedy (T,), top-k values (T, K), top-k
        ids (T, K), k_pages, v_pages) — the static top-k head feeds
        host-side seeded sampling without shipping (T, vocab) logits."""
        out, (k_pages, v_pages) = self._mixed_body(
            params, k_pages, v_pages, None, None, tokens, positions,
            write_pages, write_offs, page_tables, lane_slots, lane_lens,
            lane_adapters=lane_adapters, adapters=adapters)
        return (*out, k_pages, v_pages)

    def _mixed_q_impl(self, params, k_pages, v_pages, k_scales, v_scales,
                      tokens, positions, write_pages, write_offs,
                      page_tables, lane_slots, lane_lens,
                      lane_adapters=None, adapters=None):
        """The mixed step over an int8 page pool: identical lane
        contract, but every lane's K/V row quantizes on write (per-row
        amax scale into the per-page scale arrays) and the ragged
        kernel dequantizes at read. Scale arrays are donated and
        returned like the page arrays."""
        out, (k_pages, v_pages, k_scales, v_scales) = self._mixed_body(
            params, k_pages, v_pages, k_scales, v_scales, tokens,
            positions, write_pages, write_offs, page_tables, lane_slots,
            lane_lens, lane_adapters=lane_adapters, adapters=adapters)
        return (*out, k_pages, v_pages, k_scales, v_scales)

    # ---------------- the sharded mixed step ---------------------------
    def _tp_step_specs(self, quantized: bool):
        """(in_specs, out_specs) of the shard_map'd mixed step: params
        per _shard_params, pages/scales on the head axis, every host-
        built lane array replicated, the emitted token streams
        replicated (psum/all-gather results are)."""
        from jax.sharding import PartitionSpec as P
        page = P(None, None, None, TENSOR, None)
        scl = P(None, None, None, TENSOR)
        rep = P()
        ins = (self._param_specs, page, page)
        if quantized:
            ins += (scl, scl)
        ins += (rep,) * 7
        # adapter operands: lane slot indices replicated; the slab
        # dict per _adapter_specs (unarmed engines pass None — an
        # empty pytree any prefix spec matches)
        ins += (rep, self._adapter_specs
                if self._adapter_specs is not None else rep)
        outs = (rep, rep, rep, page, page)
        if quantized:
            outs += (scl, scl)
        return ins, outs

    def _mixed_tp_impl(self, params, k_pages, v_pages, tokens, positions,
                       write_pages, write_offs, page_tables, lane_slots,
                       lane_lens, lane_adapters=None, adapters=None):
        """The mixed step shard_map'd over the serve mesh: identical
        lane contract and donation; each device runs _mixed_body on its
        H/t heads of the params and pages (tp_axis threads the psums /
        all-gather). check_vma off: the replicated outputs come out of
        collectives, which the static replication checker cannot always
        see through."""
        from ..parallel._compat import shard_map
        ins, outs = self._tp_step_specs(False)

        def body(params, kp, vp, tokens, positions, write_pages,
                 write_offs, page_tables, lane_slots, lane_lens,
                 lane_adapters, adapters):
            out, (kp, vp) = self._mixed_body(
                params, kp, vp, None, None, tokens, positions,
                write_pages, write_offs, page_tables, lane_slots,
                lane_lens, lane_adapters=lane_adapters,
                adapters=adapters, tp_axis=TENSOR)
            return (*out, kp, vp)

        return shard_map(body, mesh=self.tp_mesh, in_specs=ins,
                         out_specs=outs, check_vma=False)(
            params, k_pages, v_pages, tokens, positions, write_pages,
            write_offs, page_tables, lane_slots, lane_lens,
            lane_adapters, adapters)

    def _mixed_q_tp_impl(self, params, k_pages, v_pages, k_scales,
                         v_scales, tokens, positions, write_pages,
                         write_offs, page_tables, lane_slots, lane_lens,
                         lane_adapters=None, adapters=None):
        """The quantized mixed step over the serve mesh: scale arrays
        shard on the same head axis as the pages, and per-row
        quantization is per-head — so each device's quantized rows are
        BIT-identical to the unsharded engine's rows for those heads
        (the execution-path-invariance contract transfers verbatim)."""
        from ..parallel._compat import shard_map
        ins, outs = self._tp_step_specs(True)

        def body(params, kp, vp, ks, vs, tokens, positions, write_pages,
                 write_offs, page_tables, lane_slots, lane_lens,
                 lane_adapters, adapters):
            out, (kp, vp, ks, vs) = self._mixed_body(
                params, kp, vp, ks, vs, tokens, positions, write_pages,
                write_offs, page_tables, lane_slots, lane_lens,
                lane_adapters=lane_adapters, adapters=adapters,
                tp_axis=TENSOR)
            return (*out, kp, vp, ks, vs)

        return shard_map(body, mesh=self.tp_mesh, in_specs=ins,
                         out_specs=outs, check_vma=False)(
            params, k_pages, v_pages, k_scales, v_scales, tokens,
            positions, write_pages, write_offs, page_tables, lane_slots,
            lane_lens, lane_adapters, adapters)

    def _mixed_body(self, params, k_pages, v_pages, k_scales, v_scales,
                    tokens, positions, write_pages, write_offs,
                    page_tables, lane_slots, lane_lens,
                    lane_adapters=None, adapters=None, tp_axis=None):
        """Shared mixed-step body. Storage-dtype handling per layer:
        f32 pages store activation values exactly (the bit-exactness
        path); bf16 pages round on the scatter (the .at[].set cast);
        quantized (int8/fp8) pages quantize each (lane, head) row
        against its own amax scale BEFORE any lane attends, so what a
        lane reads back this very step is already the dequantized
        value — quantized content is therefore invariant to chunk
        boundaries, preemption replays, and speculative rollbacks
        (every token's row quantizes independently).

        `tp_axis` runs the SAME body per device inside shard_map over
        the serve mesh: head-sharded params/pages make attention and
        quantization per-head-identical (each head's rows are the
        unsharded bits), the two per-layer psums complete the
        row-parallel projections, and the head all-gathers its vocab
        shards. Exactly one program geometry either way."""
        quantized = k_scales is not None
        x = (self._embed_tp(params, tokens, positions, tp_axis)
             if tp_axis else
             self._embed(params, tokens, positions))     # (T, E)
        scale = 1.0 / np.sqrt(self.head_dim)
        # multi-tenant adapters (serve/adapters.py): ONE gather pulls
        # each lane's whole (A, B) stack — slab (S, L, ...) rows by
        # the lane's slot index — so the per-layer loop just slices.
        # Slot 0 is the reserved zero slab: base-model and inactive
        # lanes add exactly 0.0. Under shard_map the gather runs on
        # each device's local slab shard (replicated lane indices).
        ad = ad_s = None
        if adapters is not None:
            ad = {key: jnp.take(arr, lane_adapters, axis=0)
                  for key, arr in adapters.items() if key != "scale"}
            ad_s = jnp.take(adapters["scale"], lane_adapters, axis=0)
        for i in range(self.num_layers):
            p = params[f"layer{i}_attn"]
            h = _ln(params[f"layer{i}_ln1"], x, self.ln_eps) \
                if self.layer_norm else x
            la = None if ad is None else {
                key: arr[:, i] for key, arr in ad.items()}
            q, k, v = self._attn_qkv(
                p, h, lora=None if la is None else
                (la["a_qkv"], la["b_qkv"], ad_s))         # (T, H[/t], D)
            if quantized:
                kq, ksc = quantize_kv_rows(k, self._kv_store_dtype)
                vq, vsc = quantize_kv_rows(v, self._kv_store_dtype)
                k_pages = k_pages.at[i, write_pages, write_offs].set(kq)
                v_pages = v_pages.at[i, write_pages, write_offs].set(vq)
                k_scales = k_scales.at[i, write_pages,
                                       write_offs].set(ksc)
                v_scales = v_scales.at[i, write_pages,
                                       write_offs].set(vsc)
            else:
                k_pages = k_pages.at[i, write_pages, write_offs].set(
                    k.astype(k_pages.dtype))
                v_pages = v_pages.at[i, write_pages, write_offs].set(
                    v.astype(v_pages.dtype))
            o = paged_attention_ragged(
                q, k_pages[i], v_pages[i], page_tables, lane_slots,
                lane_lens, scale=scale, use_pallas=self._use_pallas,
                interpret=self._interpret,
                k_scales=k_scales[i] if quantized else None,
                v_scales=v_scales[i] if quantized else None,
                block_kv=self.attn_block_kv)
            x = self._attn_out(
                p, o, x, psum_axis=tp_axis,
                lora=None if la is None else
                (la["a_wo"], la["b_wo"], ad_s))
            x = self._ffn(
                params, i, x, psum_axis=tp_axis,
                lora=None if la is None else
                (la["a_ff1"], la["b_ff1"], la["a_ff2"], la["b_ff2"],
                 ad_s))
        logits = (self._head_tp(params, x, tp_axis) if tp_axis
                  else self._head(params, x))            # (T, V[pad])
        topv, topi = jax.lax.top_k(logits, self.topk_cap)
        out = (jnp.argmax(logits, axis=-1).astype(jnp.int32),
               topv.astype(jnp.float32), topi.astype(jnp.int32))
        caches = (k_pages, v_pages, k_scales, v_scales) if quantized \
            else (k_pages, v_pages)
        return out, caches

    # ---------------- disaggregated page handoff -----------------------
    # Device half of the prefill->decode transfer (serve/disagg.py;
    # host bookkeeping in PagedKVCache.export_pages/import_pages).
    # Both directions move whole page ROWS — (layers, page, slot,
    # head[, dim]) blocks of the pool arrays (and the f32 scale arrays
    # on quantized pools, so quantized content crosses the link
    # bit-exactly and dequantizes identically on the far side) —
    # through ONE fixed-shape program each: the page-index vector pads
    # to pages_per_seq with the sink page 0, exactly the padding
    # convention of the mixed step's write lanes.

    def _pool_args(self):
        args = (self._k_pages, self._v_pages)
        if self.kv_quantized:
            args += (self._k_scales, self._v_scales)
        return args

    def _restash_pools(self, pools) -> None:
        self._k_pages, self._v_pages = pools[0], pools[1]
        if self.kv_quantized:
            self._k_scales, self._v_scales = pools[2], pools[3]

    def _export_impl(self, n_pools, *args):
        """Gather page rows: args = (*pools, idx); idx (pages_per_seq,)
        int32, padding entries aim at the sink (their rows ship as
        garbage the importer never addresses)."""
        idx = args[n_pools]
        return tuple(a[:, idx] for a in args[:n_pools])

    def _import_impl(self, n_pools, *args):
        """Scatter page rows: args = (*pools, *rows, idx). Padding
        entries write their (zero) rows into the sink page — harmless
        by the sink convention (reads are masked by seq_lens)."""
        idx = args[2 * n_pools]
        return tuple(p.at[:, idx].set(r)
                     for p, r in zip(args[:n_pools],
                                     args[n_pools:2 * n_pools]))

    def _handoff_specs(self, n_pools):
        """shard_map specs of the handoff programs: pools AND rows
        shard on the head axis (a page row carries the head dim), the
        index vector is replicated."""
        from jax.sharding import PartitionSpec as P
        page = P(None, None, None, TENSOR, None)
        scl = P(None, None, None, TENSOR)
        arrs = (page, page) + ((scl, scl) if n_pools == 4 else ())
        return arrs, P()

    def _export_tp_impl(self, n_pools, *args):
        # the SAME gather body per device over its head shard (pure on
        # its args, so no duplicated indexing convention to drift)
        import functools

        from ..parallel._compat import shard_map
        arrs, rep = self._handoff_specs(n_pools)
        return shard_map(functools.partial(self._export_impl, n_pools),
                         mesh=self.tp_mesh, in_specs=arrs + (rep,),
                         out_specs=arrs, check_vma=False)(*args)

    def _import_tp_impl(self, n_pools, *args):
        import functools

        from ..parallel._compat import shard_map
        arrs, rep = self._handoff_specs(n_pools)
        return shard_map(functools.partial(self._import_impl, n_pools),
                         mesh=self.tp_mesh,
                         in_specs=arrs + arrs + (rep,),
                         out_specs=arrs, check_vma=False)(*args)

    def _pad_idx(self, pages: Sequence[int]) -> np.ndarray:
        c = self.cache_cfg
        if len(pages) > c.pages_per_seq:
            raise ValueError(
                f"shipment of {len(pages)} pages exceeds this pool's "
                f"page-table ceiling ({c.pages_per_seq})")
        idx = np.zeros((c.pages_per_seq,), np.int32)
        idx[:len(pages)] = pages
        return idx

    def export_kv(self, slot: int, tokens: Sequence[int],
                  stream_id: Optional[int] = None,
                  trace_id: Optional[int] = None,
                  tenant_id: int = 0):
        """Ship `slot`'s full resident pages to the host: the
        prefill-engine half of a disaggregated handoff. Returns a
        PageShipment (serve/disagg.py) carrying the chain keys, the
        page rows (+ scale rows on quantized pools) as host numpy, and
        the geometry stamp import_kv validates — or None when the slot
        has no full page yet (the importer simply recomputes). Must
        run while the slot is still mapped (DisaggCluster exports from
        generate's on_finish hook, before the slot is freed)."""
        from .adapters import tenant_prefix_salt
        from .disagg import PageShipment
        pages, keys, ntokens = self.cache.export_pages(
            slot, tokens, prev=tenant_prefix_salt(tenant_id))
        if not pages:
            return None
        self._device_pages()
        n = len(pages)
        rows = self._call_counted(
            "export", self._export_jit, self._n_pools,
            *self._pool_args(), jnp.asarray(self._pad_idx(pages)))
        # copy the real-page slice: a view would pin the whole
        # pages_per_seq-padded gather buffer for the shipment's life
        host = [np.asarray(r)[:, :n].copy() for r in rows]
        c = self.cache_cfg
        return PageShipment(
            keys=list(keys), ntokens=int(ntokens),
            k_rows=host[0], v_rows=host[1],
            k_scale_rows=host[2] if self.kv_quantized else None,
            v_scale_rows=host[3] if self.kv_quantized else None,
            page_size=c.page_size, num_layers=c.num_layers,
            num_heads=c.num_heads, head_dim=c.head_dim,
            kv_dtype=c.kv_dtype, stream_id=stream_id,
            trace_id=trace_id, tenant_id=int(tenant_id))

    def import_kv(self, ship) -> int:
        """Adopt a PageShipment into this engine's pool: the
        decode-engine half of a disaggregated handoff. Registers the
        chain keys (PagedKVCache.import_pages — already-resident keys
        dedupe to nothing) and scatters the needed rows into freshly
        parked pages, so the NEXT generate()'s admission path prefix-
        matches the handed-off prompt exactly as it would a locally
        computed one. Returns the number of pages actually written
        (0 = full dedupe). The caller owns backpressure: check
        `cache.free_pages` first (DisaggCluster skips the import and
        lets the decode engine re-prefill instead of squeezing a
        loaded pool)."""
        c = self.cache_cfg
        if (ship.page_size, ship.num_layers, ship.num_heads,
                ship.head_dim, ship.kv_dtype) != (
                c.page_size, c.num_layers, c.num_heads, c.head_dim,
                c.kv_dtype):
            raise ValueError(
                f"shipment geometry {ship.signature()} does not match "
                f"this pool "
                f"({(c.page_size, c.num_layers, c.num_heads, c.head_dim, c.kv_dtype)})"
            )
        todo = self.cache.import_pages(ship.keys)
        if not todo:
            return 0
        self._device_pages()
        idx = self._pad_idx([page for _, page in todo])
        srcs = [ship.k_rows, ship.v_rows]
        if self.kv_quantized:
            srcs += [ship.k_scale_rows, ship.v_scale_rows]
        rows = []
        for src in srcs:
            buf = np.zeros((src.shape[0], c.pages_per_seq)
                           + src.shape[2:], src.dtype)
            for j, (chain_i, _) in enumerate(todo):
                buf[:, j] = src[:, chain_i]
            rows.append(jnp.asarray(buf))
        pools = self._call_counted(
            "import", self._import_jit, self._n_pools,
            *self._pool_args(), *rows, jnp.asarray(idx))
        self._restash_pools(pools)
        return len(todo)

    def warmup_handoff(self) -> Dict[str, int]:
        """Compile the export/import programs on sink-page dummies (a
        no-op on the pool content), so a DisaggCluster's serving loop
        never compiles after DisaggCluster.warmup(). The import dummies
        are HOST-built arrays, exactly the layout import_kv dispatches
        (a sharded engine would otherwise warm the program against
        device-committed shardings and recompile on the first real,
        host-laid-out shipment). Returns compile_counts()."""
        self._device_pages()
        c = self.cache_cfg
        idx = jnp.zeros((c.pages_per_seq,), jnp.int32)
        self._call_counted(
            "export", self._export_jit, self._n_pools,
            *self._pool_args(), idx)
        val = (c.num_layers, c.pages_per_seq, c.page_size,
               c.num_heads, c.head_dim)
        shapes = [(val, c.storage_dtype), (val, c.storage_dtype)]
        if self.kv_quantized:
            scl = val[:-1]
            shapes += [(scl, np.float32), (scl, np.float32)]
        zero_rows = [jnp.asarray(np.zeros(s, d)) for s, d in shapes]
        pools = self._call_counted(
            "import", self._import_jit, self._n_pools,
            *self._pool_args(), *zero_rows, idx)
        self._restash_pools(pools)
        return self.compile_counts()

    # ---------------- hierarchical host tier ---------------------------
    def _drain_spills(self) -> int:
        """Ship queued evicted-page content to the host tier through
        the fixed-shape export gather (the disagg program — zero new
        compiles). MUST run before any dispatch that writes the device
        pools: a queued page may already be remapped to a new slot,
        and its old rows survive only until the next jitted write. The
        session calls this right before each mixed dispatch; a reload
        drains before its import scatter for the same reason."""
        store = self.host_tier
        if store is None:
            return 0
        pending = self.cache.take_pending_spills()
        if not pending:
            return 0
        latest = {}          # a page queued twice keeps its newest key
        for page, key in pending:
            latest[page] = key
        todo = [(p, k) for p, k in latest.items()
                if not store.contains(k)]
        if not todo:
            return 0
        self._device_pages()
        c = self.cache_cfg
        shipped = 0
        for i in range(0, len(todo), c.pages_per_seq):
            batch = todo[i:i + c.pages_per_seq]
            rows = self._call_counted(
                "export", self._export_jit, self._n_pools,
                *self._pool_args(),
                jnp.asarray(self._pad_idx([p for p, _ in batch])))
            host = [np.asarray(r) for r in rows]
            for j, (_, key) in enumerate(batch):
                if store.put(key, [h[:, j] for h in host]):
                    shipped += 1
        self._host_reload_stats["spilled_pages"] += shipped
        if self.telemetry.enabled and shipped:
            self.telemetry.instant(self._ENGINE_TRACK, "host_spill",
                                   args={"pages": shipped})
        return shipped

    def _host_step_price(self, ctx_len: int) -> float:
        """Predicted seconds of ONE mixed step at this context — the
        recompute side of the spill-vs-recompute decision, from the
        same cost stack the drift calibrator prices; the analytic
        fallback mirrors the router's virtual-clock price."""
        pred = self._drift_predicted(pow2_bucket(max(1, ctx_len)))
        if pred is not None:
            return float(pred[0])
        return 1e-4 * (1.0 + self.mixed_width / 512.0) \
            * (1.0 + ctx_len / 2048.0)

    def _host_reload(self, req, keys, cached_pages,
                     max_pages: int) -> int:
        """The scheduler's admission hook when the host tier is armed:
        extend an HBM prefix match with host-resident pages IF the
        priced DMA beats recomputing those tokens through the prefill
        roofline (TPUMachineModel.host_transfer vs the cost model's
        step price — the paper's priced-placement loop applied to the
        memory hierarchy). Reloaded pages park exactly like a disagg
        import (hashed, refcount 0), so the scheduler's re-match picks
        them up; `free_pages` is unchanged (free -> parked), so the
        admission watermark math the caller already did stays valid.
        Returns the pages made resident; the decision — either way —
        is recorded on the request for explain_request."""
        store, cache = self.host_tier, self.cache
        resident = len(cached_pages)
        run = cache.match_prefix_host(keys, resident)
        if run <= 0:
            return 0
        c = self.cache_cfg
        m = min(run, int(max_pages))
        decision = {"host_matched_pages": int(run),
                    "reloaded_pages": 0, "dma_s": 0.0,
                    "recompute_s": 0.0, "chose": "none"}
        req.host_reload = decision
        if m <= 0:
            return 0
        if self._host_mm is None:
            from ..search.machine_model import default_machine_model
            self._host_mm = default_machine_model(mesh=self.tp_mesh)
        dma_s = float(self._host_mm.host_transfer(
            float(m) * float(c.page_bytes)))
        steps = -(-(m * c.page_size) // max(1, self.prefill_budget))
        recompute_s = steps * self._host_step_price(len(req.prompt))
        decision.update(dma_s=dma_s, recompute_s=recompute_s)
        if dma_s >= recompute_s:
            decision["chose"] = "recompute"
            self._host_reload_stats["recompute_chosen"] += 1
            return 0
        # protect the HBM-matched refcount-0 run from the import's
        # eviction cascade (allocation evicts LRU-oldest)
        cache.touch(cached_pages)
        t0 = time.perf_counter()
        # fetch rows FIRST: on the SHARED store another replica's puts
        # may have evicted part of the matched run since the probe
        fetched = []
        for key in keys[resident:resident + m]:
            rows = store.get(key)
            if rows is None:
                break
            fetched.append(rows)
        val_shape = (c.num_layers, c.page_size, c.num_heads,
                     c.head_dim)
        if not fetched or tuple(fetched[0][0].shape) != val_shape:
            decision["chose"] = "store_miss"  # raced away / foreign
            return 0                          # geometry: never scatter
        todo = cache.import_pages(keys[resident:resident + len(fetched)])
        if not todo:
            decision["chose"] = "store_miss"
            return 0
        # the allocation above may have queued evictions of its own —
        # their content must ship before the scatter overwrites it
        self._drain_spills()
        self._device_pages()
        idx = self._pad_idx([page for _, page in todo])
        rows_dev = []
        for pool_i in range(self._n_pools):
            src0 = fetched[0][pool_i]
            buf = np.zeros((src0.shape[0], c.pages_per_seq)
                           + src0.shape[1:], src0.dtype)
            for j, (chain_i, _) in enumerate(todo):
                buf[:, j] = fetched[chain_i][pool_i]
            rows_dev.append(jnp.asarray(buf))
        pools = self._call_counted(
            "import", self._import_jit, self._n_pools,
            *self._pool_args(), *rows_dev, jnp.asarray(idx))
        self._restash_pools(pools)
        n = len(todo)
        decision.update(chose="reload", reloaded_pages=n)
        self._host_reload_stats["reload_events"] += 1
        self._host_reload_stats["reload_pages"] += n
        self._host_reload_stats["reload_priced_s"] += dma_s
        self._host_reload_s += dma_s
        if self.telemetry.enabled:
            self.telemetry.span(
                self._ENGINE_TRACK, "host_reload", t0,
                time.perf_counter(),
                args={"trace": req.trace_id, "rid": req.rid,
                      "pages": n, "dma_s": dma_s})
        return n

    # ---------------- legacy prefill -----------------------------------
    def _prefill_impl(self, params, k_pages, v_pages, tokens, length,
                      pt_row):
        """tokens (1, S) padded to a bucket; length scalar int32 (real
        prompt tokens); pt_row (pages_per_seq,) the sequence's page
        table. Returns (last-position logits (V,), k_pages, v_pages).

        Padded positions scatter their K/V through page-table entries
        normally: entries past the mapped range are 0 (the sink), and
        padded offsets inside a mapped page are overwritten by decode
        before the length mask ever exposes them."""
        last, (k_pages, v_pages) = self._forward_tokens(
            params, tokens, length, kv=(k_pages, v_pages, pt_row))
        return last, k_pages, v_pages

    # ---------------- legacy decode ------------------------------------
    def _decode_impl(self, params, k_pages, v_pages, tokens, positions,
                     write_pages, write_offs, page_tables, seq_lens):
        """One token for every slot lane. tokens/positions (B,) int32;
        write_pages/write_offs (B,) the physical slot for each lane's
        new K/V — HOST-computed so lanes that are not decoding this
        step (empty, or prefilled moments ago) aim at the sink page 0
        instead of clobbering their own position 0; page_tables
        (B, pages_per_seq); seq_lens (B,) INCLUDING the token being
        decoded (its K/V is written here, then attended — position i
        sees keys 0..i). Non-decoding lanes compute garbage the host
        never reads. Returns (next_tokens (B,), top-k values, top-k
        ids, k_pages, v_pages)."""
        x = self._embed(params, tokens, positions)        # (B, E)
        pages, offs = write_pages, write_offs
        scale = 1.0 / np.sqrt(self.head_dim)
        for i in range(self.num_layers):
            p = params[f"layer{i}_attn"]
            h = _ln(params[f"layer{i}_ln1"], x, self.ln_eps) \
                if self.layer_norm else x
            q, k, v = self._attn_qkv(p, h)                # (B, H, D)
            k_pages = k_pages.at[i, pages, offs].set(
                k.astype(k_pages.dtype))
            v_pages = v_pages.at[i, pages, offs].set(
                v.astype(v_pages.dtype))
            o = paged_attention_decode(
                q, k_pages[i], v_pages[i], page_tables, seq_lens,
                scale=scale, use_pallas=self._use_pallas,
                interpret=self._interpret)
            x = self._attn_out(p, o, x)
            x = self._ffn(params, i, x)
        logits = self._head(params, x)                    # (B, V)
        topv, topi = jax.lax.top_k(logits, self.topk_cap)
        return (jnp.argmax(logits, axis=-1).astype(jnp.int32),
                topv.astype(jnp.float32), topi.astype(jnp.int32),
                k_pages, v_pages)

    # ---------------- naive no-cache reference -------------------------
    def _forward_logits(self, params, tokens, length):
        """Full forward over (1, S) tokens, logits at position
        length-1 — the no-KV-cache greedy-decode reference (the shared
        _forward_tokens with the cache writes off)."""
        last, _ = self._forward_tokens(params, tokens, length, kv=None)
        return last

    # ---------------- bucketing / compile bookkeeping ------------------
    def bucket_for(self, prompt_len: int) -> int:
        for b in self.buckets:
            if prompt_len <= b:
                return b
        raise ValueError(
            f"prompt of {prompt_len} tokens exceeds the largest bucket "
            f"{self.buckets[-1]}")

    def compile_counts(self) -> Dict[str, int]:
        """Compiled-program count per serving function. After warmup()
        these must never grow — the zero-recompile serving contract
        (the chunked engine's whole hot path is the single `mixed`
        program). Counted by the ProgramRegistry (core/programs.py),
        which owns every serving dispatch: a count increments exactly
        when the registry AOT-compiles a new argument signature, so
        compiles inside warmup_handoff / adapter load can no longer
        hide from it (the old monitoring-snapshot counter missed them
        on a jax without the monitoring module). Executables restored
        from --program-cache-dir count ZERO — a warm boot reports no
        compiles, which is the point."""
        return self.programs.compile_counts()

    def _device_pages(self):
        page_sh, scale_sh = self._page_shardings()
        if self._k_pages is None:
            self._k_pages, self._v_pages = \
                self.cache.alloc_device_cache(sharding=page_sh)
        if self.kv_quantized and self._k_scales is None:
            self._k_scales, self._v_scales = \
                self.cache.alloc_scale_arrays(sharding=scale_sh)
            self.cache.register_scale_meta(self._k_scales,
                                           self._v_scales)
        return self._k_pages, self._v_pages

    # ---------------- adapter pool: device half ------------------------
    def _adapter_slab_shapes(self):
        """{slab: (num_slots,) + per-slot shape} of the device pool —
        the stacked form of adapters._weight_shapes at the pool's
        padded rank/ff, plus the (S,) f32 per-slot scale."""
        from .adapters import _weight_shapes
        ac = self.adapter_cfg
        shapes = {k: (ac.num_slots,) + s for k, s in _weight_shapes(
            ac, ac.rank, ac.ff_dim).items()}
        shapes["scale"] = (ac.num_slots,)
        return shapes

    def _device_adapters(self):
        """The resident slab pytree (lazy, like _device_pages): A/B
        factors at the activation dtype, per-slot scales f32, all
        zeros until tenants load — so slot 0 stays the zero base slab
        forever (nothing ever writes it)."""
        if self.adapters is None:
            return None
        if self._adapter_slabs is None:
            slabs = {}
            for key, shape in self._adapter_slab_shapes().items():
                dt = jnp.float32 if key == "scale" else self.act_dtype
                arr = jnp.zeros(shape, dt)
                if self._adapter_shardings is not None:
                    arr = jax.device_put(arr,
                                         self._adapter_shardings[key])
                slabs[key] = arr
            self._adapter_slabs = slabs
        return self._adapter_slabs

    def _adapter_load_impl(self, slabs, slot, rows):
        """Scatter ONE tenant's (A, B, scale) rows into its slot —
        slabs donated in place, rows host-built replicated arrays."""
        return jax.tree.map(
            lambda s, r: s.at[slot].set(r.astype(s.dtype)), slabs,
            rows)

    def register_adapter(self, tenant_id: int, weights, *,
                         scale: float = 1.0) -> None:
        """Register a tenant's LoRA weights with the pool (host copy;
        the device load happens on demand at admission). `weights` is
        the adapters.ADAPTER_SLABS dict at the MODEL's ff width and
        any rank <= the pool rank (zero-padded — exact)."""
        if self.adapters is None:
            raise RuntimeError(
                "engine has no adapter pool (set adapter_rank > 0)")
        self.adapters.register(tenant_id, weights, scale=scale,
                               ff_dim=self.ff_dim)

    def adapter_resident(self, tenant_id: int) -> bool:
        """Whether a tenant's adapter already holds a slab slot — the
        router's adapter-affinity signal (routing to a resident
        replica skips the load stall)."""
        return self.adapters is not None \
            and self.adapters.resident(tenant_id)

    def _drain_adapter_loads(self) -> int:
        """Run every pending tenant load through the jitted scatter —
        the session calls this BEFORE each mixed dispatch, so a lane
        never gathers a slab its tenant has not landed in. Returns
        the number of loads dispatched (a planning-visible stall,
        never a recompile)."""
        if self.adapters is None:
            return 0
        pending = self.adapters.take_pending()
        for slot, tenant in pending:
            w, sc = self.adapters.host_weights(tenant)
            rows = {k: jnp.asarray(v) for k, v in w.items()}
            rows["scale"] = jnp.asarray(np.float32(sc))
            self._adapter_slabs = self._call_counted(
                "adapter", self._adapter_load_jit,
                self._device_adapters(), jnp.int32(slot), rows)
            if self.telemetry.enabled:
                self.telemetry.instant(
                    self._ENGINE_TRACK, "adapter_load",
                    args={"tenant": tenant, "slot": slot})
        return len(pending)

    def _dispatch_mixed(self, kp, vp, *args, lane_adapters=None):
        """One mixed-step dispatch through the right jitted program for
        the pool format, threading (and re-capturing) the donated scale
        arrays on quantized pools. Returns (greedy, topv, topi, kp, vp);
        the page AND scale arrays are re-stashed on self each step so a
        mid-run audit (check_kv_scales from an `on_step` callback, when
        sequences are actually resident) reads THIS step's content, not
        the pre-run allocation. On an adapter-armed engine the lanes'
        slot indices + the slabs ride along (read-only — the slabs are
        NOT donated); unarmed engines pass None (an empty pytree, zero
        trace cost, numerics untouched)."""
        if self.adapters is not None:
            la = lane_adapters if lane_adapters is not None \
                else jnp.zeros((self.mixed_width,), jnp.int32)
            args = args + (la, self._device_adapters())
        else:
            args = args + (None, None)
        if self.kv_quantized:
            greedy, topv, topi, kp, vp, ks, vs = self._call_counted(
                "mixed", self._mixed_q_jit, self._step_params, kp, vp,
                self._k_scales, self._v_scales, *args)
            self._k_scales, self._v_scales = ks, vs
        else:
            greedy, topv, topi, kp, vp = self._call_counted(
                "mixed", self._mixed_jit, self._step_params, kp, vp,
                *args)
        self._k_pages, self._v_pages = kp, vp
        return greedy, topv, topi, kp, vp

    def warmup(self) -> Dict[str, int]:
        """Ready the active path's programs once, on throwaway inputs
        (all writes aim at the sink page): compile on a cold boot, or
        dispatch executables the registry restored from
        --program-cache-dir on a warm one (zero compiles). Returns
        compile_counts(); `boot_stats` records which boot this was and
        what it cost (the `replica_boot` span payload), and a cold
        engine with a cache dir armed writes its snapshot back so the
        NEXT boot over this config is warm."""
        t0 = time.perf_counter()
        c = self.cache_cfg
        kp, vp = self._device_pages()
        if self.chunked_prefill:
            t = self.mixed_width
            z = jnp.zeros((t,), jnp.int32)
            pts = jnp.zeros((c.max_seqs, c.pages_per_seq), jnp.int32)
            _, _, _, kp, vp = self._dispatch_mixed(
                kp, vp, z, z, z, z, pts, z, jnp.ones((t,), jnp.int32))
            if self.adapters is not None:
                # compile the adapter-load scatter on an all-zero row
                # set aimed at the base slot (zeros into zeros — a
                # no-op on content), host-built f32 exactly like a
                # real load (the registered host weights are f32) so
                # the first tenant miss reuses this program
                rows = {k: jnp.asarray(np.zeros(s[1:], np.float32))
                        for k, s in self._adapter_slab_shapes().items()}
                self._adapter_slabs = self._call_counted(
                    "adapter", self._adapter_load_jit,
                    self._device_adapters(), jnp.int32(0), rows)
            if self.host_tier is not None:
                # spill/reload traffic runs the handoff programs —
                # warm them here or the first eviction under load
                # would compile after the pool snapshots warm counts.
                # The import donates (and restashes) the pools: the
                # locals this method stashes at the end are dead now
                self.warmup_handoff()
                kp, vp = self._k_pages, self._v_pages
        else:
            pt_row = jnp.zeros((c.pages_per_seq,), jnp.int32)
            for b in self.buckets:
                toks = jnp.zeros((1, b), jnp.int32)
                _, kp, vp = self._call_counted(
                    "prefill", self._prefill_jit, self.params, kp, vp,
                    toks, jnp.int32(1), pt_row)
            toks = jnp.zeros((c.max_seqs,), jnp.int32)
            pos = jnp.zeros((c.max_seqs,), jnp.int32)
            pts = jnp.zeros((c.max_seqs, c.pages_per_seq), jnp.int32)
            sls = jnp.ones((c.max_seqs,), jnp.int32)
            _, _, _, kp, vp = self._call_counted(
                "decode", self._decode_jit, self.params, kp, vp, toks,
                pos, toks, pos, pts, sls)
        self._k_pages, self._v_pages = kp, vp
        rec = self.programs.boot_record()
        rec["boot_s"] = time.perf_counter() - t0
        rec["warm"] = rec["compiles"] == 0 and rec["restored"] > 0
        self.boot_stats = rec
        if self.programs.cache_dir and self.programs._dirty:
            # read-through write-back: the first (cold) engine over
            # this fingerprint populates the snapshot, every later
            # replica — in-process scale-up or a fresh process —
            # deserializes instead of compiling
            self.programs.save()
        return self.compile_counts()

    # ---------------- sampling -----------------------------------------
    @staticmethod
    def _sample_params(temperature, top_k, seed, n, cap):
        """Normalize scalar-or-per-request sampling args into one
        Optional[SampleParams] per request."""
        def seq(x):
            if x is None or np.isscalar(x):
                return [x] * n
            if len(x) != n:
                raise ValueError(
                    f"per-request sampling arg has {len(x)} entries "
                    f"for {n} prompts")
            return list(x)
        out = []
        for t, k in zip(seq(temperature), seq(top_k)):
            if t is None or float(t) <= 0.0:
                if t is not None and float(t) < 0.0:
                    raise ValueError(f"temperature must be >= 0, got {t}")
                out.append(None)
                continue
            if k is not None and not (1 <= int(k) <= cap):
                raise ValueError(
                    f"top_k must be in [1, {cap}] (the engine's static "
                    f"top-k head), got {k}")
            out.append(SampleParams(temperature=float(t),
                                    top_k=None if k is None else int(k),
                                    seed=int(seed)))
        return out

    def _pick_token(self, req: Request, greedy: int, topv, topi) -> int:
        """The emitted token for a lane: greedy argmax, or a seeded
        draw from the lane's top-k logits. The RNG is stateless per
        (seed, stream-id, stream-offset + token-index) — stream_id
        defaults to the local rid, so a plain engine keeps the
        historical (seed, rid, index) keying bit-for-bit — which makes
        a fixed seed reproduce a stream exactly, preemption/resume
        replay nothing, and a stream SURVIVE crossing schedulers: the
        disaggregated decode role resumes a handed-off request at
        offset 1, and a routed replica draws the same stream a
        single-replica engine would (docs/serving.md)."""
        sp = req.sample
        if sp is None:
            return int(greedy)
        k = sp.top_k if sp.top_k is not None else self.topk_cap
        v = np.asarray(topv[:k], np.float64) / sp.temperature
        v -= v.max()
        p = np.exp(v)
        p /= p.sum()
        sid = req.rid if req.stream_id is None else req.stream_id
        rng = np.random.default_rng(
            [sp.seed, sid, req.stream_offset + len(req.out_tokens)])
        return int(topi[int(rng.choice(k, p=p))])

    # ---------------- quantized-page verification (tests) -------------
    def check_kv_scales(self) -> None:
        """Device-side scale bookkeeping check for int8 pools (the
        stress tests' companion to PagedKVCache.check_invariants):
        every audited (page, offset) row must carry finite,
        non-negative K/V scales, and a zero scale must vouch for an
        all-zero int8 row (scale 0 is only ever written for an
        all-zero activation row, so anything else means the scale and
        its page drifted — e.g. a rollback/preemption interleaving
        that reused a page slot without rewriting its scale). Audits
        RESIDENT (slot, position) rows — which only exist mid-run, so
        the stress tests call this from generate()'s `on_step`
        callback (_dispatch_mixed re-stashes the live arrays each
        step) — plus every prefix-cache-parked page: those are
        complete pages whose content must outlive their writer for a
        later request to attach, and they are what a post-run call
        still covers. No-op on lossless pools."""
        if not self.kv_quantized or self._k_pages is None:
            return
        ps = self.cache_cfg.page_size
        kq = np.asarray(self._k_pages)
        vq = np.asarray(self._v_pages)
        ks = np.asarray(self._k_scales)
        vs = np.asarray(self._v_scales)

        def audit(what: str, page: int, off: int) -> None:
            for name, s, q in (("k", ks, kq), ("v", vs, vq)):
                srow = s[:, page, off, :]      # (layers, H)
                qrow = q[:, page, off, :, :]   # (layers, H, D)
                assert np.all(np.isfinite(srow)) \
                    and np.all(srow >= 0), (
                    f"{name}-scale of {what} (page {page} off {off}) "
                    f"is not finite/non-negative")
                dead = srow == 0.0
                assert np.all(qrow[dead] == 0), (
                    f"{name}-page row of {what} (page {page} off "
                    f"{off}) has zero scale but nonzero quantized "
                    f"content")

        for slot in range(self.cache_cfg.max_seqs):
            for pos in range(int(self.cache.seq_lens[slot])):
                audit(f"slot {slot} pos {pos}",
                      int(self.cache.page_tables[slot, pos // ps]),
                      pos % ps)
        for page in self.cache.parked_pages():
            for off in range(ps):
                audit("cached page", page, off)

    @staticmethod
    def first_divergence(a, b) -> Optional[int]:
        """Index of the first position where token streams a and b
        differ, or None when one is a prefix of the other (the shared
        scan of assert_token_parity and the bench's prefix-agreement
        metric)."""
        return next((i for i, (x, y) in enumerate(zip(a, b))
                     if x != y), None)

    def assert_token_parity(self, prompts, out, ref, *, margin=None,
                            min_exact_frac=0.0,
                            what="outputs") -> int:
        """The reference-parity gate for generate() outputs (the CI
        bench and the property tests share this one implementation),
        dispatched on the pool format. Lossless pools (kv_exact) gate
        full token identity. Lossy pools (bfloat16/int8 pages) gate
        the relaxed quantized contract instead: each request either
        matches the greedy reference token-for-token, or first
        diverges at a TIE — a position where the reference's own
        top-logit margin over the engine's pick is inside the
        quantization error bound. A real quantization-path bug (a
        mis-indexed scale, a stale page) perturbs logits at O(1) and
        flips comfortable margins, which this catches; an argmax flip
        inside the margin is the priced-in cost of lossy pages (after
        one tie flips, the continuation legitimately diverges, so
        only the first divergence is comparable). Returns the
        fully-identical request count. `margin` defaults to the
        engine's pool-format tie margin (int8 rounds at amax/127, fp8
        at amax/16 — kv_tie_margin)."""
        if margin is None:
            margin = self.kv_tie_margin
        if self.kv_exact:
            for i, (o, r) in enumerate(zip(out, ref)):
                assert list(o) == list(r), (
                    f"{what}: request {i} diverged from reference")
            return len(out)
        exact = 0
        for pr, o, r in zip(prompts, out, ref):
            j = self.first_divergence(o, r)
            if j is None:
                exact += 1
                continue
            ctx = list(pr) + list(r[:j])
            b = self.bucket_for(len(ctx))
            arr = np.zeros((1, b), np.int32)
            arr[0, :len(ctx)] = ctx
            logits = np.asarray(self._forward_jit(
                self.params, jnp.asarray(arr), jnp.int32(len(ctx))))
            gap = float(logits[r[j]] - logits[o[j]])
            assert 0.0 <= gap <= margin, (
                f"{what}: lossy KV pages flipped a non-tie token — "
                f"reference margin {gap:.4f} > {margin} at "
                f"position {j}")
        assert exact >= min_exact_frac * len(prompts), (
            f"{what}: only {exact}/{len(prompts)} requests "
            f"token-identical — quantization error is not bounded at "
            f"tie scale")
        return exact

    # ---------------- robustness --------------------------------------
    def cancel(self, rid: int) -> bool:
        """Host-side cancellation: mark request `rid` of the in-flight
        generate() for abort at the next chunk boundary (its pages and
        prefix-registry pins reclaim through the normal refcount
        machinery). Safe to call from another thread or from an
        `on_step` callback; returns False when no such request is
        active (already finished, or a stale rid)."""
        req = self._active.get(rid)
        if req is None or req.state == RequestState.FINISHED:
            return False
        self._cancels.add(rid)
        return True

    def _sweep_aborts(self, sched) -> None:
        """Chunk-boundary sweep: apply pending cancels and expire
        deadlines. Runs at the top of every serving step, BEFORE the
        scheduler plans — so no aborted request can have a chunk in
        flight, and its slot/pages are free for this very step's
        admissions."""
        now = time.perf_counter()
        tel = self.telemetry
        live = list(sched.running.values()) + list(sched.waiting)
        expired = 0
        for req in live:
            if req.rid in self._cancels:
                # consume the mark either way: applied, or moot (the
                # request already finished). A long-lived session
                # (ReplicaPool) never reaches generate()'s wholesale
                # clear, and rids restart at 0 in a recovery-reopened
                # session — a stale mark must not cancel a stranger.
                self._cancels.discard(req.rid)
                if sched.abort(req, RequestOutcome.CANCELLED):
                    req.t_finish = now
                    if tel.enabled:
                        tel.instant(self._ENGINE_TRACK, "cancel",
                                    t=now, args={"rid": req.rid,
                                                 "trace": req.trace_id})
            elif req.t_deadline and now >= req.t_deadline:
                if sched.abort(req, RequestOutcome.DEADLINE_EXPIRED):
                    req.t_finish = now
                    expired += 1
                    if tel.enabled:
                        tel.instant(self._ENGINE_TRACK,
                                    "deadline_expired", t=now,
                                    args={"rid": req.rid,
                                          "trace": req.trace_id})
        if expired >= self.DEADLINE_STORM:
            # a deadline STORM (several requests expiring at one chunk
            # boundary) is the latency-collapse signature an operator
            # needs a black box for — one bounded bundle, rate-limited
            self._auto_postmortem("deadline_storm", sched=sched,
                                  detail={"expired_this_sweep": expired})

    def _fail_inflight(self, sched, reqs: Sequence[Request]) -> None:
        """Crash containment (replacing the PR-3-era hard brick): a
        mid-batch exception fails ONLY the in-flight requests — every
        live slot releases through the refcount machinery, the prefix
        registry is dropped (the device arrays its content lived in
        are stale, or consumed by the dispatch that died), and the
        page pools are reallocated lazily if donation ate them. The
        exception still propagates to the caller, but the NEXT
        generate() serves normally on a pool that check_invariants
        vouches for."""
        now = time.perf_counter()
        failed = 0
        for req in reqs:
            if req.state != RequestState.FINISHED:
                if sched.abort(req, RequestOutcome.FAILED):
                    req.t_finish = now
                    failed += 1
        # black-box the crash BEFORE resetting pool state: the bundle
        # must capture the scheduler/pool as the failure left them
        self._auto_postmortem("fault_abort", sched=sched,
                              detail={"failed_inflight": failed})
        self._reset_pool_state()

    def _reset_pool_state(self) -> None:
        """Shared tail of both recovery paths (_fail_inflight and the
        orphaned-slot self-heal): the prefix registry vouches for
        content in device arrays an interrupted batch lost (or donation
        consumed), so drop it wholesale, and reallocate the page pools
        lazily when the interrupted dispatch ate them."""
        self.cache.clear_prefix()   # also drops queued host spills
        self._host_reload_s = 0.0
        if self._k_pages is not None and \
                getattr(self._k_pages, "is_deleted", lambda: False)():
            self._k_pages = self._v_pages = None  # realloc on next use
        if self._k_scales is not None and \
                getattr(self._k_scales, "is_deleted", lambda: False)():
            self._k_scales = self._v_scales = None
        self.cache.check_invariants()

    # ---------------- telemetry ----------------------------------------
    def _drift_predicted(self, ctx_bucket: int) -> Optional[tuple]:
        """(predicted seconds, per-task-class breakdown) for one mixed
        step at this context bucket, from the SAME cost stack the
        placement search prices (cost_model.serve_step_tasks ->
        simulate_serve_step; the breakdown is the attribution vector
        drift_report folds per task class). The fixed-shape mixed
        program dispatches every lane regardless of occupancy, so the
        prediction varies only with (arch, tp, lane width, context) —
        the cache keys on the context bucket alone and the hot-path
        cost after a bucket's first step is one dict hit. None when
        the cost stack is unavailable."""
        if ctx_bucket not in self._drift_cache:
            try:
                from ..search.simulator import (serve_step_breakdown,
                                                simulate_serve_step)
                arch = self.serve_arch(context=max(1, ctx_bucket))
                # price on the SAME machine model the placement search
                # was calibrated against: --machine-model-file, when
                # set, overrides the default spec (HBM capacity
                # included — a pool whose degree overflows it pays the
                # memory penalty in its virtual step price, exactly
                # what the 2-D mesh search predicted when it rejected
                # that degree)
                mm = None
                mf = getattr(self.config, "machine_model_file", None)
                if mf:
                    from ..search.machine_model import \
                        default_machine_model
                    if getattr(self, "_drift_mm", None) is None:
                        self._drift_mm = default_machine_model(
                            machine_file=mf)
                    mm = self._drift_mm
                self._drift_cache[ctx_bucket] = (
                    float(simulate_serve_step(arch, self.tp, mm,
                                              lanes=self.mixed_width)),
                    serve_step_breakdown(arch, self.tp, mm,
                                         lanes=self.mixed_width))
            except Exception:
                self._drift_cache[ctx_bucket] = None
        return self._drift_cache[ctx_bucket]

    def _drift_regime(self, n_decode: int, pre_bucket: int,
                      ctx_bucket: int) -> str:
        return (f"t={self.tp} kv={self.kv_dtype} dec={n_decode} "
                f"pre={pre_bucket} ctx={ctx_bucket}")

    def set_track_process(self, proc: str) -> None:
        """Re-home this engine's telemetry tracks under a new process
        name (ReplicaPool labels each replica's tracks replica0/1/...
        so a multi-replica trace keeps one track group per replica)."""
        self._proc = str(proc)
        self._ENGINE_TRACK = (self._proc, "engine")
        self._QUEUE_TRACK = (self._proc, "queue")
        self._slot_tracks = []

    def _slot_track(self, slot: int):
        tracks = self._slot_tracks
        while len(tracks) <= slot:
            tracks.append((self._proc, f"slot {len(tracks)}"))
        return tracks[slot]

    def _record_step_telemetry(self, tel, plan, step_idx: int,
                               t_start: float, dt: float,
                               rung: int, occupancy: float) -> None:
        """One engine step's telemetry: the step span on the engine
        track, a chunk span per request on its slot track, queue-wait
        async spans for this step's admissions, preemption instants,
        pool-occupancy/rung counter samples, and the drift sample
        (measured dt vs the cost model's prediction for this step's
        regime). Called AFTER the dispatch returned, so a fault that
        kills the step never half-records it. The whole step is built
        as raw event tuples and handed to the bus in ONE
        :meth:`Telemetry.emit` — this runs on every engine step, and
        the per-call overhead of the one-at-a-time recorders is what
        the <= 3% gate budget goes to."""
        t_end = t_start + dt
        dur = max(0.0, dt)
        now = time.perf_counter()
        evs = []
        for req in plan.admitted:
            if req._t_requeue is not None:
                # re-admission after preemption: the span an operator
                # debugging page pressure needs is preempt -> readmit
                # (NOT a duplicate of the original queue wait; ident
                # carries the preemption ordinal so Perfetto pairs
                # each b/e uniquely per eviction)
                ident = f"{req.rid}.{req.preemptions}"
                evs.append(("b", self._QUEUE_TRACK, "requeue_wait",
                            req._t_requeue, 0.0, ident,
                            {"rid": req.rid, "trace": req.trace_id,
                             "preemptions": req.preemptions}))
                evs.append(("e", self._QUEUE_TRACK, "requeue_wait",
                            now, 0.0, ident, None))
                req._t_requeue = None
            elif not req.t_admit:
                req.t_admit = now
                evs.append(("b", self._QUEUE_TRACK, "queue_wait",
                            req.t_submit, 0.0, req.rid,
                            {"rid": req.rid, "trace": req.trace_id,
                             "prompt_tokens": len(req.prompt)}))
                evs.append(("e", self._QUEUE_TRACK, "queue_wait",
                            req.t_admit, 0.0, req.rid, None))
        for victim in plan.preempted:
            victim._t_requeue = now
            evs.append(("i", self._ENGINE_TRACK, "preempt", now, 0.0,
                        None, {"rid": victim.rid,
                               "trace": victim.trace_id,
                               "preemptions": victim.preemptions}))
        drafted = 0
        for ch in plan.chunks:
            name = ("spec_decode" if ch.draft_tokens
                    else "decode" if ch.is_decode else "prefill")
            drafted += len(ch.draft_tokens)
            evs.append(("X", self._slot_track(ch.req.slot), name,
                        t_start, dur,
                        None, {"rid": ch.req.rid,
                               "trace": ch.req.trace_id,
                               "start": ch.start, "end": ch.end,
                               "drafted": len(ch.draft_tokens)}))
        n_dec = plan.num_decode_lanes
        n_pre = plan.num_prefill_lanes
        evs.append(("X", self._ENGINE_TRACK, "step", t_start, dur,
                    None, {"step": step_idx, "decode_lanes": n_dec,
                           "prefill_lanes": n_pre, "drafted": drafted,
                           "rung": rung}))
        evs.append(("C", self._ENGINE_TRACK, "pool_occupancy", t_end,
                    occupancy, None, None))
        evs.append(("C", self._ENGINE_TRACK, "rung", t_end,
                    float(rung), None, None))
        tel.emit(evs)
        if plan.chunks and self.chunked_prefill:
            # O(1) context length — Request.context materializes a
            # prompt+out_tokens list copy, far too hot for every step
            ctxs = [len(ch.req.prompt) + len(ch.req.out_tokens)
                    for ch in plan.chunks
                    if ch.is_decode] or [ch.end for ch in plan.chunks]
            ctx_b = pow2_bucket(int(sum(ctxs) / len(ctxs)))
            pre_b = pow2_bucket(n_pre)
            pred = self._drift_predicted(ctx_b)
            if pred is not None:
                tel.record_drift(
                    "serve", self._drift_regime(n_dec, pre_b, ctx_b),
                    pred[0], dt, breakdown=pred[1])

    # ---------------- per-request latency attribution ------------------
    def explain_request(self, rid: int) -> dict:
        """Additive latency attribution for request `rid` of the most
        recent generate()/session run (docs/observability.md
        "Per-request latency attribution"): fold its spans into
        ``{queue, routing, prefill, transfer, decode, preempt_stall,
        retry, other}`` seconds summing to its measured wall latency
        EXACTLY (gated within 1% in CI). Needs telemetry enabled and a
        finished request; rids are ``last_stats['requests'][i]['rid']``.
        Adds ``rid``/``outcome``/``tokens`` to the breakdown."""
        if not self.telemetry.enabled:
            raise RuntimeError(
                "explain_request needs telemetry (pass telemetry= or "
                "set --telemetry/--trace-out)")
        req = self._last_reqs.get(rid)
        if req is None:
            raise KeyError(
                f"rid {rid} is not in the last run "
                f"({sorted(self._last_reqs)})")
        if not req.t_finish:
            raise ValueError(
                f"request {rid} has no finish stamp (outcome "
                f"{req.outcome!r}) — only terminated requests are "
                f"attributable")
        out = self.telemetry.explain_request(
            req.trace_id, req.t_submit, req.t_finish)
        out.update(rid=req.rid, outcome=req.outcome,
                   tokens=len(req.out_tokens),
                   # the admission-time spill-vs-recompute decision
                   # (None when the host tier never matched this
                   # request): priced dma_s vs recompute_s and what
                   # was chosen — next to the host_reload component
                   # the span fold attributes
                   host_reload=getattr(req, "host_reload", None))
        return out

    def fold_attribution(self, registry=None) -> dict:
        """Fold EVERY terminated request of the last run through
        :meth:`explain_request` into `registry` (default: the engine's
        lifetime registry) — the pool-level aggregate
        (`serve_latency_attribution_seconds_total{component}` + the
        derived fraction gauges). Returns the per-component second
        totals of this fold. On-demand, never on the serving hot path
        (the ≤1.03x overhead gate covers recording, not analysis)."""
        from ..utils.telemetry import (REQUEST_COMPONENTS,
                                       fold_attribution)
        m = registry if registry is not None else self.telemetry.metrics
        totals = {c: 0.0 for c in REQUEST_COMPONENTS}
        if not self.telemetry.enabled:
            # no spans to attribute — and the disabled singleton's
            # registry is process-shared, so never write into it
            return totals
        for rid, req in sorted(self._last_reqs.items()):
            if not req.t_finish:
                continue
            b = self.telemetry.explain_request(
                req.trace_id, req.t_submit, req.t_finish)
            fold_attribution(b, m)
            for c, v in b["components"].items():
                totals[c] += v
        return totals

    # ---------------- failure flight recorder ---------------------------
    def postmortem_bundle(self, reason: str = "manual",
                          detail: Optional[dict] = None,
                          sched=None) -> dict:
        """Assemble the bounded post-mortem bundle (docs/observability
        "Failure flight recorder"): the last-N ring spans, metrics +
        drift snapshots, the HBM memory ledger, scheduler and KV-pool
        state, fault accounting and compile counts — everything an
        operator needs to reconstruct a failure post-hoc, bounded so a
        pathological run cannot produce an unbounded artifact. Every
        sub-collector is individually guarded: a broken ledger must
        not cost the spans."""
        tel = self.telemetry
        if sched is None:
            sched = self._session.sched if self._session else None
        bundle = {
            "schema": "flexflow_tpu.postmortem/1",
            "reason": str(reason),
            "detail": dict(detail or {}),
            "created_unix_s": time.time(),
            "engine": {
                "mode": "chunked" if self.chunked_prefill else "legacy",
                "mixed_width": self.mixed_width,
                "tensor_parallel": self.tp,
                "kv_dtype": self.kv_dtype,
                "max_seqs": self.cache_cfg.max_seqs,
                "prefill_budget": self.prefill_budget,
                "track_process": self._proc,
            },
            "compile_counts": self.compile_counts(),
            "events": tel.events_tail(self.postmortem_events),
            "events_dropped": tel.dropped_events,
        }
        for key, collect in (
                ("metrics", lambda: tel.metrics.snapshot()),
                ("drift", tel.drift_snapshot),
                ("memory_ledger", self.memory_ledger),
                ("scheduler", (sched.debug_state if sched is not None
                               else lambda: None)),
                ("kv_pool", self.cache.debug_state),
                ("adapter_pool", lambda: (
                    self.adapters.debug_state()
                    if self.adapters is not None else None)),
                ("faults", lambda: {
                    "fired": {s: dict(k) for s, k in
                              getattr(self.faults, "fired",
                                      {}).items()},
                    "site_hits": dict(getattr(self.faults, "_count",
                                              {}))}),
                ("last_stats", lambda: self._trimmed_last_stats())):
            try:
                bundle[key] = collect()
            except Exception as e:   # a collector bug loses ONE section
                bundle[key] = {"error": f"{type(e).__name__}: {e}"}
        return bundle

    def _trimmed_last_stats(self) -> Optional[dict]:
        st = self.last_stats
        if not st:
            return None
        st = dict(st)
        reqs = st.get("requests")
        if isinstance(reqs, list) and len(reqs) > 64:
            st["requests"] = reqs[-64:]
            st["requests_trimmed"] = len(reqs) - 64
        # per-step timing lists grow with the run — the bundle keeps
        # the aggregates, tools/postmortem.py renders from those
        for k in ("decode_step_times_s", "decode_widths",
                  "prefill_times_s"):
            v = st.get(k)
            if isinstance(v, list) and len(v) > 256:
                st[k] = v[-256:]
        return st

    def _postmortem_path(self, reason: str) -> str:
        """THE bundle naming scheme — `postmortem-<reason>-<pid>-<n>
        .json` under postmortem_dir (CWD when unset). One definition:
        the pool/cluster dump_postmortem variants route through their
        lead engine's counter here, and tools/postmortem.py's glob
        patterns depend on it."""
        base = self.postmortem_dir or "."
        os.makedirs(base, exist_ok=True)
        self._postmortem_seq += 1
        return os.path.join(
            base, f"postmortem-{reason}-{os.getpid()}-"
                  f"{self._postmortem_seq}.json")

    def dump_postmortem(self, path: Optional[str] = None,
                        reason: str = "manual",
                        detail: Optional[dict] = None,
                        sched=None) -> str:
        """Write the post-mortem bundle via atomic tmp+rename and
        return the path (default: :meth:`_postmortem_path` under
        ``postmortem_dir``, or the CWD when unset). Explicit trigger —
        always writes, no rate limit. The bundle loads with
        ``tools/postmortem.py``."""
        from ..utils.telemetry import write_json_atomic
        bundle = self.postmortem_bundle(reason, detail, sched=sched)
        if path is None:
            path = self._postmortem_path(reason)
        return write_json_atomic(path, bundle)

    def _auto_postmortem(self, reason: str, sched=None,
                         detail: Optional[dict] = None) -> Optional[str]:
        """Auto-triggered flight-recorder dump (fault-abort, deadline
        storm, rung-4 rejection): only when ``postmortem_dir`` is
        armed, rate-limited, and NEVER raises — a black-box failure
        must not mask the failure it was recording."""
        if not self.postmortem_dir or not self.telemetry.enabled:
            return None
        now = time.monotonic()
        if now - self._postmortem_last < self.POSTMORTEM_MIN_INTERVAL_S:
            return None
        self._postmortem_last = now
        try:
            path = self.dump_postmortem(reason=reason, detail=detail,
                                        sched=sched)
            if self.telemetry.enabled:
                self.telemetry.instant(
                    self._ENGINE_TRACK, "postmortem_dump",
                    args={"reason": reason, "path": path})
            return path
        except Exception:
            return None

    # ---------------- memory ledger ------------------------------------
    def memory_ledger(self) -> dict:
        """Per-device HBM byte accounting for this engine — params, KV
        pages + scale rows, the mixed step's activation estimate, and
        adapter headroom (reserved for the multi-tenant LoRA pool,
        ROADMAP) — next to the simulator's HBM-penalty input
        (cost_model.serve_device_bytes) so a mis-priced memory term is
        visible before it mis-ranks a placement. ``live_bytes`` reads
        the ACTUAL device buffers (shard-aware nbytes); the ledger's
        params + KV accounting must match it (ci.sh gates within 5%).
        Components land as ``serve_hbm_bytes{component=...}`` gauges on
        the engine's registry, so the ledger is scrapeable."""
        from ..search.cost_model import serve_device_bytes
        from ..search.explain import pytree_device_bytes
        c = self.cache_cfg
        t = max(1, self.tp)
        params = pytree_device_bytes(self._step_params)
        kv_pool = float(c.pool_device_bytes)   # values + scale rows
        act_itemsize = float(self.act_dtype.itemsize)
        # live set of ONE mixed step: lane activations through the
        # widest shards (qkv, ffn hidden, logits) — an estimate, the
        # jitted program's true peak is XLA's to schedule
        activations = float(self.mixed_width) * act_itemsize * (
            self.hidden + 3.0 * self.num_heads * self.head_dim / t
            + float(self._ff_pad) / t + float(self._vocab_pad) / t)
        # adapter slab pool (serve/adapters.py): the config-derived
        # per-device bytes; 0.0 unarmed (the pre-adapter headroom line)
        adapter = (float(self.adapter_cfg.pool_device_bytes)
                   if self.adapter_cfg is not None else 0.0)
        total = params + kv_pool + activations + adapter
        pools_live = self._k_pages is not None
        adapters_live = self._adapter_slabs is not None
        live = params + pytree_device_bytes(
            (self._k_pages, self._v_pages,
             self._k_scales, self._v_scales, self._adapter_slabs))
        arch = self.serve_arch()
        sim_input = float(serve_device_bytes(arch, t))
        ledger = {
            "tensor_parallel": t,
            "params_bytes": params,
            "kv_pool_bytes": kv_pool,
            "activation_est_bytes": activations,
            "adapter_bytes": adapter,
            "total_bytes": total,
            # ground truth: live device buffers (params + allocated
            # pools); pools allocate lazily on the first generate()
            "live_bytes": live,
            "pools_live": pools_live,
            "adapters_live": adapters_live,
            "ledger_vs_live": (
                (params + kv_pool
                 + (adapter if adapters_live else 0.0)) / live
                if pools_live and live > 0 else None),
            # the simulator's HBM-penalty input for this engine's arch
            # (steady-state context KV, not the allocated pool)
            "sim_hbm_input_bytes": sim_input,
        }
        try:
            from ..search.machine_model import default_machine_model
            mm = default_machine_model(machine_file=getattr(
                self.config, "machine_model_file", None))
            ledger["hbm_capacity_bytes"] = float(mm.spec.hbm_capacity)
            ledger["hbm_utilization"] = total / ledger[
                "hbm_capacity_bytes"]
        except Exception:
            pass  # no machine model — the byte accounting stands alone
        tel = self.telemetry
        if tel.enabled:
            for comp in ("params", "kv_pool", "activation_est",
                         "adapter", "total", "live",
                         "sim_hbm_input"):
                tel.metrics.set("serve_hbm_bytes",
                                ledger[f"{comp}_bytes"], component=comp)
        return ledger

    def close(self) -> None:
        """Shut down host-side services (the /metrics endpoint thread).
        Idempotent; the engine remains usable for generate() after
        close — only the scrape endpoint goes away."""
        server, self.metrics_server = self.metrics_server, None
        if server is not None:
            server.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ---------------- the serving loop ---------------------------------
    def generate(self, prompts: Sequence[Sequence[int]],
                 max_new_tokens, eos_token: Optional[int] = None,
                 temperature=None, top_k=None, sample_seed: int = 0,
                 deadline_s=None, on_step=None, on_finish=None,
                 stream_ids: Optional[Sequence[int]] = None,
                 stream_offset: int = 0,
                 trace_ids: Optional[Sequence[int]] = None,
                 tenant_ids: Optional[Sequence[int]] = None
                 ) -> List[List[int]]:
        """Decode a ragged batch under continuous batching.
        `max_new_tokens` is an int or a per-prompt sequence; greedy by
        default, per-request seeded temperature/top-k sampling when
        `temperature` is given (scalar or per-prompt; 0 = greedy).
        Returns the generated tokens (prompt excluded) per prompt, in
        order. Per-request latency, prefix-cache/preemption/utilization
        counters, and per-token timings land in `self.last_stats`
        (render with utils/profiling.serve_report).

        Robustness: `deadline_s` (scalar or per-prompt; falls back to
        FFConfig.serve_request_deadline; 0/None = none) bounds each
        request's wall time from submission — expiry aborts it at a
        chunk boundary with outcome "deadline_expired" and its partial
        tokens are returned. `cancel(rid)` (rids are
        `last_stats["requests"][i]["rid"]`, assigned in prompt order)
        aborts a request the same way. `on_step(step_index)` is called
        after every engine step — the hook chaos tests drive cancels
        and invariant checks from. `on_finish(req)` is called when a
        request completes, BEFORE its slot releases — its pages are
        still mapped, which is the window a disaggregated prefill
        engine exports them in (serve/disagg.py passes
        `lambda r: export_kv(r.slot, r.context)` here). A mid-batch
        exception fails only the in-flight requests and the engine
        keeps serving (_fail_inflight).

        `stream_ids` (per-prompt, default None = the local rid) and
        `stream_offset` key the seeded sampling draws to an engine-
        independent stream identity (docs/serving.md "Sampled
        streams"): a DisaggCluster resumes each request's stream at
        offset 1 on the decode role, and a routed replica draws the
        exact stream a single-replica engine would — token streams
        survive crossing schedulers instead of being refused.

        The chunked path runs through a :class:`ServeSession` (the
        steppable form the multi-replica router drives directly);
        generate() is submit-everything + drain over it, so both
        tiers serve through one code path."""
        c = self.cache_cfg
        cache = self.cache
        if isinstance(max_new_tokens, int):
            max_new_tokens = [max_new_tokens] * len(prompts)
        if len(max_new_tokens) != len(prompts):
            raise ValueError(
                f"max_new_tokens has {len(max_new_tokens)} entries for "
                f"{len(prompts)} prompts")
        samples = self._sample_params(temperature, top_k, sample_seed,
                                      len(prompts), self.topk_cap)
        if deadline_s is None and self.default_deadline > 0:
            deadline_s = self.default_deadline
        if deadline_s is not None and np.isscalar(deadline_s):
            deadline_s = [deadline_s] * len(prompts)
        if deadline_s is not None and len(deadline_s) != len(prompts):
            raise ValueError(
                f"deadline_s has {len(deadline_s)} entries for "
                f"{len(prompts)} prompts")
        if stream_ids is not None and len(stream_ids) != len(prompts):
            raise ValueError(
                f"stream_ids has {len(stream_ids)} entries for "
                f"{len(prompts)} prompts")
        if trace_ids is not None and len(trace_ids) != len(prompts):
            raise ValueError(
                f"trace_ids has {len(trace_ids)} entries for "
                f"{len(prompts)} prompts")
        if tenant_ids is not None and len(tenant_ids) != len(prompts):
            raise ValueError(
                f"tenant_ids has {len(tenant_ids)} entries for "
                f"{len(prompts)} prompts")
        if tenant_ids is not None and any(tenant_ids) \
                and self.adapters is None:
            raise ValueError(
                "tenant_ids != 0 need an armed adapter pool "
                "(adapter_rank > 0); this engine serves base-only")
        if self.chunked_prefill:
            return self._generate_session(
                prompts, max_new_tokens, samples, eos_token,
                deadline_s, stream_ids, stream_offset, on_step,
                on_finish, trace_ids, tenant_ids)
        # ---- legacy bucket path: its own scheduler + orphan recovery
        # (the chunked path's ServeSession owns both)
        if cache.free_slots != c.max_seqs:
            # a previous batch died WITHOUT _fail_inflight running (a
            # BaseException like KeyboardInterrupt mid-loop, or a user
            # driving the scheduler directly): reclaim the orphaned
            # slots/pages AND reset the pool state — the registry may
            # vouch for arrays the dead batch lost, and donation may
            # have consumed the pools — then keep serving. The
            # PR-3-era answer ("build a fresh ServeEngine") threw away
            # a warm compiled program for a recoverable host state.
            cache.release_all()
            self._reset_pool_state()
        sched = ContinuousBatchingScheduler(
            cache, prefill_token_budget=self.prefill_budget,
            chunked_prefill=False,
            admit_watermark=self.admit_watermark,
            spec_tokens=self.spec_tokens, drafter=self.drafter,
            faults=self.faults, degrade_ladder=self.degrade_ladder,
            reject_stalls=self.reject_stalls)
        reqs: List[Request] = []
        t0 = time.perf_counter()
        for i, (prompt, mnt, sp) in enumerate(
                zip(prompts, max_new_tokens, samples)):
            r = sched.submit(prompt, mnt, eos_token=eos_token, sample=sp,
                             stream_id=(stream_ids[i]
                                        if stream_ids is not None
                                        else None),
                             stream_offset=stream_offset,
                             trace_id=(trace_ids[i]
                                       if trace_ids is not None
                                       else None))
            r.t_submit = time.perf_counter()
            if deadline_s is not None and deadline_s[i] \
                    and float(deadline_s[i]) > 0:
                r.t_deadline = r.t_submit + float(deadline_s[i])
            reqs.append(r)
            self._active[r.rid] = r
        kp, vp = self._device_pages()
        steps = 0
        decode_times: List[float] = []   # seconds per step with decodes
        decode_widths: List[int] = []    # decode lanes per such step
        prefill_times: List[Tuple[int, float]] = []  # (lanes, seconds)
        util: List[float] = []           # resident-page fraction per step

        def emit(chunk: ChunkPlan, greedy, topv, topi) -> None:
            req = chunk.req
            tok = self._pick_token(req, greedy, topv, topi)
            req.out_tokens.append(tok)
            if len(req.out_tokens) == 1:
                req.t_first_token = time.perf_counter()
            if req.is_done():
                req.t_finish = time.perf_counter()
                if on_finish is not None:
                    on_finish(req)
                sched.finish(req)

        retries0 = self._retries
        tel = self.telemetry
        try:
            kp, vp = self._run_legacy(sched, cache, kp, vp, emit,
                                      decode_times, decode_widths,
                                      prefill_times, util, on_step)
            steps = len(util)
        except Exception:
            self._fail_inflight(sched, reqs)
            raise
        finally:
            self._active.clear()
            self._cancels.clear()
            # chaos runs stay inspectable post-hoc (docs/robustness.md):
            # the injector's fired accounting and the Chrome trace
            # flush even when a fault aborts the run (every span is
            # already in the ring by the time the dispatch raised), and
            # an unwritable --trace-out path must not fail a generate
            # that already produced tokens (fit() makes both promises
            # in its own finally)
            if tel.enabled:
                tel.record_faults(self.faults)
                if self.trace_out:
                    try:
                        tel.export_chrome_trace(self.trace_out)
                    except OSError:
                        pass
        self._k_pages, self._v_pages = kp, vp
        cache.check_invariants()
        assert cache.free_pages == c.usable_pages, "pages leaked"
        self.last_stats = self._build_stats(
            reqs, sched, wall=time.perf_counter() - t0, steps=steps,
            retries0=retries0, decode_times=decode_times,
            decode_widths=decode_widths, prefill_times=prefill_times,
            util=util)
        # fold this run into the engine-lifetime telemetry registry
        # (counters accumulate, gauges overwrite, histograms extend) —
        # the same canonical definitions serve_report renders from
        # (fault accounting + the trace flush already happened in the
        # finally above, so aborted runs get them too)
        if tel.enabled:
            serve_metrics(self.last_stats, registry=tel.metrics)
        self._last_reqs = {r.rid: r for r in reqs}
        return [list(r.out_tokens) for r in reqs]

    def _build_stats(self, reqs, sched, *, wall, steps, retries0,
                     decode_times, decode_widths, prefill_times,
                     util) -> dict:
        """The last_stats dict — ONE construction shared by
        generate()'s legacy path and ServeSession.stats_dict() (the
        chunked path and every routed replica), so the stats surface
        cannot fork between tiers."""
        c = self.cache_cfg
        cache = self.cache
        total_new = sum(len(r.out_tokens) for r in reqs)
        peak_util = float(np.max(util)) if util else 0.0
        return {
            "requests": [
                {"rid": r.rid, "trace_id": r.trace_id,
                 "tenant": int(getattr(r, "tenant_id", 0)),
                 "prompt_tokens": len(r.prompt),
                 "new_tokens": len(r.out_tokens),
                 "preemptions": r.preemptions,
                 "outcome": r.outcome,
                 "ttft_s": (r.t_first_token - r.t_submit
                            if r.t_first_token else None),
                 "latency_s": (r.t_finish - r.t_submit
                               if r.t_finish else None)}
                for r in reqs],
            "mode": "chunked" if self.chunked_prefill else "legacy",
            "wall_s": wall,
            "total_new_tokens": total_new,
            "tokens_per_sec": total_new / wall if wall > 0 else 0.0,
            "steps": steps,
            "decode_steps": len(decode_times),
            "decode_step_times_s": decode_times,
            "decode_widths": decode_widths,
            "prefill_times_s": prefill_times,
            "compile_counts": self.compile_counts(),
            # prefix cache / chunked prefill / preemption instrumentation
            "prompt_tokens_total": sched.stats["prompt_tokens"],
            "prefill_tokens_computed": sched.stats["prefill_lane_tokens"],
            "prefix_hit_tokens": sched.stats["prefix_hit_tokens"],
            "preemptions": sched.stats["preemptions"],
            # speculative decoding instrumentation: decode_tokens are
            # the tokens decode chunks emitted, decode lane-steps the
            # times a sequence occupied a decode lane — their ratio is
            # per-sequence steps per token, exactly 1.0 without
            # speculation and < 1.0 when accepted drafts advance a
            # sequence several tokens per dispatched step
            "spec_tokens": self.spec_tokens,
            "spec_drafted_tokens": sched.stats["spec_drafted_tokens"],
            "spec_accepted_tokens": sched.stats["spec_accepted_tokens"],
            "spec_acceptance": (
                sched.stats["spec_accepted_tokens"]
                / sched.stats["spec_drafted_tokens"]
                if sched.stats["spec_drafted_tokens"] else 0.0),
            "decode_tokens": int(sum(decode_widths)),
            "steps_per_decode_token": (
                sched.stats["decode_lane_tokens"] / sum(decode_widths)
                if decode_widths else 0.0),
            "page_util_mean": float(np.mean(util)) if util else 0.0,
            "page_util_max": peak_util,
            # robustness instrumentation (docs/robustness.md): abort /
            # deadline / rejection outcomes, retried dispatches, and
            # how far up the degradation ladder this batch climbed
            "cancelled": sched.stats["cancelled"],
            "deadline_expired": sched.stats["deadline_expired"],
            "rejected": sched.stats["rejected"],
            "rejected_requests": [(rr.rid, rr.reason)
                                  for rr in sched.rejected_requests],
            "retries": self._retries - retries0,
            "degradation_rung_max": sched.stats["degradation_rung_max"],
            "rung_steps": list(sched.stats["rung_steps"]),
            "spec_shed_steps": sched.stats["spec_shed_steps"],
            "cache": dict(cache.stats),   # engine-lifetime counters
            # tensor-parallel sharding block (None single-device):
            # mesh shape, heads/device, per-device pool bytes, and the
            # analytic per-step collective payload (serve_report
            # renders it; tools/serve_bench.py --workload shard records
            # it next to the measured A/B)
            "sharding": self._sharding_stats(),
            # KV pool: storage format, itemsize-derived byte accounting,
            # effective capacity vs f32 pages, and the ragged kernel
            # v2 work-item accounting (serve_report renders both)
            "kv_pool": {
                **cache.pool_report(),
                # pool_report's occupancy is instantaneous and every
                # slot is already released here — report the run's
                # peak residency (what --kv-pool-mb tuning needs)
                "occupancy": peak_util,
                "kv_exact": self.kv_exact,
                "attn_block_kv": self.attn_block_kv,
                "attn_dispatch_passes": {
                    k: v * steps for k, v in ragged_dispatch_passes(
                        self.mixed_width, c.pages_per_seq,
                        max(1, self.attn_block_kv // c.page_size)
                    ).items()} if self.chunked_prefill else None,
            },
            # hierarchical host tier (None unarmed): the shared
            # store's occupancy + spill/reload/hit counters plus THIS
            # engine's reload accounting (a ReplicaPool's replicas
            # report one store, each with its own engine counters)
            "host_tier": (
                {**self.host_tier.report(),
                 **{k: (float(v) if isinstance(v, float) else int(v))
                    for k, v in self._host_reload_stats.items()}}
                if self.host_tier is not None else None),
            # multi-tenant adapter pool (None unarmed): slot geometry,
            # residency, and the hit/evict/load/stall counters the
            # tenant-labeled metrics fold reads (serve/adapters.py)
            "adapter_pool": (
                {**self.adapters.pool_report(),
                 **{k: int(v) for k, v in self.adapters.stats.items()},
                 "blocked_steps":
                     sched.stats["adapter_blocked_steps"]}
                if self.adapters is not None else None),
        }

    def start_session(self) -> "ServeSession":
        """Open an incremental serving session — the engine hook the
        multi-replica router tier drives (serve/router.py): submit
        requests at any time, advance ONE mixed step per
        :meth:`ServeSession.step` call, ``close()`` when done.
        generate() is submit-everything + drain over the same session
        machinery, so a routed replica serves through exactly the code
        path the single-engine contracts (token parity, zero
        recompiles, invariants) are proven on. Chunked engines only;
        at most one live session per engine (the session's scheduler
        owns the slots)."""
        return ServeSession(self)

    def _generate_session(self, prompts, max_new_tokens, samples,
                          eos_token, deadline_s, stream_ids,
                          stream_offset, on_step, on_finish,
                          trace_ids=None,
                          tenant_ids=None) -> List[List[int]]:
        """generate()'s chunked path: one ServeSession, every prompt
        submitted up front, stepped to drain — behavior-identical to
        the pre-session inline loop (same sweep/plan/dispatch order,
        same stats, same failure containment)."""
        session = self.start_session()
        reqs = session.reqs
        tel = self.telemetry
        try:
            # submits inside the containment: a submit-time rejection
            # (e.g. an unregistered adapter tenant) must fail the
            # batch AND close the session, not orphan it open
            for i, (prompt, mnt, sp) in enumerate(
                    zip(prompts, max_new_tokens, samples)):
                session.submit(
                    prompt, mnt, eos_token=eos_token, sample=sp,
                    deadline_s=(deadline_s[i] if deadline_s is not None
                                else None),
                    stream_id=(stream_ids[i] if stream_ids is not None
                               else None),
                    stream_offset=stream_offset, on_finish=on_finish,
                    trace_id=(trace_ids[i] if trace_ids is not None
                              else None),
                    tenant_id=(int(tenant_ids[i])
                               if tenant_ids is not None else 0))
            while True:
                ev = session.step()
                if ev is None:
                    break
                if ev.dispatched and on_step is not None:
                    on_step(ev.step_index)
        except Exception:
            self._fail_inflight(session.sched, reqs)
            raise
        finally:
            session.close()
            self._active.clear()
            self._cancels.clear()
            # chaos runs stay inspectable post-hoc (docs/robustness.md):
            # the injector's fired accounting and the Chrome trace
            # flush even when a fault aborts the run, and an unwritable
            # --trace-out path must not fail a generate that already
            # produced tokens
            if tel.enabled:
                tel.record_faults(self.faults)
                if self.trace_out:
                    try:
                        tel.export_chrome_trace(self.trace_out)
                    except OSError:
                        pass
        self.cache.check_invariants()
        assert self.cache.free_pages == self.cache_cfg.usable_pages, \
            "pages leaked"
        self.last_stats = session.stats_dict()
        # fold this run into the engine-lifetime telemetry registry —
        # the same canonical definitions serve_report renders from
        if tel.enabled:
            serve_metrics(self.last_stats, registry=tel.metrics)
        return [list(r.out_tokens) for r in reqs]

    def _run_legacy(self, sched, cache, kp, vp, emit, decode_times,
                    decode_widths, prefill_times, util, on_step=None):
        """The PR 1 two-program loop (serve_chunked_prefill=False):
        per-request bucketed prefill, then one full-width decode —
        kept as the A/B baseline and the bucketed-prefill fallback."""
        c = self.cache_cfg
        ps = c.page_size
        while sched.has_work():
            self._sweep_aborts(sched)
            if not sched.has_work():
                break
            plan = sched.schedule()
            if not plan.chunks:
                continue
            t_step0 = time.perf_counter()
            pre = [ch for ch in plan.chunks if not ch.is_decode]
            dec = [ch for ch in plan.chunks if ch.is_decode]
            for ch in pre:
                req = ch.req
                ctx = req.context
                b = self.bucket_for(len(ctx))
                toks = np.zeros((1, b), np.int32)
                toks[0, :len(ctx)] = ctx
                tp = time.perf_counter()
                last, kp, vp = self._call_counted(
                    "prefill", self._prefill_jit, self.params, kp, vp,
                    jnp.asarray(toks), jnp.int32(len(ctx)),
                    jnp.asarray(cache.page_tables[req.slot]))
                logits = np.asarray(last)
                prefill_times.append((b, time.perf_counter() - tp))
                sched.complete_chunk(ch)
                order = np.argsort(logits)[::-1][:self.topk_cap]
                # np.argmax, not order[0]: argsort's descending tie
                # order differs from argmax's first-wins (the parity
                # contract with generate_reference is argmax's)
                emit(ch, int(np.argmax(logits)), logits[order], order)
            if dec:
                tokens = np.zeros((c.max_seqs,), np.int32)
                positions = np.zeros((c.max_seqs,), np.int32)
                write_pages = np.zeros((c.max_seqs,), np.int32)  # sink
                write_offs = np.zeros((c.max_seqs,), np.int32)
                # the decode step must see the new token (position i
                # attends keys 0..i), so lengths include it up front
                seq_lens = np.maximum(np.asarray(cache.seq_lens), 1)
                for ch in dec:
                    s, pos = ch.req.slot, ch.start
                    tokens[s] = ch.req.context[pos]
                    positions[s] = pos
                    write_pages[s] = cache.page_tables[s, pos // ps]
                    write_offs[s] = pos % ps
                    seq_lens[s] = ch.end
                tp = time.perf_counter()
                nxt, topv, topi, kp, vp = self._call_counted(
                    "decode", self._decode_jit, self.params, kp, vp,
                    jnp.asarray(tokens), jnp.asarray(positions),
                    jnp.asarray(write_pages), jnp.asarray(write_offs),
                    jnp.asarray(cache.page_tables), jnp.asarray(seq_lens))
                nxt = np.asarray(nxt)    # ONE device->host fetch per step
                topv = np.asarray(topv)
                topi = np.asarray(topi)
                decode_times.append(time.perf_counter() - tp)
                decode_widths.append(len(dec))
                for ch in dec:
                    sched.complete_chunk(ch)
                    emit(ch, nxt[ch.req.slot], topv[ch.req.slot],
                         topi[ch.req.slot])
            util.append(1.0 - cache.free_pages / c.usable_pages)
            if self.telemetry.enabled:
                # legacy-path steps get the engine-track span + pool
                # counter (no drift: the cost model prices the mixed
                # program, not the bucketed prefill/decode pair)
                self._record_step_telemetry(
                    self.telemetry, plan, len(util) - 1,
                    t_step0, time.perf_counter() - t_step0,
                    sched.rung, util[-1])
            if on_step is not None:
                on_step(len(util) - 1)
        return kp, vp

    def generate_reference(self, prompts: Sequence[Sequence[int]],
                           max_new_tokens,
                           eos_token: Optional[int] = None
                           ) -> List[List[int]]:
        """Naive no-cache greedy decode: re-forward the WHOLE sequence
        for every new token, one request at a time. O(n^2) per token —
        the correctness oracle generate() is tested against."""
        if isinstance(max_new_tokens, int):
            max_new_tokens = [max_new_tokens] * len(prompts)
        if len(max_new_tokens) != len(prompts):
            raise ValueError(
                f"max_new_tokens has {len(max_new_tokens)} entries for "
                f"{len(prompts)} prompts")
        out: List[List[int]] = []
        for prompt, mnt in zip(prompts, max_new_tokens):
            if mnt < 1:  # mirror scheduler.submit's contract
                raise ValueError(f"max_new_tokens must be >= 1, got {mnt}")
            toks = list(prompt)
            new: List[int] = []
            while len(new) < mnt:
                b = self.bucket_for(len(toks))
                arr = np.zeros((1, b), np.int32)
                arr[0, :len(toks)] = toks
                logits = self._forward_jit(self.params, jnp.asarray(arr),
                                           jnp.int32(len(toks)))
                tok = int(jnp.argmax(logits))
                new.append(tok)
                toks.append(tok)
                if eos_token is not None and tok == eos_token:
                    break
            out.append(new)
        return out


class StepEvents:
    """What one :meth:`ServeSession.step` did — the router tier's
    window into a replica's progress (serve/router.py advances each
    replica's virtual clock by a cost-model-priced step and stamps
    TTFT/TPOT off these). ``emitted`` is [(request, tokens emitted
    this step)] (speculation can emit several per step), ``finished``
    the requests that completed THIS step, ``ctx_mean`` the mean
    decode-context length (the drift calibrator's pricing regime),
    ``dispatched`` False for a planning-only iteration (rung-4
    rejections / whole-set preemption under injected pressure — the
    scheduler's forced-progress rule guarantees re-planning
    converges)."""

    __slots__ = ("dispatched", "step_index", "plan", "emitted",
                 "finished", "ctx_mean", "wall_s", "host_reload_s")

    def __init__(self, plan=None):
        self.dispatched = False
        self.step_index = -1
        self.plan = plan
        self.emitted: List[Tuple[Request, int]] = []
        self.finished: List[Request] = []
        self.ctx_mean = 0
        self.wall_s = 0.0
        # priced host-tier DMA seconds this step's admissions spent
        # (the router adds it to the virtual clock; wall mode measures
        # it inside the step wall time naturally)
        self.host_reload_s = 0.0


class ServeSession:
    """Incremental (steppable) serving over one ServeEngine.

    The engine hook of the multi-replica tier (serve/router.py): a
    ReplicaPool keeps ONE long-lived session per replica, submits
    requests as routed traffic arrives, and advances each replica one
    mixed step at a time — while generate() drives the very same
    session submit-all + drain, so the two tiers cannot fork. The
    session owns the scheduler (and with it the engine's slots); at
    most one is live per engine until ``close()``.

    The step body is the former ``_run_chunked`` loop body verbatim:
    sweep cancels/deadlines at the chunk boundary, plan, pack lanes,
    dispatch the ONE mixed program, bookkeeping first / emission
    second / speculative verification last."""

    def __init__(self, engine: ServeEngine):
        if not engine.chunked_prefill:
            raise ValueError(
                "serving sessions need the chunked mixed program "
                "(serve_chunked_prefill=True); the legacy bucket path "
                "has no single-step form")
        if engine._session is not None:
            raise RuntimeError(
                "engine already has a live ServeSession — close() it "
                "first (the session's scheduler owns the slots)")
        self.eng = engine
        cache = engine.cache
        c = engine.cache_cfg
        if cache.free_slots != c.max_seqs:
            # same orphan recovery as the pre-session generate(): a
            # previous batch died without _fail_inflight running —
            # reclaim slots/pages, reset the pool state, serve on
            cache.release_all()
            engine._reset_pool_state()
        self.sched = ContinuousBatchingScheduler(
            cache, prefill_token_budget=engine.prefill_budget,
            chunked_prefill=True,
            admit_watermark=engine.admit_watermark,
            spec_tokens=engine.spec_tokens, drafter=engine.drafter,
            faults=engine.faults,
            degrade_ladder=engine.degrade_ladder,
            reject_stalls=engine.reject_stalls,
            adapter_pool=engine.adapters,
            host_reload=(engine._host_reload
                         if engine.host_tier is not None else None))
        self.reqs: List[Request] = []
        self._on_finish: Dict[int, object] = {}
        self.decode_times: List[float] = []
        self.decode_widths: List[int] = []
        self.prefill_times: List[Tuple[int, float]] = []
        self.util: List[float] = []
        self._retries0 = engine._retries
        self._rejected_seen = 0   # flight-recorder rejection trigger
        self._t0 = time.perf_counter()
        engine._device_pages()
        engine._session = self

    # ---------------- submission ---------------------------------------
    def submit(self, prompt: Sequence[int], max_new_tokens: int, *,
               eos_token: Optional[int] = None,
               sample: Optional[SampleParams] = None,
               deadline_s: Optional[float] = None,
               stream_id: Optional[int] = None,
               stream_offset: int = 0, on_finish=None,
               trace_id: Optional[int] = None,
               tenant_id: int = 0) -> Request:
        """Queue one request (admission happens at the next step()).
        `sample` is a ready SampleParams (None = greedy); `stream_id`/
        `stream_offset` key its sampling stream (engine._pick_token);
        `trace_id` carries an upstream tier's trace context (router /
        disagg — None mints a fresh one); `on_finish(req)` fires when
        THIS request completes, before its slot releases; `tenant_id`
        selects the tenant's registered LoRA adapter (0 = the base
        model — the only tenant an unarmed engine serves)."""
        r = self.sched.submit(prompt, int(max_new_tokens),
                              eos_token=eos_token, sample=sample,
                              stream_id=stream_id,
                              stream_offset=stream_offset,
                              trace_id=trace_id,
                              tenant_id=tenant_id)
        r.t_submit = time.perf_counter()
        if deadline_s is None and self.eng.default_deadline > 0:
            deadline_s = self.eng.default_deadline
        if deadline_s and float(deadline_s) > 0:
            r.t_deadline = r.t_submit + float(deadline_s)
        if on_finish is not None:
            self._on_finish[r.rid] = on_finish
        self.reqs.append(r)
        self.eng._active[r.rid] = r
        return r

    def has_work(self) -> bool:
        return self.sched.has_work()

    # ---------------- emission -----------------------------------------
    def _finish(self, ev: StepEvents, req: Request) -> None:
        req.t_finish = time.perf_counter()
        cb = self._on_finish.pop(req.rid, None)
        if cb is not None:
            cb(req)
        self.sched.finish(req)
        self.eng._active.pop(req.rid, None)
        ev.finished.append(req)

    def _emit(self, ev: StepEvents, chunk: ChunkPlan, greedy, topv,
              topi) -> None:
        req = chunk.req
        tok = self.eng._pick_token(req, greedy, topv, topi)
        req.out_tokens.append(tok)
        ev.emitted.append((req, 1))
        if len(req.out_tokens) == 1:
            req.t_first_token = time.perf_counter()
        if req.is_done():
            self._finish(ev, req)

    def _emit_spec(self, ev: StepEvents, chunk: ChunkPlan, lane0: int,
                   greedy, topv, topi) -> int:
        """Verify a speculative decode chunk and emit its step's
        tokens: walk lanes lane0..lane0+k (the context token and the k
        drafts), picking each lane's token exactly as sequential
        decode would — lane j's logits are valid BECAUSE every earlier
        pick matched the draft that fed lane j+1 — and stop at the
        first mismatch (that pick IS the corrected token), at EOS /
        max_new, or after the bonus token when every draft held. Then
        the scheduler commits the verified prefix and rolls the
        rejected tail's pages back. Returns the number of tokens
        emitted (1 when k=0 — the plain decode step, bit for bit)."""
        eng = self.eng
        req = chunk.req
        k = len(chunk.draft_tokens)
        matched = emitted = 0
        for j in range(k + 1):
            ln = lane0 + j
            tok = eng._pick_token(req, greedy[ln], topv[ln], topi[ln])
            # (no t_first_token stamp: only decode chunks speculate,
            # and a decoding request already emitted)
            req.out_tokens.append(tok)
            emitted += 1
            ok = j < k and tok == chunk.draft_tokens[j]
            if ok:
                matched += 1
            if req.is_done() or not ok:
                break
        self.sched.complete_spec_chunk(chunk, matched)
        if eng.telemetry.enabled:
            eng.telemetry.instant(
                eng._slot_track(req.slot), "spec_verify",
                args={"rid": req.rid, "trace": req.trace_id,
                      "drafted": k, "accepted": matched,
                      "emitted": emitted})
        ev.emitted.append((req, emitted))
        if req.is_done():
            self._finish(ev, req)
        return emitted

    # ---------------- the step -----------------------------------------
    def step(self) -> Optional[StepEvents]:
        """Advance one engine step. Returns None when the session is
        drained (no waiting or running requests survive the abort
        sweep), else a StepEvents."""
        eng = self.eng
        sched = self.sched
        cache = eng.cache
        c = eng.cache_cfg
        # chunk boundary: cancels and expired deadlines leave the
        # system HERE, before any of this step's chunks exist
        eng._sweep_aborts(sched)
        if not sched.has_work():
            return None
        plan = sched.schedule()
        ev = StepEvents(plan)
        # claim the priced host-tier DMA this plan's admissions spent
        # (carried even on planning-only iterations)
        ev.host_reload_s, eng._host_reload_s = eng._host_reload_s, 0.0
        if sched.stats["rejected"] > self._rejected_seen:
            # rung-4 structured rejection: the ladder refused service —
            # exactly the state an operator wants black-boxed (one
            # bundle per rate-limit window, not one per rejection)
            self._rejected_seen = sched.stats["rejected"]
            eng._auto_postmortem("rejection", sched=sched)
        if not plan.chunks:
            # every waiting request was rejected (rung 4) or the
            # running set was preempted whole under injected pressure;
            # the next step() re-plans (forced progress guarantees
            # this cannot spin)
            return ev
        t_w = eng.mixed_width
        ps = c.page_size
        tokens = np.zeros((t_w,), np.int32)
        positions = np.zeros((t_w,), np.int32)
        write_pages = np.zeros((t_w,), np.int32)   # sink by default
        write_offs = np.zeros((t_w,), np.int32)
        lane_slots = np.zeros((t_w,), np.int32)
        lane_lens = np.ones((t_w,), np.int32)      # NaN-free padding
        # inactive lanes gather adapter slot 0 (the zero base slab)
        lane_adapters = np.zeros((t_w,), np.int32) \
            if eng.adapters is not None else None
        lane = 0
        emitters: List[Tuple[ChunkPlan, int]] = []
        spec_emitters: List[Tuple[ChunkPlan, int]] = []
        for ch in plan.chunks:
            ctx = ch.req.context
            row = cache.page_tables[ch.req.slot]
            aslot = int(getattr(ch.req, "adapter_slot", 0) or 0)
            for pos in range(ch.start, ch.end):
                tokens[lane] = ctx[pos]
                positions[lane] = pos
                write_pages[lane] = row[pos // ps]
                write_offs[lane] = pos % ps
                lane_slots[lane] = ch.req.slot
                lane_lens[lane] = pos + 1
                if lane_adapters is not None:
                    lane_adapters[lane] = aslot
                lane += 1
            if ch.draft_tokens:
                spec_emitters.append((ch, lane - 1))
                for j, d in enumerate(ch.draft_tokens):
                    pos = ch.end + j
                    tokens[lane] = d
                    positions[lane] = pos
                    write_pages[lane] = row[pos // ps]
                    write_offs[lane] = pos % ps
                    lane_slots[lane] = ch.req.slot
                    lane_lens[lane] = pos + 1
                    if lane_adapters is not None:
                        lane_adapters[lane] = aslot
                    lane += 1
            elif ch.emits:
                emitters.append((ch, lane - 1))
        assert lane <= t_w, (
            f"scheduler packed {lane} lanes into a {t_w}-lane step")
        # land any adapters this plan admitted BEFORE their lanes
        # dispatch — the planning-visible load stall, not a recompile
        eng._drain_adapter_loads()
        # ship queued evictions to the host tier BEFORE the dispatch
        # overwrites their pages (the spill-safety window)
        eng._drain_spills()
        tp = time.perf_counter()
        greedy, topv, topi, _, _ = eng._dispatch_mixed(
            eng._k_pages, eng._v_pages,
            jnp.asarray(tokens), jnp.asarray(positions),
            jnp.asarray(write_pages), jnp.asarray(write_offs),
            jnp.asarray(cache.page_tables), jnp.asarray(lane_slots),
            jnp.asarray(lane_lens),
            lane_adapters=(None if lane_adapters is None
                           else jnp.asarray(lane_adapters)))
        greedy = np.asarray(greedy)
        topv = np.asarray(topv)
        topi = np.asarray(topi)
        dt = time.perf_counter() - tp
        self.util.append(1.0 - cache.free_pages / c.usable_pages)
        if eng.telemetry.enabled:
            eng._record_step_telemetry(
                eng.telemetry, plan, len(self.util) - 1, tp, dt,
                sched.rung, self.util[-1])
        # bookkeeping FIRST (page commits hash the context as it was
        # when the chunk ran), emission second; speculative chunks
        # verify LAST — their residency bookkeeping is a function of
        # the tokens they emit
        for ch in plan.chunks:
            if not ch.draft_tokens:
                sched.complete_chunk(ch)
        dec_tokens = 0
        for ch, ln in emitters:
            self._emit(ev, ch, greedy[ln], topv[ln], topi[ln])
            if ch.is_decode:
                dec_tokens += 1
        for ch, ln in spec_emitters:
            dec_tokens += self._emit_spec(ev, ch, ln, greedy, topv,
                                          topi)
        if plan.num_decode_lanes:
            self.decode_times.append(dt)
            # width = tokens this step's decode chunks EMITTED
            # (speculation makes it exceed the decode-lane count),
            # the denominator of per-token decode latency
            self.decode_widths.append(dec_tokens)
        if plan.num_prefill_lanes:
            self.prefill_times.append((plan.num_prefill_lanes, dt))
        ev.dispatched = True
        ev.step_index = len(self.util) - 1
        ev.wall_s = dt
        ctxs = [len(ch.req.prompt) + len(ch.req.out_tokens)
                for ch in plan.chunks if ch.is_decode] \
            or [ch.end for ch in plan.chunks]
        ev.ctx_mean = int(sum(ctxs) / len(ctxs))
        return ev

    # ---------------- stats / lifecycle --------------------------------
    def stats_dict(self) -> dict:
        """This session's last_stats-shaped dict so far (generate()
        publishes it as engine.last_stats; a ReplicaPool folds it per
        replica via serve_metrics(..., replica=...))."""
        return self.eng._build_stats(
            self.reqs, self.sched,
            wall=time.perf_counter() - self._t0,
            steps=len(self.util), retries0=self._retries0,
            decode_times=self.decode_times,
            decode_widths=self.decode_widths,
            prefill_times=self.prefill_times, util=self.util)

    def close(self) -> None:
        """Release the session (idempotent): the engine can open a new
        one. Does NOT force-abort live requests — drain first, or use
        engine.cancel / _fail_inflight for abnormal teardown."""
        if self.eng._session is self:
            self.eng._session = None
        if self.reqs:
            # the closed session's requests become the engine's
            # explain_request(rid) namespace (rids restart per session)
            self.eng._last_reqs = {r.rid: r for r in self.reqs}
        for r in self.reqs:
            self.eng._active.pop(r.rid, None)
            self.eng._cancels.discard(r.rid)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
