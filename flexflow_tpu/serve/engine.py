"""ServeEngine: jitted prefill/decode steps over a paged KV-cache.

Wraps an LM built by models/transformer.build_transformer_lm into the
two functions autoregressive serving actually runs:

  prefill — one sequence's whole prompt in one pass: full causal
    attention (the MXU-friendly shape), K/V scattered into the
    sequence's pages, logits of the LAST real position returned.
  decode  — ONE token for EVERY running sequence as a single batch:
    single-query attention through the page tables
    (kernels/flash_attention.paged_attention_decode), new K/V written
    in-place at each sequence's tail.

Static shapes are the whole game on TPU: decode always runs at the
full slot width (max_seqs lanes; empty lanes aim at the sink page), and
prompts pad to power-of-two token BUCKETS, so XLA compiles one decode
program plus one prefill program per bucket — ever. After
`warmup()` a serving process never recompiles (generate() can assert
this via `compile_counts()`), which is what keeps p99 latency flat.

The engine reads weights straight out of the compiled FFModel's
TrainState and re-implements the block math as pure functions — the
graph executor has no notion of carried state, and threading a cache
through it would force every op to learn about sequence position. The
ops' numerics are mirrored exactly (LayerNorm f32 statistics, f32
matmul accumulation), so `generate_reference` (naive no-cache
re-forward each step) produces identical tokens — the parity test.

Caches flow functionally: generate() owns (k_pages, v_pages) for its
lifetime and threads them through the jitted steps with donated
buffers, so the update is in-place on device and the host never holds
two copies.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import CompMode
from ..kernels.flash_attention import paged_attention_decode
from .kv_cache import KVCacheConfig, PagedKVCache
from .scheduler import ContinuousBatchingScheduler, Request


def _ln(p, x, eps):
    """LayerNorm with f32 statistics — must mirror ops/elementwise.py
    LayerNorm.forward exactly (the reference-parity contract)."""
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    y = y * p["scale"].astype(jnp.float32) + p["bias"].astype(jnp.float32)
    return y.astype(x.dtype)


def _dense(p, x, activation=None):
    y = jnp.dot(x, p["kernel"].astype(x.dtype),
                preferred_element_type=jnp.float32).astype(x.dtype)
    if "bias" in p:
        y = y + p["bias"].astype(x.dtype)
    if activation == "relu":
        y = jax.nn.relu(y)
    return y


class ServeEngine:
    """Continuous-batching generation over a build_transformer_lm model.

    model must be compiled (any comp_mode); if not, it is compiled here
    in INFERENCE mode (no optimizer slots). All serving knobs come from
    the model's FFConfig (kv_page_size / kv_num_pages / serve_max_seqs /
    serve_prefill_budget).
    """

    def __init__(self, model, *, max_seq_len: Optional[int] = None,
                 use_pallas: Optional[bool] = None, interpret: bool = False):
        if model.state is None:
            model.compile(comp_mode=CompMode.INFERENCE)
        self.model = model
        self.config = model.config
        self._use_pallas = use_pallas
        self._interpret = interpret
        self._read_arch(model)
        if max_seq_len is None:
            max_seq_len = self.max_positions
        if max_seq_len > self.max_positions:
            raise ValueError(
                f"max_seq_len {max_seq_len} exceeds the LM's learned "
                f"positions ({self.max_positions})")
        self.cache_cfg = KVCacheConfig.from_ff(
            self.config, num_layers=self.num_layers,
            num_heads=self.num_heads, head_dim=self.head_dim,
            max_seq_len=max_seq_len)
        self.cache_cfg.validate()
        # prompt-length buckets: powers of two from one page up to the
        # page-table ceiling — each bucket is one prefill compilation
        cap = self.cache_cfg.pages_per_seq * self.cache_cfg.page_size
        b = max(self.cache_cfg.page_size, 16)
        self.buckets = []
        while b < cap:
            self.buckets.append(b)
            b *= 2
        self.buckets.append(cap)
        self._prefill_jit = jax.jit(self._prefill_impl,
                                    donate_argnums=(1, 2))
        self._decode_jit = jax.jit(self._decode_impl, donate_argnums=(1, 2))
        self._forward_jit = jax.jit(self._forward_logits)  # naive reference
        # shape signatures seen per serving function: the version-proof
        # compile counter (jit._cache_size is a private API) — a new
        # signature IS a new XLA program under jit
        self._shapes_seen: Dict[str, set] = {"prefill": set(),
                                             "decode": set()}
        self.last_stats: Optional[dict] = None

    def _call_counted(self, name, fn, *args):
        self._shapes_seen[name].add(tuple(
            (tuple(a.shape), str(a.dtype)) for a in args
            if hasattr(a, "shape")))
        return fn(*args)

    # ---------------- model introspection -----------------------------
    def _read_arch(self, model) -> None:
        ops = {op.name: op for op in model.ops}
        for required in ("tok_embed", "pos_embed", "lm_head"):
            if required not in ops:
                raise ValueError(
                    f"ServeEngine needs a build_transformer_lm-shaped "
                    f"model (missing op {required!r})")
        self.vocab_size = ops["tok_embed"].num_entries
        self.max_positions = ops["pos_embed"].num_entries
        self.layer_norm = "layer0_ln1" in ops
        self.num_layers = 0
        while f"layer{self.num_layers}_attn" in ops:
            self.num_layers += 1
        if self.num_layers == 0:
            raise ValueError("model has no layer{i}_attn blocks")
        attn0 = ops[f"layer{0}_attn"]
        if not attn0.causal:
            raise ValueError("serving needs causal attention blocks")
        self.num_heads = attn0.num_heads
        self.head_dim = attn0.head_dim
        self.hidden = attn0.embed_dim
        self.ln_eps = ops["layer0_ln1"].eps if self.layer_norm else 1e-5
        self.params = model.state.params  # live references, not copies

    # ---------------- pure block math ----------------------------------
    def _embed(self, params, tokens, positions):
        te = jnp.take(params["tok_embed"]["kernel"], tokens, axis=0)
        pe = jnp.take(params["pos_embed"]["kernel"], positions, axis=0)
        return (te + pe).astype(jnp.float32)

    def _attn_qkv(self, p, h):
        """h (..., E) -> q, k, v (..., H, D)."""
        q = jnp.einsum("...e,ehd->...hd", h, p["wq"].astype(h.dtype))
        k = jnp.einsum("...e,ehd->...hd", h, p["wk"].astype(h.dtype))
        v = jnp.einsum("...e,ehd->...hd", h, p["wv"].astype(h.dtype))
        return q, k, v

    def _attn_out(self, p, o, x):
        y = jnp.einsum("...hd,hde->...e", o, p["wo"].astype(o.dtype))
        if "bo" in p:
            y = y + p["bo"].astype(y.dtype)
        return x + y

    def _ffn(self, params, i, x):
        h = _ln(params[f"layer{i}_ln2"], x, self.ln_eps) \
            if self.layer_norm else x
        h = _dense(params[f"layer{i}_ff1"], h, activation="relu")
        h = _dense(params[f"layer{i}_ff2"], h)
        return x + h

    def _head(self, params, x):
        if self.layer_norm:
            x = _ln(params["final_ln"], x, self.ln_eps)
        return _dense(params["lm_head"], x)

    # ---------------- full-sequence forward (prefill + reference) ------
    def _forward_tokens(self, params, tokens, length, kv=None):
        """Causal forward over (1, S) padded tokens; returns the
        logits of position length-1 plus the (possibly updated)
        caches. `kv = (k_pages, v_pages, pt_row)` scatters each
        layer's K/V into the sequence's pages on the way through
        (prefill); kv=None is the pure no-cache forward (the naive
        reference) — ONE implementation so the parity oracle and the
        serving path can never drift apart."""
        ps = self.cache_cfg.page_size
        s = tokens.shape[1]
        positions = jnp.arange(s, dtype=jnp.int32)[None, :]
        x = self._embed(params, tokens, positions)        # (1, S, E)
        if kv is not None:
            k_pages, v_pages, pt_row = kv
            pages = jnp.take(pt_row, positions[0] // ps)  # (S,)
            offs = positions[0] % ps
        scale = 1.0 / np.sqrt(self.head_dim)
        causal = jnp.tril(jnp.ones((s, s), dtype=bool))
        for i in range(self.num_layers):
            p = params[f"layer{i}_attn"]
            h = _ln(params[f"layer{i}_ln1"], x, self.ln_eps) \
                if self.layer_norm else x
            q, k, v = self._attn_qkv(p, h)                # (1, S, H, D)
            if kv is not None:
                k_pages = k_pages.at[i, pages, offs].set(k[0])
                v_pages = v_pages.at[i, pages, offs].set(v[0])
            logits = jnp.einsum("bihd,bjhd->bhij", q, k,
                                preferred_element_type=jnp.float32) * scale
            logits = jnp.where(causal, logits, -jnp.inf)
            probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
            o = jnp.einsum("bhij,bjhd->bihd", probs, v)
            x = self._attn_out(p, o, x)
            x = self._ffn(params, i, x)
        logits = self._head(params, x)                    # (1, S, V)
        last = jnp.take(logits[0], length - 1, axis=0)    # (V,)
        return last, (None if kv is None else (k_pages, v_pages))

    # ---------------- prefill ------------------------------------------
    def _prefill_impl(self, params, k_pages, v_pages, tokens, length,
                      pt_row):
        """tokens (1, S) padded to a bucket; length scalar int32 (real
        prompt tokens); pt_row (pages_per_seq,) the sequence's page
        table. Returns (last-position logits (V,), k_pages, v_pages).

        Padded positions scatter their K/V through page-table entries
        normally: entries past the reserved range are 0 (the sink), and
        padded offsets inside a reserved page are overwritten by decode
        before the length mask ever exposes them."""
        last, (k_pages, v_pages) = self._forward_tokens(
            params, tokens, length, kv=(k_pages, v_pages, pt_row))
        return last, k_pages, v_pages

    # ---------------- decode -------------------------------------------
    def _decode_impl(self, params, k_pages, v_pages, tokens, positions,
                     write_pages, write_offs, page_tables, seq_lens):
        """One token for every slot lane. tokens/positions (B,) int32;
        write_pages/write_offs (B,) the physical slot for each lane's
        new K/V — HOST-computed so lanes that are not decoding this
        step (empty, or prefilled moments ago) aim at the sink page 0
        instead of clobbering their own position 0; page_tables
        (B, pages_per_seq); seq_lens (B,) INCLUDING the token being
        decoded (its K/V is written here, then attended — position i
        sees keys 0..i). Non-decoding lanes compute garbage the host
        never reads. Returns (next_tokens (B,), k_pages, v_pages)."""
        x = self._embed(params, tokens, positions)        # (B, E)
        pages, offs = write_pages, write_offs
        scale = 1.0 / np.sqrt(self.head_dim)
        for i in range(self.num_layers):
            p = params[f"layer{i}_attn"]
            h = _ln(params[f"layer{i}_ln1"], x, self.ln_eps) \
                if self.layer_norm else x
            q, k, v = self._attn_qkv(p, h)                # (B, H, D)
            k_pages = k_pages.at[i, pages, offs].set(k)
            v_pages = v_pages.at[i, pages, offs].set(v)
            o = paged_attention_decode(
                q, k_pages[i], v_pages[i], page_tables, seq_lens,
                scale=scale, use_pallas=self._use_pallas,
                interpret=self._interpret)
            x = self._attn_out(p, o, x)
            x = self._ffn(params, i, x)
        logits = self._head(params, x)                    # (B, V)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32), \
            k_pages, v_pages

    # ---------------- naive no-cache reference -------------------------
    def _forward_logits(self, params, tokens, length):
        """Full forward over (1, S) tokens, logits at position
        length-1 — the no-KV-cache greedy-decode reference (the shared
        _forward_tokens with the cache writes off)."""
        last, _ = self._forward_tokens(params, tokens, length, kv=None)
        return last

    # ---------------- bucketing / compile bookkeeping ------------------
    def bucket_for(self, prompt_len: int) -> int:
        for b in self.buckets:
            if prompt_len <= b:
                return b
        raise ValueError(
            f"prompt of {prompt_len} tokens exceeds the largest bucket "
            f"{self.buckets[-1]}")

    def compile_counts(self) -> Dict[str, int]:
        """Compiled-program count per serving function. After warmup()
        these must never grow — the zero-recompile serving contract.
        Uses jit's compilation-cache size when the (private) API
        exists, else the engine's own count of distinct argument-shape
        signatures (each distinct signature is one XLA program), so the
        contract check can never go vacuous on a jax without
        _cache_size."""
        def n(f, name):
            try:
                return int(f._cache_size())
            except AttributeError:  # jit cache API moved across versions
                return len(self._shapes_seen[name])
        return {"prefill": n(self._prefill_jit, "prefill"),
                "decode": n(self._decode_jit, "decode")}

    def warmup(self) -> Dict[str, int]:
        """Compile every prefill bucket and the decode step once, on
        throwaway inputs. Returns compile_counts() afterwards."""
        c = self.cache_cfg
        kp, vp = PagedKVCache(c).alloc_device_cache()
        pt_row = jnp.zeros((c.pages_per_seq,), jnp.int32)
        for b in self.buckets:
            toks = jnp.zeros((1, b), jnp.int32)
            _, kp, vp = self._call_counted(
                "prefill", self._prefill_jit, self.params, kp, vp, toks,
                jnp.int32(1), pt_row)
        toks = jnp.zeros((c.max_seqs,), jnp.int32)
        pos = jnp.zeros((c.max_seqs,), jnp.int32)
        pts = jnp.zeros((c.max_seqs, c.pages_per_seq), jnp.int32)
        sls = jnp.ones((c.max_seqs,), jnp.int32)
        self._call_counted("decode", self._decode_jit, self.params, kp,
                           vp, toks, pos, toks, pos, pts, sls)
        return self.compile_counts()

    # ---------------- the serving loop ---------------------------------
    def generate(self, prompts: Sequence[Sequence[int]],
                 max_new_tokens, eos_token: Optional[int] = None
                 ) -> List[List[int]]:
        """Greedy-decode a ragged batch under continuous batching.
        `max_new_tokens` is an int or a per-prompt sequence. Returns
        the generated tokens (prompt excluded) per prompt, in order.
        Per-request latency and per-token timings land in
        `self.last_stats` (render with utils/profiling.serve_report)."""
        c = self.cache_cfg
        cache = PagedKVCache(c)
        sched = ContinuousBatchingScheduler(
            cache, prefill_token_budget=int(
                getattr(self.config, "serve_prefill_budget", 512)))
        if isinstance(max_new_tokens, int):
            max_new_tokens = [max_new_tokens] * len(prompts)
        if len(max_new_tokens) != len(prompts):
            raise ValueError(
                f"max_new_tokens has {len(max_new_tokens)} entries for "
                f"{len(prompts)} prompts")
        reqs: List[Request] = []
        t0 = time.perf_counter()
        for prompt, mnt in zip(prompts, max_new_tokens):
            r = sched.submit(prompt, mnt, eos_token=eos_token)
            r.t_submit = time.perf_counter()
            reqs.append(r)
        k_pages, v_pages = cache.alloc_device_cache()
        decode_steps = 0
        decode_times: List[float] = []   # seconds per decode step
        decode_widths: List[int] = []    # active lanes per decode step
        prefill_times: List[Tuple[int, float]] = []  # (bucket, seconds)

        while sched.has_work():
            plan = sched.schedule()
            for req in plan.prefills:
                b = self.bucket_for(len(req.prompt))
                toks = np.zeros((1, b), np.int32)
                toks[0, :len(req.prompt)] = req.prompt
                tp = time.perf_counter()
                last, k_pages, v_pages = self._call_counted(
                    "prefill", self._prefill_jit,
                    self.params, k_pages, v_pages, jnp.asarray(toks),
                    jnp.int32(len(req.prompt)),
                    jnp.asarray(cache.page_tables[req.slot]))
                tok = int(jnp.argmax(last))
                prefill_times.append((b, time.perf_counter() - tp))
                req.out_tokens.append(tok)
                req.t_first_token = time.perf_counter()
                if req.is_done():
                    req.t_finish = req.t_first_token
                    sched.finish(req)
            if plan.decodes:
                tokens = np.zeros((c.max_seqs,), np.int32)
                positions = np.zeros((c.max_seqs,), np.int32)
                write_pages = np.zeros((c.max_seqs,), np.int32)  # sink
                write_offs = np.zeros((c.max_seqs,), np.int32)
                for req in plan.decodes:
                    # the new token's K/V slot: append BEFORE the step
                    # so seq_lens includes it (self-attention sees it)
                    pos = cache.append_token(req.slot)
                    positions[req.slot] = pos
                    tokens[req.slot] = req.out_tokens[-1]
                    write_pages[req.slot] = cache.page_tables[
                        req.slot, pos // c.page_size]
                    write_offs[req.slot] = pos % c.page_size
                seq_lens = np.maximum(cache.seq_lens, 1)  # empty lanes:
                # >= 1 valid (sink) key so the masked softmax stays NaN-free
                tp = time.perf_counter()
                nxt, k_pages, v_pages = self._call_counted(
                    "decode", self._decode_jit,
                    self.params, k_pages, v_pages, jnp.asarray(tokens),
                    jnp.asarray(positions), jnp.asarray(write_pages),
                    jnp.asarray(write_offs), jnp.asarray(cache.page_tables),
                    jnp.asarray(seq_lens))
                nxt = np.asarray(nxt)    # ONE device->host fetch per step
                now = time.perf_counter()
                decode_times.append(now - tp)
                decode_widths.append(len(plan.decodes))
                decode_steps += 1
                for req in plan.decodes:
                    req.out_tokens.append(int(nxt[req.slot]))
                    if req.is_done():
                        req.t_finish = time.perf_counter()
                        sched.finish(req)
        cache.check_invariants()
        assert cache.free_pages == c.usable_pages, "pages leaked"
        total_new = sum(len(r.out_tokens) for r in reqs)
        wall = time.perf_counter() - t0
        self.last_stats = {
            "requests": [
                {"rid": r.rid, "prompt_tokens": len(r.prompt),
                 "new_tokens": len(r.out_tokens),
                 "ttft_s": r.t_first_token - r.t_submit,
                 "latency_s": r.t_finish - r.t_submit}
                for r in reqs],
            "wall_s": wall,
            "total_new_tokens": total_new,
            "tokens_per_sec": total_new / wall if wall > 0 else 0.0,
            "decode_steps": decode_steps,
            "decode_step_times_s": decode_times,
            "decode_widths": decode_widths,
            "prefill_times_s": prefill_times,
            "compile_counts": self.compile_counts(),
        }
        return [list(r.out_tokens) for r in reqs]

    def generate_reference(self, prompts: Sequence[Sequence[int]],
                           max_new_tokens,
                           eos_token: Optional[int] = None
                           ) -> List[List[int]]:
        """Naive no-cache greedy decode: re-forward the WHOLE sequence
        for every new token, one request at a time. O(n^2) per token —
        the correctness oracle generate() is tested against."""
        if isinstance(max_new_tokens, int):
            max_new_tokens = [max_new_tokens] * len(prompts)
        if len(max_new_tokens) != len(prompts):
            raise ValueError(
                f"max_new_tokens has {len(max_new_tokens)} entries for "
                f"{len(prompts)} prompts")
        out: List[List[int]] = []
        for prompt, mnt in zip(prompts, max_new_tokens):
            if mnt < 1:  # mirror scheduler.submit's contract
                raise ValueError(f"max_new_tokens must be >= 1, got {mnt}")
            toks = list(prompt)
            new: List[int] = []
            while len(new) < mnt:
                b = self.bucket_for(len(toks))
                arr = np.zeros((1, b), np.int32)
                arr[0, :len(toks)] = toks
                logits = self._forward_jit(self.params, jnp.asarray(arr),
                                           jnp.int32(len(toks)))
                tok = int(jnp.argmax(logits))
                new.append(tok)
                toks.append(tok)
                if eos_token is not None and tok == eos_token:
                    break
            out.append(new)
        return out
