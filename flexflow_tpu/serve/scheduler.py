"""Continuous-batching scheduler with chunked prefill and preemption.

Policy (the "continuous batching" of Orca / vLLM plus Sarathi-style
chunked prefill, re-cut for TPU static shapes — see docs/serving.md):

  * Everything is a CHUNK. Each step, every running request gets a
    chunk of positions [num_computed, end) to compute: a decoding
    request's chunk is its single next token, a prefilling request's
    chunk is up to `prefill_token_budget` prompt tokens. Decode chunks
    never wait on prefill chunks — they ride in the same fixed-shape
    engine step — so a long prompt never stalls running decodes, and
    a prompt longer than the budget simply prefills across several
    steps (no per-bucket programs, no oversized-prompt special case).
  * FCFS admission under a WATERMARK, not a worst-case reservation:
    a request is admitted when a slot is free, the prefill budget has
    room, and the pool can supply its first chunk's pages while
    keeping `admit_watermark` of the pool reclaimable. Pages for the
    rest of the sequence are allocated on demand as it grows.
  * PREFIX CACHING at admission: the prompt's full token blocks are
    chain-hashed and matched against resident pages (including pages
    other chunks in this very step will compute — intra-step sharing
    is sound because the engine scatters all chunk K/V before any lane
    attends). Matched tokens are marked computed without running.
  * PREEMPTION instead of reservation: if a step cannot supply a page
    for a chunk, the youngest running request (highest rid — the one
    FCFS would have admitted last) is evicted back to the FRONT of the
    waiting queue and its pages released. Its completed pages stay in
    the prefix cache, so on re-admission it matches most of its own
    history and recomputes only the tail — preemption costs one page
    walk, not a full re-prefill.
  * Head-of-line blocking is deliberate: when the oldest waiting
    request doesn't fit, admission stops rather than scanning past it,
    so no request can be starved by a stream of smaller latecomers.
    A forced-progress escape admits the head with a shrunken chunk when
    nothing at all is running (the watermark must not deadlock an
    empty engine).

The scheduler is pure host-side bookkeeping over the PagedKVCache; the
engine owns all device work. Splitting it this way keeps the policy
testable as plain Python (tests/test_serve*.py property asserts) and
keeps the jitted steps free of data-dependent shapes.
"""

from __future__ import annotations

import dataclasses
import enum
from collections import deque
from typing import Deque, Dict, List, Optional, Sequence

from ..utils.faults import FaultInjector
from .adapters import AdapterPool, tenant_prefix_salt
from .kv_cache import PagedKVCache, prefix_page_keys
from .speculative import DraftControl, Drafter, PromptLookupDrafter


class RequestState(enum.Enum):
    WAITING = "waiting"
    RUNNING = "running"   # holds a decode slot (prefilling or decoding)
    FINISHED = "finished"


class RequestOutcome:
    """How a request left the system (Request.outcome). PENDING while
    in flight; exactly one terminal value afterwards."""

    PENDING = "pending"
    COMPLETED = "completed"
    CANCELLED = "cancelled"
    DEADLINE_EXPIRED = "deadline_expired"
    REJECTED = "rejected"
    FAILED = "failed"          # a mid-generate engine exception


@dataclasses.dataclass(frozen=True)
class RejectedRequest:
    """Structured record of a rung-4 rejection (stats['rejected_requests']):
    the request was refused service instead of deadlocking the step or
    raising out of the whole batch."""

    rid: int
    reason: str


@dataclasses.dataclass(frozen=True)
class SampleParams:
    """Per-request sampling. temperature <= 0 means greedy; top_k
    restricts sampling to the k highest logits (None = the engine's
    static top-k cap). The (seed, rid, token-index) triple seeds every
    draw, so a fixed seed reproduces a stream exactly — including
    across a preemption, which replays no RNG state."""

    temperature: float = 0.0
    top_k: Optional[int] = None
    seed: int = 0


@dataclasses.dataclass
class Request:
    """One generation request. `prompt` is token ids; generation stops
    after `max_new_tokens` or on `eos_token` (if given)."""

    rid: int
    prompt: List[int]
    max_new_tokens: int
    eos_token: Optional[int] = None
    sample: Optional[SampleParams] = None
    # sampling stream identity (docs/serving.md "Sampled streams"):
    # seeded draws key on (seed, stream_id, stream_offset + token
    # index) instead of the LOCAL scheduler's rid/token index, so a
    # stream survives crossing schedulers — the disaggregated
    # prefill->decode handoff resumes a stream at offset 1 on the
    # decode engine, and a routed replica reproduces the exact stream
    # a single-replica engine would emit. None = the rid (the
    # pre-stream behavior, bit-identical).
    stream_id: Optional[int] = None
    stream_offset: int = 0
    # multi-tenant adapter serving (serve/adapters.py): the tenant
    # whose LoRA adapter this request decodes under (0 = the base
    # model, no adapter). adapter_slot is the pool slot the request
    # holds from admission to finish/abort/preempt (None while
    # waiting or for tenant 0) — the lane's slab gather index.
    tenant_id: int = 0
    adapter_slot: Optional[int] = None
    # trace-context propagation (docs/observability.md): the
    # process-unique trace id every telemetry span of this request
    # carries. Minted at the FIRST tier that sees the request (router
    # submit / DisaggCluster generate / scheduler submit), and carried
    # across engines — a disagg decode-role request REUSES the id its
    # prefill-role twin was minted, so one causally-linked timeline
    # covers the whole life. Never None after submit().
    trace_id: int = 0

    state: RequestState = RequestState.WAITING
    slot: int = -1
    out_tokens: List[int] = dataclasses.field(default_factory=list)
    # tokens whose K/V is resident (prefix-cache hits + computed chunks)
    num_computed: int = 0
    preemptions: int = 0
    # robustness: absolute (perf_counter) deadline, 0 = none; terminal
    # outcome; consecutive stalled admission attempts at rung >= 3
    t_deadline: float = 0.0
    outcome: str = RequestOutcome.PENDING
    stalled: int = 0
    # adaptive draft-length state (speculative decoding); None when the
    # request is ineligible (non-deterministic sampling) or spec is off
    spec: Optional[DraftControl] = None
    _page_keys: List[bytes] = dataclasses.field(default_factory=list,
                                                repr=False)
    # serving metrics (utils/profiling.serve_report, telemetry queue-
    # wait spans): wall-clock stamps. t_admit is stamped by the engine
    # at the first step that plans the request (0.0 until then).
    t_submit: float = 0.0
    t_admit: float = 0.0
    t_first_token: float = 0.0
    t_finish: float = 0.0
    # host-tier spill-vs-recompute decision recorded at admission
    # (ServeEngine._host_reload; explain_request surfaces it) — None
    # until the armed tier matches this request's prefix
    host_reload: Optional[dict] = dataclasses.field(default=None,
                                                    repr=False)
    # preemption stamp for the telemetry requeue_wait span (set at
    # eviction, cleared at re-admission; telemetry-only bookkeeping)
    _t_requeue: Optional[float] = dataclasses.field(default=None,
                                                    repr=False)

    @property
    def total_tokens(self) -> int:
        return len(self.prompt) + self.max_new_tokens

    @property
    def context(self) -> List[int]:
        """Every token whose K/V the engine may need: the prompt plus
        all generated tokens. A freshly-preempted request resumes by
        re-prefilling THIS (its generated work is not redone, only its
        K/V), which is why it lives here and not on the engine."""
        return self.prompt + self.out_tokens

    def is_done(self) -> bool:
        if len(self.out_tokens) >= self.max_new_tokens:
            return True
        return (self.eos_token is not None and self.out_tokens
                and self.out_tokens[-1] == self.eos_token)


@dataclasses.dataclass
class ChunkPlan:
    """One request's work in one engine step: compute K/V (and logits)
    for context positions [start, end). When `end` reaches the full
    context length the chunk's last lane EMITS the next token — that is
    both the final prefill chunk of a prompt and every decode step
    (a decode is just a 1-token chunk that reaches the end)."""

    req: Request
    start: int
    end: int
    is_decode: bool   # an actively-generating request's 1-token chunk
    # speculative continuation: drafted tokens for positions
    # [end, end + len(draft_tokens)) packed as extra lanes AFTER the
    # context lanes. Their K/V scatters like any lane's, but nothing is
    # resident until verification accepts a prefix (complete_spec_chunk)
    # and the remainder rolls back. Only decode chunks draft.
    draft_tokens: List[int] = dataclasses.field(default_factory=list)

    @property
    def emits(self) -> bool:
        return self.end == len(self.req.context)


@dataclasses.dataclass
class StepPlan:
    """What one engine iteration executes."""

    chunks: List[ChunkPlan]
    admitted: List[Request]
    preempted: List[Request]

    @property
    def prefills(self) -> List[Request]:
        return [c.req for c in self.chunks if not c.is_decode]

    @property
    def decodes(self) -> List[Request]:
        return [c.req for c in self.chunks if c.is_decode]

    @property
    def num_prefill_lanes(self) -> int:
        return sum(c.end - c.start for c in self.chunks if not c.is_decode)

    @property
    def num_decode_lanes(self) -> int:
        return sum(1 for c in self.chunks if c.is_decode)


def watermark_pages(admit_watermark: float, usable_pages: int) -> int:
    """The admission watermark as a page count: the floor of
    reclaimable pages admission must leave standing. ONE formula,
    shared by every consumer of the backpressure signal — the
    scheduler's waiting-queue admissions, the disagg handoff's
    shipment gate, and the cross-process shipment receiver — so
    "above the watermark" means the same thing in-process and across
    the wire."""
    return int(float(admit_watermark) * int(usable_pages))


class ContinuousBatchingScheduler:
    # graceful-degradation ladder: page-pool utilization (1 - the
    # reclaimable fraction) at which each rung arms. Rung 1 sheds
    # speculation (drafts are optimism, not owed work), rung 2 stops
    # prefix-matching new admissions and sheds the parked LRU (an
    # attach would pin reclaimable pages), rung 3 tightens the
    # admission watermark 4x, rung 4 rejects what cannot be served
    # (structured RejectedRequest instead of a deadlock or a raise).
    #
    # Every threshold here — like the admission watermark and all of
    # ensure_capacity/pages_to_extend — is a fraction of PAGE COUNTS
    # over cfg.usable_pages, never device bytes: the page count is
    # derived upstream from the configured kv_dtype's itemsize AND the
    # serve mesh's tensor degree (KVCacheConfig.page_device_bytes /
    # kv_pool_mb per-DEVICE sizing), so a quantized pool's extra pages
    # raise the rung/watermark ceilings automatically and nothing
    # below may assume 4-byte elements. Under head-sharded serving
    # every device holds ALL pages at H/t heads each, so the count —
    # and with it every watermark/ladder fraction — is per-device-
    # identical: rungs fire at the same relative per-device pressure
    # at any tensor degree (docs/serving.md "Sharded serving").
    LADDER = (0.85, 0.92, 0.97)
    RUNG3_WATERMARK_FRAC = 0.08

    def __init__(self, cache: PagedKVCache,
                 prefill_token_budget: int = 512,
                 chunked_prefill: bool = True,
                 admit_watermark: float = 0.02,
                 spec_tokens: int = 0,
                 drafter: Optional[Drafter] = None,
                 faults: Optional[FaultInjector] = None,
                 degrade_ladder: bool = True,
                 reject_stalls: int = 0,
                 adapter_pool: Optional[AdapterPool] = None,
                 host_reload=None):
        self.cache = cache
        # hierarchical host tier (serve/host_tier.py): the engine's
        # priced reload hook `host_reload(req, keys, cached_pages,
        # max_pages) -> pages made resident`. None = no tier; the
        # scheduler only decides WHEN to ask (rung < 2, HBM match
        # exhausted, room below the watermark) — the engine prices
        # DMA-vs-recompute and moves the bytes.
        self.host_reload = host_reload
        # multi-tenant LoRA pool (serve/adapters.py): admission
        # acquires the tenant's slot (possibly queueing a device load)
        # and finish/abort/preempt release it — the same lifecycle as
        # KV pages. None = single-tenant serving (tenant 0 only).
        self.adapters = adapter_pool
        self.faults = faults if faults is not None else FaultInjector()
        self.degrade_ladder = bool(degrade_ladder)
        self.reject_stalls = int(reject_stalls)
        self.rung = 0
        self.prefill_token_budget = int(prefill_token_budget)
        self.chunked_prefill = bool(chunked_prefill)
        # prefix sharing needs chunked prefill: the legacy per-bucket
        # program recomputes and RE-SCATTERS every prompt position, which
        # would clobber shared pages other sequences are reading
        self.prefix_cache = cache.prefix_enabled and self.chunked_prefill
        # speculative decoding also needs the mixed program: the legacy
        # decode step has exactly one lane per slot, nowhere to verify
        self.spec_tokens = int(spec_tokens) if self.chunked_prefill else 0
        self.drafter = drafter if drafter is not None \
            else (PromptLookupDrafter() if self.spec_tokens > 0 else None)
        self.watermark_pages = watermark_pages(
            admit_watermark, cache.cfg.usable_pages)
        self.waiting: Deque[Request] = deque()
        self.running: Dict[int, Request] = {}  # slot -> request
        self._next_rid = 0
        self.stats = {"prefix_hit_tokens": 0, "prompt_tokens": 0,
                      "prefill_lane_tokens": 0, "decode_lane_tokens": 0,
                      "preemptions": 0, "spec_drafted_tokens": 0,
                      "spec_accepted_tokens": 0,
                      # robustness counters (serve_report)
                      "cancelled": 0, "deadline_expired": 0,
                      "rejected": 0, "failed": 0, "spec_shed_steps": 0,
                      # adapter-pool admission stalls (head-of-line
                      # blocks because every usable slot was mapped)
                      "adapter_blocked_steps": 0,
                      "degradation_rung_max": 0,
                      "rung_steps": [0, 0, 0, 0, 0]}
        self.rejected_requests: List[RejectedRequest] = []

    # ---------------- submission --------------------------------------
    def submit(self, prompt: Sequence[int], max_new_tokens: int,
               eos_token: Optional[int] = None,
               sample: Optional[SampleParams] = None,
               stream_id: Optional[int] = None,
               stream_offset: int = 0,
               trace_id: Optional[int] = None,
               tenant_id: int = 0) -> Request:
        if len(prompt) < 1:
            raise ValueError("empty prompt")
        tenant_id = int(tenant_id)
        if tenant_id < 0:
            raise ValueError(f"tenant_id must be >= 0, got {tenant_id}")
        if tenant_id != 0:
            # fail fast at submit, not at admission: an unarmed engine
            # or an unregistered tenant can never be served, and
            # admission-time failure would poison the queue head
            if self.adapters is None:
                raise ValueError(
                    f"tenant {tenant_id} needs an adapter pool "
                    f"(--adapter-rank > 0), but this engine serves "
                    f"the base model only")
            if tenant_id not in self.adapters.registered():
                raise ValueError(
                    f"tenant {tenant_id} has no registered adapter "
                    f"(engine.register_adapter first)")
        if int(max_new_tokens) < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1 (got {max_new_tokens}): "
                f"the final prefill chunk always emits the first token")
        total = len(prompt) + int(max_new_tokens)
        if total > self.cache.cfg.max_seq_len:
            raise ValueError(
                f"request needs {total} tokens > max_seq_len "
                f"{self.cache.cfg.max_seq_len}")
        if stream_id is not None and int(stream_id) < 0:
            raise ValueError(
                f"stream_id must be >= 0 (seed-sequence entries are "
                f"unsigned), got {stream_id}")
        from ..utils.telemetry import next_trace_id
        req = Request(rid=self._next_rid, prompt=list(prompt),
                      max_new_tokens=int(max_new_tokens),
                      eos_token=eos_token, sample=sample,
                      stream_id=(None if stream_id is None
                                 else int(stream_id)),
                      stream_offset=int(stream_offset),
                      # an upstream tier (router / disagg cluster)
                      # passes the id it minted; a plain engine mints
                      # here — either way every span carries ONE id
                      trace_id=(next_trace_id() if trace_id is None
                                else int(trace_id)),
                      tenant_id=tenant_id)
        # speculation needs a deterministic per-lane pick to verify
        # against: greedy, or top_k=1 sampling (the already-drawn sample
        # is always the top-1 logit). Other sampling decodes with k=0.
        if self.spec_tokens > 0 and (sample is None or sample.top_k == 1):
            req.spec = DraftControl(self.spec_tokens)
        self._next_rid += 1
        self.waiting.append(req)
        self.stats["prompt_tokens"] += len(prompt)
        return req

    def has_work(self) -> bool:
        return bool(self.waiting or self.running)

    # ---------------- prefix keys -------------------------------------
    def _keys_for(self, req: Request, npages: int) -> List[bytes]:
        """The request's chain keys for its first `npages` full pages,
        extended INCREMENTALLY from the last cached key (hashing is
        O(pages) per sequence, not O(pages^2) across chunk steps) and
        kept across preemptions (the context tokens a key commits to
        never change). The chain is SEEDED with the tenant's prefix
        salt: an adapted lane's K/V is a function of its adapter, so
        equal tokens under different tenants must hash to disjoint
        keys — tenant 0 keeps the unsalted chain (adapters.
        tenant_prefix_salt)."""
        keys = req._page_keys
        if len(keys) < npages:
            keys.extend(prefix_page_keys(
                req.context, self.cache.cfg.page_size, npages,
                start=len(keys),
                prev=(keys[-1] if keys
                      else tenant_prefix_salt(req.tenant_id))))
        return keys[:npages]

    # ---------------- the policy --------------------------------------
    def schedule(self) -> StepPlan:
        """Plan one step. Continues running requests first (decodes are
        guaranteed lanes; prefill continuations share the budget FCFS),
        preempting youngest-first on page pressure, then admits from
        the waiting queue under the budget + watermark."""
        ps = self.cache.cfg.page_size
        cache = self.cache
        usable = cache.cfg.usable_pages
        # injected page-pool pressure (chaos tests): hide a fraction of
        # the reclaimable pool from PLANNING. Allocation still draws
        # from the real pool, so invariants cannot break — the step
        # just shrinks/preempts/degrades exactly as real exhaustion
        # would force it to.
        squeeze = self.faults.level("serve.page_pressure")
        hidden = min(usable, int(squeeze * usable))

        def eff_free() -> int:
            return max(0, cache.free_pages - hidden)

        # degradation rung for THIS step, from planning-visible pressure
        util = 1.0 - eff_free() / usable
        self.rung = (sum(util >= t for t in self.LADDER)
                     if self.degrade_ladder else 0)
        if self.rung >= 2:
            cache.shrink_lru(usable // 4)
        wm = self.watermark_pages
        if self.rung >= 3:
            wm = max(wm, int(self.RUNG3_WATERMARK_FRAC * usable) + 1)
        rejected_before = len(self.rejected_requests)
        chunks: List[ChunkPlan] = []
        admitted: List[Request] = []
        preempted: List[Request] = []
        budget = self.prefill_token_budget
        # chain key -> physical page for FULL pages some chunk planned
        # THIS step will compute: later admissions in the same step may
        # share them (the engine scatters all chunk K/V before any lane
        # attends, so intra-step sharing observes computed values)
        pending: Dict[bytes, int] = {}

        def note_pending(req: Request, start: int, end: int) -> None:
            if not self.prefix_cache:
                return
            keys = self._keys_for(req, end // ps)
            for idx in range(start // ps, end // ps):
                pending.setdefault(keys[idx],
                                   int(cache.page_tables[req.slot, idx]))

        # ---- 1. running requests, FCFS (oldest first) ----
        order = sorted(self.running.values(), key=lambda r: r.rid)
        shed_this_step = False   # spec_shed_steps is per-STEP
        i = 0
        while i < len(order):
            req = order[i]
            ctx_len = len(req.context)
            remaining = ctx_len - req.num_computed
            assert remaining >= 1, f"request {req.rid} over-computed"
            is_decode = remaining == 1 and bool(req.out_tokens)
            want = 1 if is_decode else min(budget, remaining)
            if want == 0:           # prefill budget spent this step
                i += 1
                continue
            end = req.num_computed + want
            # shrink to the pages actually available before preempting
            fit = cache.mapped_tokens(req.slot) + eff_free() * ps
            end = min(end, fit)
            if end <= req.num_computed:
                # not even one token's page: evict the youngest running
                victim = order.pop()   # always at an index >= i
                self._preempt(victim)
                preempted.append(victim)
                continue               # retry req (unless req WAS victim)
            cache.ensure_capacity(req.slot, end)
            draft: List[int] = []
            if is_decode and req.spec is not None and self.rung >= 1:
                # ladder rung 1: shed speculation — a draft is
                # optimism, and under page pressure its mapped-ahead
                # pages are exactly what admissions are starved of.
                # Counted once per step, and only when the non-degraded
                # path would actually have drafted (budget left).
                if budget > 0 and not shed_this_step:
                    self.stats["spec_shed_steps"] += 1
                    shed_this_step = True
            elif is_decode and req.spec is not None and budget > 0:
                # drafts ride in PREFILL-budget lanes (the decode lane
                # itself is from the guaranteed max_seqs reserve, so
                # decode never starves) and draw pages like any growth —
                # but they only SHRINK under pressure, never preempt: a
                # draft is an optimization, not owed work. Capped so the
                # step cannot emit past max_new_tokens (each accepted
                # draft plus the bonus token is one emission).
                k = min(req.spec.next_k(), budget,
                        req.max_new_tokens - len(req.out_tokens) - 1,
                        cache.mapped_tokens(req.slot)
                        + eff_free() * ps - end)
                if k > 0:
                    # clamp: the budget/page/length math above assumed
                    # at most k, and a plugged-in drafter's contract is
                    # "UP TO k" — never trust it with the allocator
                    draft = list(self.drafter.draft(req.context, k))[:k]
                if draft:
                    cache.ensure_capacity(req.slot, end + len(draft))
                    budget -= len(draft)
            chunks.append(ChunkPlan(req, req.num_computed, end, is_decode,
                                    draft_tokens=draft))
            note_pending(req, req.num_computed, end)
            if not is_decode:
                budget -= end - req.num_computed
            i += 1

        # ---- 2. admissions, FCFS with head-of-line blocking ----
        while self.waiting and cache.free_slots > 0:
            req = self.waiting[0]
            # forced-progress escape: with nothing running and nothing
            # planned, the watermark/page checks must not deadlock —
            # admit the head with however small a chunk fits
            forced = not chunks and not self.running
            if budget <= 0:
                break
            ctx = req.context
            ctx_len = len(ctx)
            cached_pages: List[int] = []
            # ladder rung 2: no prefix matching for new admissions — an
            # attach pins reclaimable parked pages at refcount > 0
            # right when the pool needs them back
            if self.prefix_cache and self.rung < 2:
                # never match the final token's page: at least one lane
                # must run to produce the next-token logits, and a
                # partial tail page is never shared anyway
                keys = self._keys_for(req, (ctx_len - 1) // ps)
                cached_pages = cache.match_prefix(keys)
                # host-tier fall-through: when the HBM run ends short
                # of the chain, ask the engine to extend it from the
                # host store — capped so the import cannot eat the
                # watermark or the matched run's own reclaimability.
                # Reloaded pages park hashed/refcount-0, so free_pages
                # (and the admission math below) is unchanged.
                if self.host_reload is not None \
                        and len(cached_pages) < len(keys):
                    lru0 = sum(1 for p in cached_pages
                               if cache.ref(p) == 0)
                    room = eff_free() - lru0 - wm
                    if room > 0 and self.host_reload(
                            req, keys, cached_pages, room) > 0:
                        cached_pages = cache.match_prefix(keys)
                k = len(cached_pages)
                while k < len(keys) and keys[k] in pending:
                    cached_pages.append(pending[keys[k]])
                    k += 1
            cached_len = len(cached_pages) * ps
            end = min(ctx_len, cached_len + budget)
            if not self.chunked_prefill:
                # legacy whole-prompt prefill: one bucket program per
                # request; the first admission of a step ignores the
                # budget so an over-budget prompt still gets served
                if end < ctx_len and any(not c.is_decode for c in chunks):
                    break
                end = ctx_len
            # matched pages sitting at refcount 0 come OUT of the
            # reclaimable count the moment we attach them
            lru_cached = sum(1 for p in cached_pages if cache.ref(p) == 0)
            need = cache.pages_for(end) - len(cached_pages)
            if forced:
                avail = (eff_free() - lru_cached) * ps
                if self.chunked_prefill:
                    end = min(end, cached_len + avail)
                if end <= cached_len or cached_len + avail < end:
                    # ladder rung 4: nothing is running, nothing else is
                    # planned, and the head STILL cannot get one chunk's
                    # pages — serving it is impossible at current
                    # pressure. Reject it (structured outcome) instead
                    # of raising out of the whole batch, and let the
                    # next waiting request try. With the ladder
                    # disabled, the pre-ladder contract (raise) holds.
                    if not self.degrade_ladder:
                        raise RuntimeError(
                            "page pool too small for the oldest waiting "
                            "request's first chunk")
                    self._reject(req, "first chunk cannot fit the "
                                 "reclaimable page pool")
                    continue
            elif need + lru_cached + wm > eff_free():
                # head-of-line: nothing admits past the head. Under the
                # opt-in online-serving policy, a head that stalls
                # `reject_stalls` CONSECUTIVE steps at rung >= 3 is
                # rejected (rung 4) so the queue behind it is not
                # starved by a request the pool cannot serve soon.
                # Ordinary low-pressure blocking (waiting out a full
                # running set) must not pre-charge the counter, so
                # stalls only count — and only survive — at rung >= 3.
                if self.rung >= 3:
                    req.stalled += 1
                    if self.reject_stalls \
                            and req.stalled >= self.reject_stalls:
                        self._reject(
                            req, f"stalled {req.stalled} admission "
                            f"attempts at rung {self.rung}")
                        continue
                else:
                    req.stalled = 0
                break
            # adapter admission gate (serve/adapters.py): attach the
            # tenant's pool slot — possibly queueing a device load the
            # session drains before dispatch — BEFORE the request
            # leaves the queue. None means every usable slot is mapped
            # by OTHER running tenants: head-of-line block, exactly
            # like KV page exhaustion (a release at finish/abort/
            # preempt unblocks a later schedule()). The stall is
            # planning-visible, never a recompile. Cannot deadlock:
            # with nothing running no slot holds refs, so the forced-
            # progress head always acquires.
            if self.adapters is not None and req.tenant_id != 0 \
                    and req.adapter_slot is None:
                aslot = self.adapters.acquire(req.tenant_id)
                if aslot is None:
                    self.stats["adapter_blocked_steps"] += 1
                    break
                req.adapter_slot = aslot
            req.stalled = 0
            self.waiting.popleft()
            slot = cache.alloc_slot()
            req.slot = slot
            req.state = RequestState.RUNNING
            if cached_pages:
                cache.attach_prefix(slot, cached_pages, cached_len)
                self.stats["prefix_hit_tokens"] += cached_len
            req.num_computed = cached_len
            cache.ensure_capacity(slot, end)
            self.running[slot] = req
            chunks.append(ChunkPlan(req, cached_len, end, False))
            note_pending(req, cached_len, end)
            admitted.append(req)
            budget -= end - cached_len

        plan = StepPlan(chunks=chunks, admitted=admitted,
                        preempted=preempted)
        self.stats["prefill_lane_tokens"] += plan.num_prefill_lanes
        self.stats["decode_lane_tokens"] += plan.num_decode_lanes
        # rung_steps is a per-STEP histogram (sums to schedule() calls):
        # a step that rejected anything counts as rung 4, regardless of
        # how many requests it refused
        step_rung = 4 if len(self.rejected_requests) > rejected_before \
            else self.rung
        self.stats["rung_steps"][step_rung] += 1
        self.stats["degradation_rung_max"] = max(
            self.stats["degradation_rung_max"], step_rung)
        return plan

    def _reject(self, req: Request, reason: str) -> None:
        """Rung-4 action: refuse service to the WAITING-queue head with
        a structured outcome instead of deadlocking the step or
        raising out of the whole batch."""
        assert self.waiting and self.waiting[0] is req
        self.waiting.popleft()
        self._release_adapter(req)
        req.state = RequestState.FINISHED
        req.outcome = RequestOutcome.REJECTED
        self.stats["rejected"] += 1
        self.rejected_requests.append(RejectedRequest(req.rid, reason))

    def abort(self, req: Request, outcome: str) -> bool:
        """Abort a request at a chunk boundary (host-side cancel, an
        expired deadline, or a mid-batch engine failure): a RUNNING
        request's slot and pages release through the same refcount
        machinery as finish() — committed prefix pages stay matchable,
        everything else returns to the pool — and a WAITING request
        simply leaves the queue. Returns False when the request is
        already finished (abort lost the race with completion)."""
        if req.state == RequestState.RUNNING:
            del self.running[req.slot]
            self.cache.free_slot(req.slot)
            req.slot = -1
        elif req.state == RequestState.WAITING:
            try:
                self.waiting.remove(req)
            except ValueError:
                return False
        else:
            return False
        self._release_adapter(req)
        req.state = RequestState.FINISHED
        req.outcome = outcome
        if outcome in self.stats:
            self.stats[outcome] += 1
        return True

    def _release_adapter(self, req: Request) -> None:
        """Drop the request's adapter-pool reference (no-op for the
        base tenant / a never-admitted request). The slot parks in the
        pool's LRU at refcount 0 — still loaded, so re-admission of
        the same tenant (including a preempted request's own return)
        re-attaches without a device load."""
        if req.adapter_slot is not None and self.adapters is not None:
            self.adapters.release(req.tenant_id)
        req.adapter_slot = None

    def _preempt(self, victim: Request) -> None:
        """Evict a running request back to the FRONT of the waiting
        queue (it is the youngest running, so rid order — FCFS priority
        — is preserved). Its pages are released; the content-hashed
        ones stay matchable, so re-admission restores most of its
        history from the prefix cache instead of recomputing it."""
        del self.running[victim.slot]
        self.cache.free_slot(victim.slot)
        self._release_adapter(victim)
        victim.slot = -1
        victim.state = RequestState.WAITING
        victim.num_computed = 0
        victim.preemptions += 1
        self.stats["preemptions"] += 1
        self.waiting.appendleft(victim)

    def complete_chunk(self, chunk: ChunkPlan) -> None:
        """Bookkeeping after the engine computed a chunk: the tokens
        are now resident, and every page the chunk COMPLETED is
        registered in the prefix cache (full pages only — the tail is
        still being written). The engine emits the chunk's token (if
        `chunk.emits`) after this call."""
        assert not chunk.draft_tokens, (
            "speculative chunks complete via complete_spec_chunk "
            "(their residency depends on verification)")
        req = chunk.req
        self.cache.advance(req.slot, chunk.end)
        req.num_computed = chunk.end
        if self.prefix_cache:
            ps = self.cache.cfg.page_size
            keys = self._keys_for(req, chunk.end // ps)
            for idx in range(chunk.start // ps, chunk.end // ps):
                self.cache.commit_page(req.slot, idx, keys[idx])

    def complete_spec_chunk(self, chunk: ChunkPlan, accepted: int) -> None:
        """Bookkeeping after the engine VERIFIED a speculative decode
        chunk: the chunk's context token plus the `accepted`-token
        prefix of its drafts are resident (their K/V was computed with
        exactly the tokens the model emitted, so it is bit-identical to
        what sequential decode would have written); everything past
        them — rejected drafts and the pages mapped ahead for them —
        rolls back. Must be called AFTER the engine appended the
        emitted tokens to the request (prefix keys hash the context,
        which now covers every verified position); only fully-verified
        pages are committed, so a rolled-back page can never enter the
        registry."""
        assert chunk.is_decode, "only decode chunks speculate"
        assert 0 <= accepted <= len(chunk.draft_tokens)
        req = chunk.req
        verified = chunk.end + accepted
        self.cache.advance(req.slot, verified)
        self.cache.rollback(req.slot, verified)
        req.num_computed = verified
        self.stats["spec_drafted_tokens"] += len(chunk.draft_tokens)
        self.stats["spec_accepted_tokens"] += accepted
        if req.spec is not None:
            req.spec.record(len(chunk.draft_tokens), accepted)
        if self.prefix_cache:
            ps = self.cache.cfg.page_size
            keys = self._keys_for(req, verified // ps)
            for idx in range(chunk.start // ps, verified // ps):
                self.cache.commit_page(req.slot, idx, keys[idx])

    def debug_state(self, max_requests: int = 32) -> dict:
        """Bounded JSON-ready snapshot of the scheduler for the
        failure flight recorder (docs/observability.md "Failure flight
        recorder"): the waiting queue and running set (capped at
        `max_requests` entries each — a post-mortem bundle must stay
        bounded no matter how deep the queue was), the current
        degradation rung, the lifetime stats dict, and the structured
        rejections. Pure observation — never mutates."""
        def row(r: Request) -> dict:
            return {"rid": r.rid, "trace": r.trace_id,
                    "state": r.state.value, "slot": r.slot,
                    "tenant": r.tenant_id,
                    "adapter_slot": r.adapter_slot,
                    "prompt_tokens": len(r.prompt),
                    "out_tokens": len(r.out_tokens),
                    "num_computed": r.num_computed,
                    "preemptions": r.preemptions,
                    "outcome": r.outcome}
        waiting = list(self.waiting)
        running = sorted(self.running.values(), key=lambda r: r.rid)
        return {
            "rung": self.rung,
            "waiting_depth": len(waiting),
            "running_depth": len(running),
            "waiting": [row(r) for r in waiting[:max_requests]],
            "running": [row(r) for r in running[:max_requests]],
            "stats": {k: (list(v) if isinstance(v, list) else v)
                      for k, v in self.stats.items()},
            "rejected_requests": [
                {"rid": rr.rid, "reason": rr.reason}
                for rr in self.rejected_requests[-max_requests:]],
        }

    def finish(self, req: Request) -> None:
        """Evict a finished sequence: its slot's pages drop a refcount —
        unshared, unhashed ones return to the pool; hashed ones park in
        the prefix cache's LRU — so the next schedule() backfills from
        the waiting queue."""
        assert req.state == RequestState.RUNNING, req.state
        req.state = RequestState.FINISHED
        req.outcome = RequestOutcome.COMPLETED
        del self.running[req.slot]
        self.cache.free_slot(req.slot)
        self._release_adapter(req)
        req.slot = -1
