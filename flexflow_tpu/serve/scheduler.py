"""Continuous-batching scheduler.

Policy (the "continuous batching" of Orca / vLLM, re-cut for TPU static
shapes — see docs/serving.md):

  * FCFS admission: waiting requests are admitted in arrival order,
    never reordered, as long as (a) a decode slot is free, (b) the
    KV-cache can reserve the request's WORST-CASE pages (prompt +
    max_new_tokens — no preemption path exists, so a running sequence
    must never be able to strand the pool), and (c) this step's
    admitted prompt tokens stay under `prefill_token_budget` (bounds
    the latency hit decode lanes take while prefills run).
  * Prefill/decode interleaving: every scheduler step first admits
    prefills under the budget, then decodes ALL running sequences as
    one batch. A long queue therefore never starves decode, and fresh
    capacity never idles waiting for the batch to drain.
  * Eviction + backfill: the moment a sequence finishes, its slot and
    pages are freed — the NEXT schedule() call immediately admits from
    the waiting queue into the vacated capacity. The batch composition
    changes between steps, not between full batches (the whole point
    of continuous batching vs. static batching).

The scheduler is pure host-side bookkeeping over the PagedKVCache; the
engine owns all device work. Splitting it this way keeps the policy
testable as plain Python (tests/test_serve.py property asserts) and
keeps the jitted steps free of data-dependent shapes.
"""

from __future__ import annotations

import dataclasses
import enum
from collections import deque
from typing import Deque, Dict, List, Optional, Sequence

from .kv_cache import PagedKVCache


class RequestState(enum.Enum):
    WAITING = "waiting"
    RUNNING = "running"   # prefilled; holds a decode slot
    FINISHED = "finished"


@dataclasses.dataclass
class Request:
    """One generation request. `prompt` is token ids; generation stops
    after `max_new_tokens` or on `eos_token` (if given)."""

    rid: int
    prompt: List[int]
    max_new_tokens: int
    eos_token: Optional[int] = None

    state: RequestState = RequestState.WAITING
    slot: int = -1
    out_tokens: List[int] = dataclasses.field(default_factory=list)
    # serving metrics (utils/profiling.serve_report): wall-clock stamps
    t_submit: float = 0.0
    t_first_token: float = 0.0
    t_finish: float = 0.0

    @property
    def total_tokens(self) -> int:
        return len(self.prompt) + self.max_new_tokens

    def is_done(self) -> bool:
        if len(self.out_tokens) >= self.max_new_tokens:
            return True
        return (self.eos_token is not None and self.out_tokens
                and self.out_tokens[-1] == self.eos_token)


@dataclasses.dataclass
class StepPlan:
    """What one engine iteration executes: the prompts to prefill now
    (each lands in its own freshly-bound slot) and the running set to
    decode one token for."""

    prefills: List[Request]
    decodes: List[Request]


class ContinuousBatchingScheduler:
    def __init__(self, cache: PagedKVCache,
                 prefill_token_budget: int = 512):
        self.cache = cache
        self.prefill_token_budget = int(prefill_token_budget)
        self.waiting: Deque[Request] = deque()
        self.running: Dict[int, Request] = {}  # slot -> request
        self._next_rid = 0

    # ---------------- submission --------------------------------------
    def submit(self, prompt: Sequence[int], max_new_tokens: int,
               eos_token: Optional[int] = None) -> Request:
        if len(prompt) < 1:
            raise ValueError("empty prompt")
        if int(max_new_tokens) < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1 (got {max_new_tokens}): "
                f"prefill always emits the first token")
        total = len(prompt) + int(max_new_tokens)
        if total > self.cache.cfg.max_seq_len:
            raise ValueError(
                f"request needs {total} tokens > max_seq_len "
                f"{self.cache.cfg.max_seq_len}")
        req = Request(rid=self._next_rid, prompt=list(prompt),
                      max_new_tokens=int(max_new_tokens),
                      eos_token=eos_token)
        self._next_rid += 1
        self.waiting.append(req)
        return req

    def has_work(self) -> bool:
        return bool(self.waiting or self.running)

    # ---------------- the policy --------------------------------------
    def schedule(self) -> StepPlan:
        """One step's plan. Admits FCFS under the token budget, then
        decodes everything running. Head-of-line blocking is
        deliberate: when the oldest waiting request doesn't fit we stop
        admitting rather than scan past it, so no request can be
        starved by a stream of smaller latecomers."""
        prefills: List[Request] = []
        budget = self.prefill_token_budget
        while self.waiting:
            req = self.waiting[0]
            # the FIRST admission of a step ignores the budget so a
            # prompt longer than the whole budget still gets served
            # (alone in its step) instead of deadlocking the queue
            if prefills and len(req.prompt) > budget:
                break
            if not self.cache.can_admit(req.total_tokens):
                break
            self.waiting.popleft()
            req.slot = self.cache.alloc_slot(len(req.prompt),
                                             req.total_tokens)
            req.state = RequestState.RUNNING
            self.running[req.slot] = req
            budget -= len(req.prompt)
            prefills.append(req)
        decodes = [self.running[s] for s in sorted(self.running)
                   if self.running[s] not in prefills]
        return StepPlan(prefills=prefills, decodes=decodes)

    def finish(self, req: Request) -> None:
        """Evict a finished sequence: free its slot's pages back to the
        pool so the next schedule() backfills from the waiting queue."""
        assert req.state == RequestState.RUNNING, req.state
        req.state = RequestState.FINISHED
        del self.running[req.slot]
        self.cache.free_slot(req.slot)
        req.slot = -1
