"""Disaggregated prefill/decode serving: page-handoff engines.

Why split the roles (docs/serving.md "Disaggregated serving"): the ONE
mixed program is fixed-shape — every step dispatches
``serve_prefill_budget + serve_max_seqs`` lanes whether or not any
prefill is riding along, so under mixed traffic every DECODE token
pays the prefill budget's compute. That is the TPOT tax disaggregation
removes: a ``PrefillEngine`` role runs the budget-wide program and
nothing else, a ``DecodeEngine`` role runs a program whose prefill
budget is a page-sized stub (just enough to recompute a handoff's
partial tail page), and finished KV pages cross between them as a
host-side page transfer.

The handoff rides the existing machinery end to end:

  * pages are already the transfer unit (serve/kv_cache.py), and the
    chain-hash prefix registry is already a content identity — a page's
    key commits to every token before it, so equal keys mean equal
    (content, position) on ANY engine serving the same model;
  * ``PagedKVCache.export_pages`` names a finished slot's full pages +
    keys, ``ServeEngine.export_kv`` gathers their device rows (values
    + scale rows — int8/fp8 pools ship their quantized bytes, the same
    up-to-4x lever they are in HBM), ``import_pages``/``import_kv``
    park them in the decode engine's prefix LRU: hashed, refcount 0,
    matchable — EXACTLY the state a locally computed page reaches when
    its last owner finishes, so admission, attach, eviction and the
    degradation ladder need no new states;
  * the decode engine then serves the request as a prefix-cache hit:
    its admission path matches the imported chain, attaches the pages
    with zero compute, and chunk-prefills only the partial tail page
    (+ the first token's position) — which keeps the cluster
    token-identical to the unified engine by construction, because
    every K/V the decode engine reads is either bit-equal transferred
    content or locally recomputed at the same positions.

Backpressure is the degradation ladder: a shipment only imports while
the decode pool can hold it above the admission watermark; past that
the cluster SKIPS the import (counted, spanned) and the decode engine
re-prefills the prompt itself — graceful degradation to unified
behavior instead of a stalled link.

The prefill:decode engine ratio is not hand-tuned: the placement
search prices the split — per-role step costs + the page-handoff link
on the machine model's host link — and returns the ratio table
(search/serve_place.optimize_serve_disagg, ``optimize_serve(...,
disaggregated=True)``), the "Beyond Data and Model Parallelism"
discipline applied to a new axis.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..utils.telemetry import (Telemetry, serve_metrics,
                               telemetry_for)
from .engine import ServeEngine

# the cluster's telemetry track (kv_handoff spans + skip instants)
_CLUSTER_TRACK = ("serve", "cluster")


@dataclasses.dataclass
class PageShipment:
    """One slot's finished KV pages, host-side: the unit a prefill
    engine hands a decode engine. ``keys`` are the chain hashes (the
    transfer identity — position-dependence is implicit in the chain),
    ``k_rows``/``v_rows`` the page value rows as numpy
    ``(layers, n_pages, page_size, heads, head_dim)`` at the pool's
    storage dtype, ``*_scale_rows`` the f32 per-row scale arrays on
    quantized pools (None otherwise). The geometry stamp lets
    ``import_kv`` reject a pool-shape mismatch loudly instead of
    dequantizing garbage. ``stream_id`` carries the request's
    sampling-stream identity across the split (docs/serving.md
    "Sampled streams"): the decode role resumes the stream at offset
    1, so seeded temperature/top-k decoding survives the handoff
    token-for-token instead of being refused."""

    keys: List[bytes]
    ntokens: int
    k_rows: np.ndarray
    v_rows: np.ndarray
    k_scale_rows: Optional[np.ndarray]
    v_scale_rows: Optional[np.ndarray]
    page_size: int
    num_layers: int
    num_heads: int
    head_dim: int
    kv_dtype: str
    stream_id: Optional[int] = None
    # multi-tenant adapter serving (serve/adapters.py): the tenant
    # whose adapter the request decodes under crosses the link WITH
    # its pages — the decode role must admit the continuation under
    # the same tenant (salted prefix chain, adapter slot) or the
    # imported pages could never match
    tenant_id: int = 0
    # trace-context propagation (docs/observability.md): the request's
    # trace id crosses the link WITH its pages, so the kv_handoff span
    # and the decode role's spans land on the same causally-linked
    # timeline the prefill role started
    trace_id: Optional[int] = None

    def signature(self) -> tuple:
        return (self.page_size, self.num_layers, self.num_heads,
                self.head_dim, self.kv_dtype)

    @property
    def num_pages(self) -> int:
        return len(self.keys)

    @property
    def nbytes(self) -> int:
        """Host-link bytes this shipment moves (values + scale rows) —
        what kv_transfer_bytes_total counts and what the search prices
        via cost_model.kv_handoff_bytes."""
        n = int(self.k_rows.nbytes + self.v_rows.nbytes)
        if self.k_scale_rows is not None:
            n += int(self.k_scale_rows.nbytes
                     + self.v_scale_rows.nbytes)
        return n


def engine_for(model, **kw):
    """The config-driven serving entry point — the consumer of
    ``--serve-disagg``: a :class:`DisaggCluster` (ratio per
    ``serve_disagg_ratio``: "" = 1:1, "P:D", or "auto" via the ratio
    search) when ``FFConfig.serve_disagg`` is set, else a plain
    :class:`ServeEngine`.

    The SHARED surface a flag-agnostic driver may use: ``warmup()``,
    ``generate(prompts, max_new_tokens, eos_token=, temperature=,
    top_k=, sample_seed=, on_step=)``, ``generate_reference()``,
    ``last_stats``, ``close()`` / context manager. ``on_step`` is
    arity-normalized (:func:`normalize_on_step`): the cluster accepts
    BOTH the engine's ``on_step(step)`` and its own
    ``on_step(role, engine_idx, step)``, so a hook written for one
    type cannot silently receive the wrong arguments from the other.
    Anything beyond the shared surface is type-specific — engine-only
    constructor kwargs (``mesh``/``faults``/...) — and ``**kw`` goes
    verbatim to whichever type the flag selects, so pass only kwargs
    valid for that type."""
    if getattr(model.config, "serve_disagg", False):
        return DisaggCluster.from_config(model, **kw)
    return ServeEngine(model, **kw)


def normalize_on_step(on_step):
    """Normalize a step hook to the cluster's canonical
    ``cb(role, engine_idx, step)`` form, accepting either arity:

      * ``on_step(step)`` — the ``ServeEngine.generate`` signature; the
        role/index context is dropped on the adapter's floor;
      * ``on_step(role, engine_idx, step)`` — the cluster-native form.

    Arity is resolved by signature binding (bound methods, partials
    and ``*args`` callables all work; a callable binding both forms is
    taken as 3-ary — the richer one). Anything that binds neither
    raises here, at arming time, instead of detonating mid-serve on
    the first step."""
    if on_step is None:
        return None
    import inspect
    try:
        sig = inspect.signature(on_step)
    except (TypeError, ValueError):
        return on_step   # uninspectable (builtin): trust 3-ary
    def binds(k):
        try:
            sig.bind(*(None,) * k)
            return True
        except TypeError:
            return False
    if binds(3):
        return on_step
    if binds(1):
        return lambda _role, _idx, step: on_step(step)
    raise TypeError(
        "on_step must accept (step) or (role, engine_idx, step); "
        f"got signature {sig}")


class DisaggCluster:
    """Prefill/decode-disaggregated serving over one model.

    Builds dedicated ``ServeEngine`` roles sharing the model's
    parameters (and device copies thereof):

      * ``prefill_engines`` engines run the full budget-wide mixed
        program; each request prefills there with ``max_new=1`` — the
        final prefill chunk emits the FIRST token, and the finished
        prompt pages export at that boundary (generate's ``on_finish``
        hook, while the slot is still mapped);
      * ``decode_engines`` engines run a program whose prefill budget
        is ``decode_budget`` lanes (default 2 pages' worth — the stub
        that recomputes a handoff's partial tail), so a decode step
        costs the decode lanes, not the budget;
      * requests route prefill -> (page handoff) -> decode
        round-robin, with the decode pool's admission watermark as the
        handoff backpressure signal.

    Sampled streams cross the split (docs/serving.md "Sampled
    streams"): seeded draws key on a stream-id carried with the
    request (and stamped into its PageShipment) plus a stream offset,
    not the local scheduler's rid/token index — the prefill role draws
    index 0 of stream i, the decode role resumes stream i at offset 1,
    so seeded temperature/top-k decoding is token-identical to the
    unified engine at the same seed instead of being refused.

    Everything is synchronous host-side orchestration (one process,
    both roles' programs on the same devices here): the measurable win
    is structural — decode steps stop paying for prefill lanes — and
    tools/serve_bench.py ``--workload disagg`` gates it as the
    TPOT-p99 reduction at equal device count, next to the placement
    search's simulated ratio table for the production shape."""

    def __init__(self, model, *, prefill_engines: int = 1,
                 decode_engines: int = 1,
                 decode_budget: Optional[int] = None,
                 spec_tokens: Optional[int] = None, drafter=None,
                 use_pallas: Optional[bool] = None,
                 interpret: bool = False,
                 telemetry: Optional[Telemetry] = None):
        if prefill_engines < 1 or decode_engines < 1:
            raise ValueError(
                f"a disaggregated cluster needs >= 1 engine per role, "
                f"got {prefill_engines}:{decode_engines}")
        if model.state is None:
            from ..config import CompMode
            model.compile(comp_mode=CompMode.INFERENCE)
        self.model = model
        cfg = model.config
        self.config = cfg
        self.telemetry = telemetry if telemetry is not None \
            else telemetry_for(cfg)
        ps = int(getattr(cfg, "kv_page_size", 16))
        if decode_budget is None:
            decode_budget = int(getattr(cfg, "serve_disagg_decode_budget",
                                        0) or 0)
        # the decode role's prefill stub: big enough for one handoff
        # tail chunk per admission (a tail is < page_size prompt tokens
        # + the first generated token), two pages' worth by default so
        # two requests can land per step
        self.decode_budget = int(decode_budget) if decode_budget \
            else 2 * ps
        if self.decode_budget < ps:
            raise ValueError(
                f"decode_budget ({self.decode_budget}) must cover at "
                f"least one page ({ps} tokens): the decode role "
                f"recomputes handoff tail chunks through it")

        def role_engine(budget: int) -> ServeEngine:
            role_cfg = dataclasses.replace(
                cfg, serve_prefill_budget=int(budget),
                # role engines own no scrape endpoint — the cluster's
                # caller decides where metrics serve from
                metrics_port=None)
            return ServeEngine(
                model, chunked_prefill=True, prefix_cache=True,
                spec_tokens=spec_tokens, drafter=drafter,
                use_pallas=use_pallas, interpret=interpret,
                telemetry=self.telemetry, config=role_cfg)

        full_budget = int(getattr(cfg, "serve_prefill_budget", 512))
        self.prefill: List[ServeEngine] = [
            role_engine(full_budget) for _ in range(int(prefill_engines))]
        self.decode: List[ServeEngine] = [
            role_engine(self.decode_budget)
            for _ in range(int(decode_engines))]
        # prefill-role speculation is moot (max_new=1 never decodes);
        # leave it configured — the scheduler simply never drafts
        self.kv_exact = self.prefill[0].kv_exact
        self.stats: Dict[str, float] = {
            "handoff_requests": 0, "handoff_pages": 0,
            "handoff_bytes": 0, "handoff_dedup_pages": 0,
            "handoff_skipped": 0, "handoff_seconds": 0.0}
        self.last_stats: Optional[dict] = None
        self.placement = None   # set by from_config's "auto" path
        # (trace_id, prefill Request, decode Request) triples of the
        # last generate() — the cross-role explain_request source
        self._last_traces: List[list] = []
        # the cluster-lifetime registry the per-role TTFT/TPOT split
        # folds into (serve_metrics role labels; disagg_report reads
        # it). With telemetry enabled it IS the bus's registry (the
        # engines fold their aggregates there too); disabled, the
        # cluster keeps its own — never the shared disabled
        # singleton's, which other components would see polluted.
        from ..utils.telemetry import MetricsRegistry
        self.metrics = self.telemetry.metrics if self.telemetry.enabled \
            else MetricsRegistry()
        # the cluster owns the scrape endpoint the role engines were
        # denied (role_cfg forces metrics_port=None): --metrics-port
        # under --serve-disagg serves the CLUSTER registry — aggregate
        # + role-labeled series + handoff counters — from one port,
        # exactly the autoscaler poll target a unified engine exposes
        self.metrics_server = None
        mport = getattr(cfg, "metrics_port", None)
        if mport is not None:
            from ..utils.telemetry import MetricsServer
            self.metrics_server = MetricsServer(
                self.metrics.to_prometheus, port=int(mport),
                host=str(getattr(cfg, "metrics_host", "127.0.0.1")))
        # --transport tcp: shipments leave generate() as length-
        # prefixed socket frames (serve/transport.py) instead of
        # in-process handoffs. The cluster arms BOTH ends on loopback —
        # the receiver imports into this cluster's own decode pool
        # (same watermark gate, via _import_shipment) — so one process
        # exercises the full wire path; a multi-host deployment points
        # the sender at another host's receiver (open_receiver()).
        self._receiver = None
        self._sender = None
        tname = str(getattr(cfg, "serve_transport", "") or "").strip()
        if tname:
            if tname != "tcp":
                raise ValueError(
                    f"unknown serve transport {tname!r} (supported: "
                    f"'tcp', '' = in-process handoff)")
            from .transport import ShipmentSender
            self._receiver = self.open_receiver(
                host=str(getattr(cfg, "serve_transport_host",
                                 "127.0.0.1")),
                port=int(getattr(cfg, "serve_transport_port", 0) or 0))
            self._sender = ShipmentSender(self._receiver.host,
                                          self._receiver.port)

    def open_receiver(self, *, host: str = "127.0.0.1",
                      port: int = 0):
        """Start a :class:`~.transport.ShipmentReceiver` importing
        into THIS cluster's decode pool — the listening end a remote
        prefill tier's ``ShipmentSender`` targets. Admission is the
        same watermark gate as the in-process handoff; the import runs
        on the receiver's connection thread while the sender blocks on
        the ack, so at most one import mutates an engine at a time."""
        from .transport import ShipmentReceiver
        return ShipmentReceiver(self._import_shipment, host=host,
                                port=int(port))

    def _import_shipment(self, ship: PageShipment) -> dict:
        """Receiver-side import: decode-engine choice keys on the
        shipment's stream id (== the request's global index, the same
        round-robin the in-process handoff uses), so the wire path is
        placement-identical to the in-process one."""
        return self._handoff(ship, int(ship.stream_id or 0))

    @classmethod
    def from_config(cls, model, *, num_devices: Optional[int] = None,
                    **kw) -> "DisaggCluster":
        """Build a cluster from FFConfig's --serve-disagg knobs:
        serve_disagg_ratio "" = 1:1, "P:D" = those engine counts,
        "auto" = the placement search's ratio table
        (search/serve_place.optimize_serve_disagg over this model's
        ServeArch at `num_devices` — default: the visible device
        count, floored at 2 so the split exists). The winning
        DisaggPlacement lands on `cluster.placement`."""
        cfg = model.config
        sr = str(getattr(cfg, "serve_disagg_ratio", "") or "").strip()
        p = d = 1
        placement = None
        if sr == "auto":
            import jax
            from ..search.serve_place import optimize_serve
            # a light probe engine, purely for serve_arch()'s model
            # introspection: no scrape port, no serve-mesh resolution
            # (which could itself run the unified search), and the
            # device page pools are lazy so nothing allocates
            probe = ServeEngine(
                model, tensor_parallel=1,
                config=dataclasses.replace(cfg, metrics_port=None,
                                           serve_mesh=""))
            try:
                ndev = int(num_devices) if num_devices else max(
                    2, len(jax.devices()))
                ps = int(getattr(cfg, "kv_page_size", 16))
                stub = int(getattr(cfg, "serve_disagg_decode_budget",
                                   0) or 0) or 2 * ps
                # price the decode role at the stub width the cluster
                # will ACTUALLY build (the search's
                # priced-like-executed contract)
                arch = dataclasses.replace(probe.serve_arch(),
                                           handoff_stub_lanes=stub)
                placement = optimize_serve(arch, ndev, config=cfg,
                                           disaggregated=True)
                p, d = (placement.prefill_engines,
                        placement.decode_engines)
            finally:
                probe.close()
        elif sr:
            p, d = (int(x) for x in sr.split(":"))
        cluster = cls(model, prefill_engines=p, decode_engines=d, **kw)
        cluster.placement = placement
        return cluster

    # ---------------- role plumbing ------------------------------------
    def engines(self) -> List[Tuple[str, ServeEngine]]:
        return ([("prefill", e) for e in self.prefill]
                + [("decode", e) for e in self.decode])

    def warmup(self) -> Dict[str, Dict[str, int]]:
        """Compile every role's mixed program AND the handoff
        export/import programs; after this the cluster never compiles
        (compile_counts drift is the zero-recompile gate)."""
        out = {}
        for i, (role, eng) in enumerate(self.engines()):
            eng.warmup()
            out[f"{role}{i}"] = eng.warmup_handoff()
        return out

    def compile_counts(self) -> Dict[str, Dict[str, int]]:
        return {f"{role}{i}": eng.compile_counts()
                for i, (role, eng) in enumerate(self.engines())}

    def check_invariants(self) -> None:
        for _, eng in self.engines():
            eng.cache.check_invariants()
            if eng.adapters is not None:
                eng.adapters.check_invariants()

    def register_adapter(self, tenant_id: int, weights, *,
                         scale: float = 1.0) -> None:
        """Register a tenant's LoRA adapter on EVERY role engine: a
        request may prefill on any prefill engine and decode on any
        decode engine, so the registry must be cluster-uniform."""
        for _, eng in self.engines():
            eng.register_adapter(tenant_id, weights, scale=scale)

    def close(self) -> None:
        server, self.metrics_server = self.metrics_server, None
        if server is not None:
            server.close()
        sender, self._sender = self._sender, None
        if sender is not None:
            sender.close()
        receiver, self._receiver = self._receiver, None
        if receiver is not None:
            receiver.close()
        for _, eng in self.engines():
            eng.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ---------------- the handoff --------------------------------------
    def _admit_shipment(self, eng: ServeEngine, ship: PageShipment
                        ) -> bool:
        """Backpressure: import only while the decode pool can hold
        the new pages AND stay above its admission watermark — the
        same planning-visible pressure signal the degradation ladder
        reads. Past it the shipment is dropped and the decode engine
        re-prefills (rung-2 behavior: stop pinning reclaimable pages
        when admissions are starved)."""
        need = sum(1 for k in ship.keys
                   if not eng.cache.key_resident(k))
        headroom = eng.cache.free_pages - need
        from .scheduler import watermark_pages
        wm = watermark_pages(eng.admit_watermark,
                             eng.cache_cfg.usable_pages)
        return headroom >= max(wm, 1)

    def _ship(self, ship: Optional[PageShipment], rid) -> None:
        """Route one shipment toward the decode pool: over the armed
        socket transport when --transport is set (send blocks for the
        receiver's ack — the wire's backpressure), else the in-process
        handoff."""
        if ship is None:
            return
        if self._sender is not None:
            self._sender.send(ship)
        else:
            self._handoff(ship, rid)

    def _handoff(self, ship: Optional[PageShipment], rid) -> dict:
        """Move one shipment prefill -> decode (round-robin by rid),
        emitting the kv_handoff span + transfer counters. Returns the
        ack dict the socket receiver forwards to its sender."""
        if ship is None:
            return {"accepted": False, "pages_written": 0}
        eng = self.decode[rid % len(self.decode)]
        tel = self.telemetry
        t0 = time.perf_counter()
        if not self._admit_shipment(eng, ship):
            self.stats["handoff_skipped"] += 1
            if tel.enabled:
                tel.instant(_CLUSTER_TRACK, "kv_handoff_skipped",
                            args={"rid": rid, "pages": ship.num_pages,
                                  "trace": ship.trace_id})
            return {"accepted": False, "pages_written": 0}
        before_dedup = eng.cache.stats["import_dedup_pages"]
        written = eng.import_kv(ship)
        dt = time.perf_counter() - t0
        dedup = eng.cache.stats["import_dedup_pages"] - before_dedup
        nbytes = ship.nbytes * written // max(1, ship.num_pages)
        self.stats["handoff_requests"] += 1
        self.stats["handoff_pages"] += written
        self.stats["handoff_bytes"] += nbytes
        self.stats["handoff_dedup_pages"] += dedup
        self.stats["handoff_seconds"] += dt
        if tel.enabled:
            tel.span(_CLUSTER_TRACK, "kv_handoff", t0, t0 + dt,
                     args={"rid": rid, "pages": written,
                           "dedup_pages": dedup, "bytes": nbytes,
                           "trace": ship.trace_id})
            tel.metrics.inc("kv_transfer_bytes_total", nbytes)
            tel.metrics.inc("kv_transfer_pages_total", written)
        return {"accepted": True, "pages_written": written}

    # ---------------- the serving loop ---------------------------------
    def generate(self, prompts: Sequence[Sequence[int]],
                 max_new_tokens, eos_token: Optional[int] = None,
                 temperature=None, top_k=None, sample_seed: int = 0,
                 on_step=None,
                 tenant_ids: Optional[Sequence[int]] = None
                 ) -> List[List[int]]:
        """Serve a batch disaggregated: prefill engines compute every
        prompt and its FIRST token, finished pages hand off to decode
        engines, which emit the rest. Token-identical to the unified
        ``ServeEngine.generate`` on lossless pools (the quantized
        contract relaxes exactly as it does everywhere else). Greedy /
        top_k=1 only (see class docstring). ``on_step`` observes every
        role engine's steps (the per-pool invariant hook of the
        property tests) — either arity, ``on_step(step)`` or
        ``on_step(role, engine_idx, step)``, via
        :func:`normalize_on_step`."""
        on_step = normalize_on_step(on_step)
        n = len(prompts)

        def per_req(x, name):
            """Broadcast a scalar/None arg to one entry per request —
            the waves below slice these, so every role engine sees
            exactly its requests' entries."""
            if x is None or np.isscalar(x):
                return [x] * n
            x = list(x)
            if len(x) != n:
                raise ValueError(
                    f"{name} has {len(x)} entries for {n} prompts")
            return x

        temps = per_req(temperature, "temperature")
        tks = per_req(top_k, "top_k")
        # tenancy crosses the split with the request: the prefill role
        # computes the salted chain + adapted K/V, the shipment stamps
        # the tenant, and the decode role re-admits under the same id
        tens = per_req(0 if tenant_ids is None else list(tenant_ids),
                       "tenant_ids")
        if isinstance(max_new_tokens, int):
            max_new_tokens = [max_new_tokens] * n
        if len(max_new_tokens) != n:
            raise ValueError(
                f"max_new_tokens has {len(max_new_tokens)} entries "
                f"for {n} prompts")
        for mnt in max_new_tokens:
            if int(mnt) < 1:
                # mirror scheduler.submit's contract up front: the
                # prefill role would otherwise silently serve 1 token
                # where the unified engine refuses
                raise ValueError(
                    f"max_new_tokens must be >= 1, got {mnt}")
        t_start = time.perf_counter()
        tel = self.telemetry
        stats0 = dict(self.stats)  # lifetime counters: fold the DELTA
        # ONE trace id per request for its WHOLE disaggregated life:
        # the prefill-role spans, the kv_handoff span (via the
        # PageShipment) and the decode-role spans all carry it, so the
        # exported trace holds one causally-linked timeline per
        # request across the split (docs/observability.md)
        from ..utils.telemetry import next_trace_id
        tids = [next_trace_id() for _ in range(n)]
        # (trace_id, prefill Request, decode Request) per request —
        # the explain_request / fold_attribution source
        self._last_traces = [[tids[i], None, None] for i in range(n)]

        # ---- phase 1: prefill role (+ export at each finish) ----------
        # round-robin the batch over the prefill engines; every request
        # runs max_new=1, so the mixed program only ever carries
        # prefill chunks and each request's finish IS its first token
        first: List[Optional[int]] = [None] * n
        ships: List[Optional[PageShipment]] = [None] * n
        waves: List[List[int]] = [[] for _ in self.prefill]
        for i in range(n):
            waves[i % len(self.prefill)].append(i)
        pre_stats: List[dict] = []
        for w, (eng, idxs) in enumerate(zip(self.prefill, waves)):
            if not idxs:
                continue
            local = {}

            def grab(req, _eng=eng, _local=local, _idxs=idxs):
                # rids are assigned in submit order within this wave;
                # skip the export entirely for requests phase 3 will
                # drop anyway (max_new=1, or eos as the first token) —
                # no point gathering and copying pages nobody imports
                i = _idxs[req.rid]
                if max_new_tokens[i] <= 1 or (
                        eos_token is not None and req.out_tokens
                        and req.out_tokens[-1] == eos_token):
                    return
                _local[req.rid] = _eng.export_kv(
                    req.slot, req.context, stream_id=req.stream_id,
                    trace_id=req.trace_id, tenant_id=req.tenant_id)

            # stream ids = GLOBAL request indices (the identity a
            # unified engine's rids would be), so sampled draws on
            # either side of the split reproduce the unified stream
            out = eng.generate(
                [prompts[i] for i in idxs], 1, eos_token=eos_token,
                temperature=[temps[i] for i in idxs],
                top_k=[tks[i] for i in idxs],
                sample_seed=sample_seed, on_finish=grab,
                stream_ids=list(idxs),
                trace_ids=[tids[i] for i in idxs],
                tenant_ids=[tens[i] for i in idxs],
                on_step=(None if on_step is None else
                         (lambda s, _w=w: on_step("prefill", _w, s))))
            for rid, i in enumerate(idxs):
                # an aborted prefill (deadline expiry, fault-failed
                # in-flight) returns NO tokens — mirror the unified
                # engine's empty output instead of crashing the batch
                first[i] = out[rid][0] if out[rid] else None
                ships[i] = local.get(rid)
                self._last_traces[i][1] = eng._last_reqs.get(rid)
            pre_stats.append(eng.last_stats)

        # which requests actually continue to the decode role: done-at-
        # first-token requests (max_new=1, eos on the first token, or
        # aborted before emitting) ship NOTHING — their pages would
        # only park in the decode pool and compete with real handoffs
        # for backpressure headroom
        decode_idx = [i for i in range(n)
                      if first[i] is not None
                      and max_new_tokens[i] > 1
                      and not (eos_token is not None
                               and first[i] == eos_token)]

        # ---- phase 2: page handoff (with backpressure) ----------------
        for i in decode_idx:
            self._ship(ships[i], i)

        # ---- phase 3: decode role -------------------------------------
        # each surviving request continues as prompt + [first token]
        # with max_new - 1 budget; the decode engine admits it as a
        # prefix-cache hit over the imported pages and recomputes only
        # the tail chunk
        results: List[List[int]] = [
            [] if t is None else [t] for t in first]
        dec_stats: List[dict] = []
        dwaves: List[List[int]] = [[] for _ in self.decode]
        for i in decode_idx:
            dwaves[i % len(self.decode)].append(i)
        for w, (eng, idxs) in enumerate(zip(self.decode, dwaves)):
            if not idxs:
                continue
            # the decode role RESUMES each stream at offset 1: the
            # prefill role already drew token-index 0 (the first
            # token), so the continuation's draws line up with the
            # unified engine's indices 1..max_new-1
            out = eng.generate(
                [list(prompts[i]) + [first[i]] for i in idxs],
                [max_new_tokens[i] - 1 for i in idxs],
                eos_token=eos_token,
                temperature=[temps[i] for i in idxs],
                top_k=[tks[i] for i in idxs],
                sample_seed=sample_seed,
                stream_ids=list(idxs), stream_offset=1,
                trace_ids=[tids[i] for i in idxs],
                tenant_ids=[tens[i] for i in idxs],
                on_step=(None if on_step is None else
                         (lambda s, _w=w: on_step("decode", _w, s))))
            for j, i in enumerate(idxs):
                results[i].extend(out[j])
                self._last_traces[i][2] = eng._last_reqs.get(j)
            dec_stats.append(eng.last_stats)

        wall = time.perf_counter() - t_start
        total_new = sum(len(r) for r in results)
        self.last_stats = {
            "mode": "disagg",
            "pipelined": False,
            "transport": ("tcp" if self._sender is not None
                          else "inproc"),
            "prefill_engines": len(self.prefill),
            "decode_engines": len(self.decode),
            "decode_budget": self.decode_budget,
            "wall_s": wall,
            "total_new_tokens": total_new,
            "tokens_per_sec": total_new / wall if wall > 0 else 0.0,
            # THIS call's handoff accounting (self.stats stays the
            # cluster-lifetime totals) — per-call numbers must sit
            # next to per-call wall_s/tokens
            "handoff": {k: self.stats[k] - stats0[k]
                        for k in self.stats},
            "roles": {"prefill": pre_stats, "decode": dec_stats},
            "compile_counts": self.compile_counts(),
        }
        # fold the per-role latency split into the cluster registry —
        # what disagg_report renders from. With telemetry enabled the
        # role engines already folded the UNLABELED aggregates into
        # this same registry after their generates, so only the
        # role-labeled series are added here; disabled, the cluster
        # owns its registry and folds both.
        m = self.metrics
        for st in pre_stats:
            if not tel.enabled:
                serve_metrics(st, registry=m)
            serve_metrics(st, registry=m, role="prefill")
        for st in dec_stats:
            if not tel.enabled:
                serve_metrics(st, registry=m)
            serve_metrics(st, registry=m, role="decode")
        def delta(k):
            return self.stats[k] - stats0[k]

        m.inc("kv_handoff_requests_total", delta("handoff_requests"))
        m.inc("kv_handoff_skipped_total", delta("handoff_skipped"))
        if not tel.enabled:
            # with telemetry on, _handoff already counted these on the
            # (same) registry per shipment
            m.inc("kv_transfer_bytes_total", delta("handoff_bytes"))
            m.inc("kv_transfer_pages_total", delta("handoff_pages"))
        return results

    # ---------------- the pipelined serving loop ------------------------
    def generate_pipelined(self, prompts: Sequence[Sequence[int]],
                           max_new_tokens,
                           eos_token: Optional[int] = None,
                           temperature=None, top_k=None,
                           sample_seed: int = 0, on_step=None,
                           tenant_ids: Optional[Sequence[int]] = None
                           ) -> List[List[int]]:
        """Serve the batch with CONTINUOUS prefill/decode pipelining:
        one event loop drives every role engine's steppable
        ``ServeSession``, so the moment a request's prefill finishes
        its pages hand off and its continuation is admitted to a
        decode engine — while the remaining prefills are still
        running. Both roles' programs stay busy concurrently instead
        of the phased generate()'s prefill-wave -> handoff ->
        decode-wave barriers; per-request TTFT stops paying for the
        rest of the batch's prefill wave.

        TOKEN-IDENTICAL to the phased ``generate`` (and the unified
        engine) by the same construction: stream ids are the global
        request indices, the decode continuation resumes each stream
        at offset 1, and the handoff/admission path is byte-for-byte
        the one the phased loop uses — the loop only reorders WHEN
        steps run, never what they compute. With ``--transport tcp``
        each shipment crosses the socket (the ack blocks this loop, so
        the receiver's import never races a decode step).

        ``on_step`` accepts either hook arity (normalize_on_step)."""
        on_step = normalize_on_step(on_step)
        n = len(prompts)

        def per_req(x, name):
            if x is None or np.isscalar(x):
                return [x] * n
            x = list(x)
            if len(x) != n:
                raise ValueError(
                    f"{name} has {len(x)} entries for {n} prompts")
            return x

        tens = per_req(0 if tenant_ids is None else list(tenant_ids),
                       "tenant_ids")
        if isinstance(max_new_tokens, int):
            max_new_tokens = [max_new_tokens] * n
        if len(max_new_tokens) != n:
            raise ValueError(
                f"max_new_tokens has {len(max_new_tokens)} entries "
                f"for {n} prompts")
        for mnt in max_new_tokens:
            if int(mnt) < 1:
                raise ValueError(
                    f"max_new_tokens must be >= 1, got {mnt}")
        lead = self.prefill[0]
        samples = lead._sample_params(temperature, top_k, sample_seed,
                                      n, lead.topk_cap)
        t_start = time.perf_counter()
        tel = self.telemetry
        stats0 = dict(self.stats)
        from ..utils.telemetry import next_trace_id
        tids = [next_trace_id() for _ in range(n)]
        self._last_traces = [[tids[i], None, None] for i in range(n)]

        first: List[Optional[int]] = [None] * n
        ships: List[Optional[PageShipment]] = [None] * n
        dreqs: Dict[int, object] = {}
        psess = [eng.start_session() for eng in self.prefill]
        dsess = [eng.start_session() for eng in self.decode]
        try:
            for i in range(n):
                w = i % len(self.prefill)

                def grab(req, _eng=self.prefill[w], _i=i):
                    # export at the finish boundary, slot still
                    # mapped — skipped for requests the decode role
                    # will never see (phased generate's rule)
                    if max_new_tokens[_i] <= 1 or (
                            eos_token is not None and req.out_tokens
                            and req.out_tokens[-1] == eos_token):
                        return
                    ships[_i] = _eng.export_kv(
                        req.slot, req.context,
                        stream_id=req.stream_id,
                        trace_id=req.trace_id,
                        tenant_id=req.tenant_id)

                psess[w].submit(
                    prompts[i], 1, eos_token=eos_token,
                    sample=samples[i], stream_id=i,
                    trace_id=tids[i], tenant_id=tens[i],
                    on_finish=grab)

            def step_role(role, engines, sessions):
                """One step on every busy engine of a role; returns
                the finished requests per engine index."""
                fins = []
                for w, eng in enumerate(engines):
                    s = sessions[w]
                    if not s.has_work():
                        continue
                    try:
                        ev = s.step()
                    except Exception:
                        # contain per engine, phased-generate style:
                        # fail its in-flight requests, keep the rest
                        # of the cluster serving
                        eng._fail_inflight(s.sched, s.reqs)
                        s.close()
                        sessions[w] = eng.start_session()
                        continue
                    if ev is None:
                        continue
                    if on_step is not None:
                        on_step(role, w, ev)
                    for req in ev.finished:
                        fins.append(req)
                return fins

            while any(s.has_work() for s in psess) \
                    or any(s.has_work() for s in dsess):
                for req in step_role("prefill", self.prefill, psess):
                    i = req.stream_id
                    ft = req.out_tokens[0] if req.out_tokens else None
                    first[i] = ft
                    self._last_traces[i][1] = req
                    if ft is None or max_new_tokens[i] <= 1 or (
                            eos_token is not None
                            and ft == eos_token):
                        continue
                    # the pipelining: handoff + decode admission NOW,
                    # not after the whole prefill wave
                    self._ship(ships[i], i)
                    d = i % len(self.decode)
                    dreqs[i] = dsess[d].submit(
                        list(prompts[i]) + [ft],
                        int(max_new_tokens[i]) - 1,
                        eos_token=eos_token, sample=samples[i],
                        stream_id=i, stream_offset=1,
                        trace_id=tids[i], tenant_id=tens[i])
                    self._last_traces[i][2] = dreqs[i]
                step_role("decode", self.decode, dsess)
            pre_stats = [s.stats_dict() for s in psess if s.reqs]
            dec_stats = [s.stats_dict() for s in dsess if s.reqs]
        finally:
            for s in psess + dsess:
                try:
                    s.close()
                except Exception:
                    pass
        results: List[List[int]] = []
        for i in range(n):
            if first[i] is None:
                results.append([])
            elif i in dreqs:
                results.append([first[i]]
                               + list(dreqs[i].out_tokens))
            else:
                results.append([first[i]])
        wall = time.perf_counter() - t_start
        total_new = sum(len(r) for r in results)
        self.last_stats = {
            "mode": "disagg",
            "pipelined": True,
            "transport": ("tcp" if self._sender is not None
                          else "inproc"),
            "prefill_engines": len(self.prefill),
            "decode_engines": len(self.decode),
            "decode_budget": self.decode_budget,
            "wall_s": wall,
            "total_new_tokens": total_new,
            "tokens_per_sec": total_new / wall if wall > 0 else 0.0,
            "handoff": {k: self.stats[k] - stats0[k]
                        for k in self.stats},
            "roles": {"prefill": pre_stats, "decode": dec_stats},
            "compile_counts": self.compile_counts(),
        }
        # sessions never auto-fold (unlike generate(), where each role
        # engine folds its unlabeled aggregates after its wave), so
        # fold both the aggregate and the role-labeled series here
        m = self.metrics
        for st in pre_stats:
            serve_metrics(st, registry=m)
            serve_metrics(st, registry=m, role="prefill")
        for st in dec_stats:
            serve_metrics(st, registry=m)
            serve_metrics(st, registry=m, role="decode")

        def delta(k):
            return self.stats[k] - stats0[k]

        m.inc("kv_handoff_requests_total", delta("handoff_requests"))
        m.inc("kv_handoff_skipped_total", delta("handoff_skipped"))
        if not tel.enabled:
            m.inc("kv_transfer_bytes_total", delta("handoff_bytes"))
            m.inc("kv_transfer_pages_total", delta("handoff_pages"))
        return results

    # ---------------- observability --------------------------------------
    def explain_request(self, index: int) -> dict:
        """Cross-role latency attribution for request `index` of the
        last generate() (docs/observability.md): ONE trace id ties the
        prefill-role spans, the kv_handoff transfer span and the
        decode-role spans together, so the breakdown spans the whole
        disaggregated life — measured from the prefill submit stamp to
        the decode finish stamp (prefill finish when the request never
        crossed the link). Batch-phase orchestration time (other
        requests' waves) lands in ``other`` — honestly unattributable
        to this request's critical path."""
        if not self.telemetry.enabled:
            raise RuntimeError(
                "explain_request needs telemetry (pass telemetry= or "
                "set --telemetry/--trace-out)")
        if not (0 <= index < len(self._last_traces)):
            raise KeyError(
                f"request index {index} not in the last generate "
                f"({len(self._last_traces)} requests)")
        tid, pre, dec = self._last_traces[index]
        if pre is None or not pre.t_finish:
            raise ValueError(
                f"request {index} has no terminated prefill-role "
                f"request to attribute")
        t_finish = dec.t_finish if dec is not None and dec.t_finish \
            else pre.t_finish
        out = self.telemetry.explain_request(tid, pre.t_submit,
                                             t_finish)
        out.update(index=index,
                   outcome=(dec.outcome if dec is not None
                            else pre.outcome),
                   crossed_link=dec is not None)
        return out

    def fold_attribution(self, registry=None) -> dict:
        """Fold every attributable request of the last generate() into
        `registry` (default: the cluster registry) — the aggregate
        `serve_latency_attribution_*` series (utils/telemetry
        .fold_attribution)."""
        from ..utils.telemetry import (REQUEST_COMPONENTS,
                                       fold_attribution)
        m = registry if registry is not None else self.metrics
        totals = {c: 0.0 for c in REQUEST_COMPONENTS}
        if not self.telemetry.enabled:
            return totals   # no spans to attribute (router-fold rule)
        for i in range(len(self._last_traces)):
            try:
                b = self.explain_request(i)
            except (ValueError, KeyError):
                continue
            fold_attribution(b, m)
            for c, v in b["components"].items():
                totals[c] += v
        return totals

    def dump_postmortem(self, path: Optional[str] = None,
                        reason: str = "manual",
                        detail: Optional[dict] = None) -> str:
        """Cluster flight-recorder dump: the lead prefill engine's
        bundle (the roles share ONE telemetry bus, so its ring/metrics
        ARE the cluster's) plus per-role KV-pool state and compile
        counts, and the cluster's handoff accounting."""
        from ..utils.telemetry import write_json_atomic
        lead = self.prefill[0]
        bundle = lead.postmortem_bundle(reason, detail)
        bundle["mode"] = "disagg"
        bundle["handoff"] = dict(self.stats)
        bundle["roles"] = {
            f"{role}{i}": {"kv_pool": eng.cache.debug_state(),
                           "compile_counts": eng.compile_counts()}
            for i, (role, eng) in enumerate(self.engines())}
        if path is None:
            path = lead._postmortem_path(reason)
        return write_json_atomic(path, bundle)

    # ---------------- reference / ledger --------------------------------
    def generate_reference(self, prompts, max_new_tokens,
                           eos_token=None) -> List[List[int]]:
        """The no-cache greedy oracle (one engine's reference — they
        share the model's params)."""
        return self.prefill[0].generate_reference(
            prompts, max_new_tokens, eos_token=eos_token)

    def memory_ledger(self) -> dict:
        """Cluster-wide HBM accounting: BOTH roles' pools summed (the
        satellite contract — a disaggregated deployment's gauges must
        not undercount by reporting one role), with the per-role
        ledgers attached and the serve_hbm_bytes gauges emitted per
        (component, role) plus the cluster totals."""
        tel = self.telemetry
        roles = {}
        totals = {"params_bytes": 0.0, "kv_pool_bytes": 0.0,
                  "activation_est_bytes": 0.0, "adapter_bytes": 0.0,
                  "total_bytes": 0.0, "live_bytes": 0.0}
        for i, (role, eng) in enumerate(self.engines()):
            led = eng.memory_ledger()
            roles[f"{role}{i}"] = led
            for k in totals:
                totals[k] += float(led.get(k) or 0.0)
            if tel.enabled:
                for comp in ("params", "kv_pool", "activation_est",
                             "adapter", "total", "live"):
                    tel.metrics.set("serve_hbm_bytes",
                                    led[f"{comp}_bytes"],
                                    component=comp, role=f"{role}{i}")
        if tel.enabled:
            for k, v in totals.items():
                tel.metrics.set("serve_hbm_bytes", v,
                                component=k[:-len("_bytes")],
                                role="cluster")
        return {"mode": "disagg", "roles": roles, **totals}
