"""Cross-process PageShipment transport: length-prefixed socket frames.

The disaggregated handoff (serve/disagg.py) moves a
:class:`~flexflow_tpu.serve.disagg.PageShipment` — host-numpy page
rows + chain keys + stream/trace/tenant ids — between a prefill role
and a decode role. In-process that is a Python reference; this module
is the wire twin, giving the cluster a multi-host shape: the shipment
serializes to ONE length-prefixed frame, crosses a TCP socket, and the
RECEIVER enforces the existing backpressure-by-watermark semantics
before importing (a shipment the decode pool cannot hold above its
admission watermark is skipped at the receiving side, acked as such,
and the decode role re-prefills — identical degradation behavior to
the in-process `_admit_shipment` path).

Frame format (docs/serving.md "Wall-clock mode"):

    [4s magic b"FFPS"] [u8 version] [u64 body_len] [body] [u32 crc32]

where ``body`` is ``[u32 header_len][header JSON][array payload]``.
The header carries the shipment's scalar fields (chain keys hex-coded,
geometry stamp, stream/tenant/trace ids) plus per-array dtype NAMES
and shapes; the payload is the arrays' raw C-order bytes concatenated
in header order. Dtype names (``int8``, ``float8_e4m3fn``, ...)
round-trip through ``np.dtype(name)`` — quantized pools ship their
storage bytes bit-exactly, with their f32 scale rows alongside,
exactly as the in-process handoff does. The trailing CRC covers the
whole body: a truncated or corrupted frame raises
:class:`ShipmentWireError` instead of admitting garbage pages.

Every ack is a small JSON frame (``[4s b"FFPA"][u32 len][JSON]``)
carrying the receiver's verdict: ``accepted`` (watermark admission),
``pages_written`` (post-dedupe), and the error string when decoding
failed. The sender side is synchronous request/response — the handoff
call returns only after the receiver imported (or skipped) the pages,
which is what keeps the cluster's refcount/admission invariants
single-writer per engine even when the receiver lives in a thread or
another process.
"""

from __future__ import annotations

import json
import socket
import struct
import threading
import zlib
from typing import Callable, List, Optional

import numpy as np

from .disagg import PageShipment

__all__ = [
    "ShipmentWireError", "dumps_shipment", "loads_shipment",
    "ShipmentReceiver", "ShipmentSender",
]

MAGIC = b"FFPS"
ACK_MAGIC = b"FFPA"
WIRE_VERSION = 1
# magic + version + body_len
_HDR = struct.Struct(">4sBQ")
_CRC = struct.Struct(">I")
_LEN = struct.Struct(">I")

# a frame larger than this is a protocol error, not a shipment (64 GiB
# would be ~4M pages of a large pool — nothing legitimate gets there)
MAX_FRAME_BYTES = 64 << 30

_ARRAY_FIELDS = ("k_rows", "v_rows", "k_scale_rows", "v_scale_rows")


class ShipmentWireError(ValueError):
    """A frame failed to decode: truncated stream, bad magic/version,
    length out of range, CRC mismatch, or a header that does not
    describe its payload. The receiver drops the frame (and acks the
    error when the stream is still usable) — corrupt bytes never reach
    ``import_kv``."""


def _encode_array(a: Optional[np.ndarray]):
    if a is None:
        return None, b""
    a = np.ascontiguousarray(a)
    return {"dtype": a.dtype.name, "shape": list(a.shape)}, a.tobytes()


def _decode_array(desc, buf: bytes, offset: int):
    if desc is None:
        return None, offset
    try:
        dt = np.dtype(str(desc["dtype"]))
    except TypeError as e:
        raise ShipmentWireError(
            f"unknown array dtype {desc.get('dtype')!r}") from e
    shape = tuple(int(x) for x in desc["shape"])
    n = int(np.prod(shape, dtype=np.int64)) * dt.itemsize
    if offset + n > len(buf):
        raise ShipmentWireError(
            f"array payload truncated: need {n} bytes at offset "
            f"{offset}, frame body has {len(buf)}")
    # .copy(): frombuffer views the frame's read-only bytes; the
    # imported pages must own writable storage of their own
    a = np.frombuffer(buf, dtype=dt, count=int(np.prod(
        shape, dtype=np.int64)), offset=offset).reshape(shape).copy()
    return a, offset + n


def dumps_shipment(ship: PageShipment) -> bytes:
    """Serialize one shipment to a self-delimiting wire frame
    (bit-exact round trip: ``loads_shipment(dumps_shipment(s))``
    reproduces every array byte, chain key and id)."""
    header = {
        "keys": [k.hex() for k in ship.keys],
        "ntokens": int(ship.ntokens),
        "page_size": int(ship.page_size),
        "num_layers": int(ship.num_layers),
        "num_heads": int(ship.num_heads),
        "head_dim": int(ship.head_dim),
        "kv_dtype": str(ship.kv_dtype),
        "stream_id": ship.stream_id,
        "tenant_id": int(ship.tenant_id),
        "trace_id": ship.trace_id,
        "arrays": {},
    }
    payload_parts: List[bytes] = []
    for name in _ARRAY_FIELDS:
        desc, raw = _encode_array(getattr(ship, name))
        header["arrays"][name] = desc
        payload_parts.append(raw)
    hjson = json.dumps(header, separators=(",", ":")).encode("utf-8")
    body = _LEN.pack(len(hjson)) + hjson + b"".join(payload_parts)
    return (_HDR.pack(MAGIC, WIRE_VERSION, len(body)) + body
            + _CRC.pack(zlib.crc32(body) & 0xFFFFFFFF))


def loads_shipment(frame: bytes) -> PageShipment:
    """Decode one complete frame back into a :class:`PageShipment`.
    Raises :class:`ShipmentWireError` on ANY malformation — short
    frame, wrong magic/version, CRC mismatch, or arrays that don't fit
    the declared body."""
    if len(frame) < _HDR.size + _CRC.size:
        raise ShipmentWireError(
            f"frame too short ({len(frame)} bytes) for the "
            f"{_HDR.size + _CRC.size}-byte envelope")
    magic, version, body_len = _HDR.unpack_from(frame, 0)
    if magic != MAGIC:
        raise ShipmentWireError(f"bad magic {magic!r}")
    if version != WIRE_VERSION:
        raise ShipmentWireError(
            f"unsupported wire version {version} (speaks "
            f"{WIRE_VERSION})")
    if body_len > MAX_FRAME_BYTES:
        raise ShipmentWireError(f"frame body length {body_len} "
                                f"exceeds {MAX_FRAME_BYTES}")
    want = _HDR.size + body_len + _CRC.size
    if len(frame) != want:
        raise ShipmentWireError(
            f"frame is {len(frame)} bytes, envelope declares {want}")
    body = frame[_HDR.size:_HDR.size + body_len]
    (crc,) = _CRC.unpack_from(frame, _HDR.size + body_len)
    if zlib.crc32(body) & 0xFFFFFFFF != crc:
        raise ShipmentWireError("CRC mismatch: frame corrupted in "
                                "flight")
    if len(body) < _LEN.size:
        raise ShipmentWireError("body too short for header length")
    (hlen,) = _LEN.unpack_from(body, 0)
    if _LEN.size + hlen > len(body):
        raise ShipmentWireError(
            f"header length {hlen} overruns body ({len(body)} bytes)")
    try:
        header = json.loads(body[_LEN.size:_LEN.size + hlen]
                            .decode("utf-8"))
        keys = [bytes.fromhex(k) for k in header["keys"]]
        arrays_desc = header["arrays"]
    except (ValueError, KeyError, TypeError) as e:
        raise ShipmentWireError(f"undecodable header: {e}") from e
    offset = _LEN.size + hlen
    decoded = {}
    for name in _ARRAY_FIELDS:
        decoded[name], offset = _decode_array(
            arrays_desc.get(name), body, offset)
    if offset != len(body):
        raise ShipmentWireError(
            f"{len(body) - offset} trailing bytes after declared "
            f"arrays")
    if decoded["k_rows"] is None or decoded["v_rows"] is None:
        raise ShipmentWireError("shipment frame carries no page rows")
    sid = header.get("stream_id")
    tid = header.get("trace_id")
    return PageShipment(
        keys=keys, ntokens=int(header["ntokens"]),
        k_rows=decoded["k_rows"], v_rows=decoded["v_rows"],
        k_scale_rows=decoded["k_scale_rows"],
        v_scale_rows=decoded["v_scale_rows"],
        page_size=int(header["page_size"]),
        num_layers=int(header["num_layers"]),
        num_heads=int(header["num_heads"]),
        head_dim=int(header["head_dim"]),
        kv_dtype=str(header["kv_dtype"]),
        stream_id=None if sid is None else int(sid),
        tenant_id=int(header.get("tenant_id", 0)),
        trace_id=None if tid is None else int(tid))


# ---------------------------------------------------------------------------
# socket plumbing
# ---------------------------------------------------------------------------

def _recv_exact(sock: socket.socket, n: int) -> bytes:
    """Read exactly `n` bytes or raise ShipmentWireError (a peer that
    closes mid-frame is a truncated frame, not a silent partial)."""
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(min(1 << 20, n - got))
        if not chunk:
            raise ShipmentWireError(
                f"connection closed mid-frame ({got}/{n} bytes)")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def _recv_frame(sock: socket.socket) -> bytes:
    """Read one complete shipment frame off the stream."""
    head = _recv_exact(sock, _HDR.size)
    magic, version, body_len = _HDR.unpack(head)
    if magic != MAGIC:
        raise ShipmentWireError(f"bad magic {magic!r}")
    if version != WIRE_VERSION:
        raise ShipmentWireError(f"unsupported wire version {version}")
    if body_len > MAX_FRAME_BYTES:
        raise ShipmentWireError(f"frame body length {body_len} "
                                f"exceeds {MAX_FRAME_BYTES}")
    rest = _recv_exact(sock, body_len + _CRC.size)
    return head + rest


def _send_ack(sock: socket.socket, doc: dict) -> None:
    raw = json.dumps(doc, separators=(",", ":")).encode("utf-8")
    sock.sendall(ACK_MAGIC + _LEN.pack(len(raw)) + raw)


def _recv_ack(sock: socket.socket) -> dict:
    head = _recv_exact(sock, len(ACK_MAGIC) + _LEN.size)
    if head[:len(ACK_MAGIC)] != ACK_MAGIC:
        raise ShipmentWireError(f"bad ack magic {head[:4]!r}")
    (n,) = _LEN.unpack_from(head, len(ACK_MAGIC))
    if n > 1 << 20:
        raise ShipmentWireError(f"ack length {n} out of range")
    try:
        return json.loads(_recv_exact(sock, n).decode("utf-8"))
    except ValueError as e:
        raise ShipmentWireError(f"undecodable ack: {e}") from e


class ShipmentReceiver:
    """The decode-side endpoint: a listening TCP socket + acceptor
    thread. Each received frame decodes to a PageShipment and is
    handed to ``import_fn(ship) -> dict`` — the cluster's admission
    path, which applies the watermark check and returns the ack
    payload (``{"accepted": bool, "pages_written": int, ...}``). The
    import runs ON the receiver thread while the sender blocks for the
    ack, so the decode engine keeps one writer at a time.

    ``port=0`` binds an ephemeral port; read ``.port`` after
    construction (how tests and the in-process "tcp" cluster mode
    avoid port collisions)."""

    def __init__(self, import_fn: Callable[[PageShipment], dict], *,
                 host: str = "127.0.0.1", port: int = 0,
                 backlog: int = 8):
        self._import_fn = import_fn
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((str(host), int(port)))
        self._sock.listen(int(backlog))
        self.host, self.port = self._sock.getsockname()[:2]
        self._closed = False
        self.stats = {"frames": 0, "bytes": 0, "accepted": 0,
                      "skipped": 0, "wire_errors": 0}
        self._thread = threading.Thread(
            target=self._serve, name="shipment-receiver", daemon=True)
        self._thread.start()

    # ---------------- acceptor loop ------------------------------------
    def _serve(self) -> None:
        while not self._closed:
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                return  # closed
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 name="shipment-conn", daemon=True)
            t.start()

    def _serve_conn(self, conn: socket.socket) -> None:
        with conn:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            while not self._closed:
                try:
                    frame = _recv_frame(conn)
                except ShipmentWireError:
                    return  # stream unusable (peer gone / desynced)
                try:
                    ship = loads_shipment(frame)
                except ShipmentWireError as e:
                    self.stats["wire_errors"] += 1
                    try:
                        _send_ack(conn, {"accepted": False,
                                         "pages_written": 0,
                                         "error": str(e)})
                    except OSError:
                        return
                    continue
                self.stats["frames"] += 1
                self.stats["bytes"] += len(frame)
                try:
                    ack = dict(self._import_fn(ship))
                except Exception as e:  # import failure is an ack,
                    ack = {"accepted": False, "pages_written": 0,
                           "error": f"{type(e).__name__}: {e}"}
                ack.setdefault("accepted", False)
                ack.setdefault("pages_written", 0)
                self.stats["accepted" if ack["accepted"]
                           else "skipped"] += 1
                try:
                    _send_ack(conn, ack)
                except OSError:
                    return

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass
        self._thread.join(timeout=2.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class ShipmentSender:
    """The prefill-side endpoint: one TCP connection to a
    :class:`ShipmentReceiver`. ``send(ship)`` frames, ships, and
    blocks for the receiver's ack — the wire analogue of the
    in-process ``DisaggCluster._handoff`` call."""

    def __init__(self, host: str, port: int,
                 connect_timeout: float = 5.0):
        self._sock = socket.create_connection(
            (str(host), int(port)), timeout=connect_timeout)
        self._sock.settimeout(None)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.stats = {"frames": 0, "bytes": 0}

    def send(self, ship: PageShipment) -> dict:
        frame = dumps_shipment(ship)
        self._sock.sendall(frame)
        self.stats["frames"] += 1
        self.stats["bytes"] += len(frame)
        return _recv_ack(self._sock)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
